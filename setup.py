from setuptools import find_packages, setup

setup(
    name="fiber-tpu",
    version="0.1.0",
    description=(
        "TPU-native distributed computing framework: a multiprocessing-"
        "compatible API (Process/Pool/Queue/Pipe/Manager/Ring) whose "
        "backend is a Cloud TPU pod slice and whose device plane is "
        "JAX/XLA over ICI"
    ),
    packages=find_packages(include=["fiber_tpu", "fiber_tpu.*"]),
    python_requires=">=3.10",
    install_requires=[
        "cloudpickle",
        "psutil",
    ],
    extras_require={
        "device": ["jax"],
    },
    entry_points={
        "console_scripts": [
            "fiber-tpu=fiber_tpu.cli:main",
        ],
    },
)

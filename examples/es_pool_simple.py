"""Parallel OpenAI-ES over Pool.map — the host-path half of
docs/tutorials/01-parallel-es.md (reference: the GECCO-2020 tutorial's
ES loop, examples/gecco-2020/es.py — a fiber.Pool(40).map loop over a
numpy objective).

Finds a hidden 3-vector by fitness alone. Workers are idempotent (all
inputs ride in the task argument), so the resilient pool can resubmit
them safely on worker death.

Run:  python examples/es_pool_simple.py [--workers 8] [--iters 200]
      FIBER_BACKEND=tpu FIBER_TPU_HOSTS=sim:2 python examples/es_pool_simple.py
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse

import numpy as np

SOLUTION = np.array([5.0, -5.0, 1.5])


def fitness(theta):
    return -np.sum(np.square(theta - SOLUTION))


def worker(args):
    theta, sigma, seed = args
    rng = np.random.default_rng(seed)
    epsilon = rng.standard_normal(theta.shape[0])
    return fitness(theta + sigma * epsilon), epsilon


def es(theta0, pop, sigma, alpha, iterations, pool):
    theta = theta0
    for t in range(iterations):
        jobs = [(theta, sigma, t * pop + i) for i in range(pop)]
        returns = pool.map(worker, jobs)
        rewards = np.array([r for r, _ in returns])
        epsilons = np.stack([e for _, e in returns])
        normalized = (rewards - rewards.mean()) / (rewards.std() + 1e-8)
        theta = theta + alpha / (pop * sigma) * normalized @ epsilons
        if t % 20 == 0:
            print(f"iter {t:4d} fitness {fitness(theta):10.4f} theta {theta}")
    return theta


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=4,
                        help="pool processes (tasks fan out over these)")
    parser.add_argument("--pop", type=int, default=40,
                        help="candidates per iteration (the GECCO "
                             "tutorial used 40 = one per worker; they "
                             "need not match)")
    parser.add_argument("--iters", type=int, default=200)
    parser.add_argument("--sigma", type=float, default=0.1)
    parser.add_argument("--alpha", type=float, default=0.05)
    args = parser.parse_args()

    import fiber_tpu

    theta0 = np.random.default_rng(0).standard_normal(3)
    with fiber_tpu.Pool(args.workers) as pool:
        theta = es(theta0, args.pop, args.sigma, args.alpha,
                   args.iters, pool)
    err = float(np.linalg.norm(theta - SOLUTION))
    print(f"result {theta}  (|error| = {err:.3f})")
    return 0 if err < 0.5 else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Nested managed objects mutated from a worker — the reference's
manager semantics demo (reference: examples/shared_data.py): which
mutations through Namespace/list/dict proxies are visible to the
master, and which need an explicit assign-back because the inner
object is an unmanaged copy.

Run:  python examples/shared_data.py
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def mutate(ns, ls, di):
    ns.x += 1
    # ns.y is a plain list INSIDE the namespace: in-place mutation is
    # lost (the proxy returned a copy)...
    ns.y[0] += 1
    # ...unless the mutated copy is assigned back.
    z = ns.z
    z[0] += 1
    ns.z = z

    ls[0] += 1          # direct managed-list slot: visible
    ls[1][0] += 1       # nested plain list, not assigned back: lost
    inner = ls[2]
    inner[0] += 1
    ls[2] = inner       # assigned back: visible
    ls[3][0] += 1       # nested MANAGED list: direct mutation visible

    di["a"] += 1
    di["nested"][0] += 1        # plain nested, lost
    nested = di["copy"]
    nested[0] += 1
    di["copy"] = nested         # assigned back: visible
    di["managed"][0] += 1       # managed nested: visible


def main():
    import fiber_tpu

    with fiber_tpu.Manager() as manager:
        ns = manager.Namespace()
        ns.x = 0
        ns.y = [0]
        ns.z = [0]
        ls = manager.list([0, [0], [0], manager.list([0])])
        di = manager.dict({"a": 0, "nested": [0], "copy": [0],
                           "managed": manager.list([0])})

        p = fiber_tpu.Process(target=mutate, args=(ns, ls, di))
        p.start()
        p.join()
        assert p.exitcode == 0, p.exitcode

        print(f"ns.x   = {ns.x}  (direct attr: visible)")
        print(f"ns.y   = {ns.y}  (nested, no assign-back: LOST)")
        print(f"ns.z   = {ns.z}  (nested, assigned back: visible)")
        print(f"ls     = {list(ls)[:3]} + [{list(ls[3])}]")
        print(f"di     = a={di['a']} nested={di['nested']} "
              f"copy={di['copy']} managed={list(di['managed'])}")
        assert ns.x == 1 and ns.y == [0] and ns.z == [1]
        assert ls[0] == 1 and ls[1] == [0] and ls[2] == [1]
        assert list(ls[3]) == [1]
        assert di["a"] == 1 and di["nested"] == [0]
        assert di["copy"] == [1] and list(di["managed"]) == [1]
    print("shared data semantics demonstrated")


if __name__ == "__main__":
    main()

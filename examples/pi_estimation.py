"""Monte-Carlo pi estimation with Pool.map — the reference's hello-world
workload (reference: examples/pi_estimation.py) plus the on-device variant.

Run:  python examples/pi_estimation.py [--device]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import random
import sys
import time


def inside(n):
    count = 0
    for _ in range(n):
        x, y = random.random(), random.random()
        if x * x + y * y <= 1.0:
            count += 1
    return count


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--samples", type=int, default=200_000)
    parser.add_argument("--device", action="store_true",
                        help="run the jittable variant on the device mesh")
    args = parser.parse_args()

    import fiber_tpu

    if args.device:
        import jax
        import jax.numpy as jnp

        from fiber_tpu.meta import meta

        @meta(device=True)
        def inside_dev(seed):
            key = jax.random.PRNGKey(seed.astype("int32"))
            pts = jax.random.uniform(key, (args.samples, 2))
            return (jnp.sum(pts[:, 0] ** 2 + pts[:, 1] ** 2 <= 1.0)
                    .astype(jnp.float32))

        import numpy as np

        with fiber_tpu.Pool(args.workers) as pool:
            t0 = time.time()
            counts = pool.map(inside_dev, np.arange(args.workers * 4))
            elapsed = time.time() - t0
        total = float(sum(counts))
        n = args.samples * args.workers * 4
    else:
        chunks = [args.samples // args.workers] * args.workers
        with fiber_tpu.Pool(args.workers) as pool:
            t0 = time.time()
            counts = pool.map(inside, chunks)
            elapsed = time.time() - t0
        total = sum(counts)
        n = sum(chunks)

    print(f"pi ~= {4.0 * total / n:.6f}  ({n} samples, {elapsed:.2f}s)")


if __name__ == "__main__":
    sys.exit(main())

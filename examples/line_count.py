"""Count lines of every example, in parallel — the reference's
smallest Pool demo (reference: examples/line_count.py), unchanged in
spirit: Pool.map of a plain-Python function over a file list.

Run:  python examples/line_count.py
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


from pathlib import Path


def line_count(fname):
    with open(fname) as f:
        return len(f.readlines())


def main():
    import fiber_tpu

    here = Path(__file__).parent
    files = sorted(str(p) for p in here.glob("*.py"))
    with fiber_tpu.Pool(4) as pool:
        counts = pool.map(line_count, files)
    for f, c in zip(files, counts):
        print(f"{Path(f).name}\t{c}")
    print(f"{len(files)} files counted")


if __name__ == "__main__":
    main()

"""Train a tiny causal LM with the sequence axis sharded over the mesh.

The model's attention is exact ring attention
(``fiber_tpu.ops.ring_attention``): each device holds S/n_devices of
the sequence, K/V blocks rotate around the ICI ring with an online
softmax, and jax AD differentiates straight through it (gradient parity
with full-matrix attention is pinned in the test suite). Context length
therefore scales with device count — the long-context plane the
reference framework doesn't have.

The training task is the classic induction probe: the second half of
every sequence repeats the first half, so predicting it well requires
attending ~S/2 tokens back. Watch the half2 loss dive under the half1
(unpredictable) loss as the induction circuit forms.

Run:  python examples/long_context_lm.py [--seq 512] [--steps 300]
      [--attention ring|ulysses|flash]

``--attention flash`` trains through the Pallas flash-attention
kernels: on one device directly (whole sequence in HBM, scores
streamed through VMEM), and on a multi-device mesh as the RING's
per-device block — every rotation runs the kernel and the partial
(out, lse) pairs merge exactly, so context length still scales with
device count while the kernel does the math (`bench.py --lm` and
`--attention` A/B the paths on chip).
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq", type=int, default=256)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--batch", type=int, default=16)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--dim", type=int, default=128)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--attention", default="ring",
                        choices=("ring", "ulysses", "flash"))
    parser.add_argument("--kv-heads", type=int, default=None,
                        help="GQA: KV heads < heads (flash reads the "
                             "small KV natively; XLA planes broadcast)")
    parser.add_argument("--lr", type=float, default=1e-3)
    args = parser.parse_args()

    import jax

    n_dev_check = len(jax.devices())  # every plane shards now
    if args.seq % 2 or args.seq % n_dev_check:
        parser.error(
            f"--seq must be even (copy task halves) and divisible by "
            f"the {n_dev_check}-device mesh; got {args.seq}")
    import jax.numpy as jnp
    import optax

    from fiber_tpu.models import TinyLM, make_train_step
    from fiber_tpu.parallel import default_mesh

    # An explicit mesh makes every plane — flash included — shard the
    # sequence; with mesh=None flash stays single-device.
    mesh = default_mesh() if len(jax.devices()) > 1 else None
    model = TinyLM(vocab=args.vocab, dim=args.dim, heads=8,
                   layers=args.layers, max_seq=args.seq,
                   mesh=mesh, attention=args.attention,
                   kv_heads=args.kv_heads)
    params = model.init(jax.random.PRNGKey(0))
    opt = optax.adamw(args.lr, weight_decay=0.01)
    opt_state = opt.init(params)
    step = make_train_step(model, opt, batched=True)

    half = args.seq // 2

    def make_batch(key):
        h = jax.random.randint(key, (args.batch, half), 0, args.vocab)
        return jnp.concatenate([h, h], axis=1)

    @jax.jit
    def half_losses(params, tokens):
        def one(t):
            logits = model.apply(params, t)[:-1]
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, t[1:][:, None], axis=1)
            return nll[: half - 1].mean(), nll[half - 1:].mean()

        l1, l2 = jax.vmap(one)(tokens)
        return l1.mean(), l2.mean()

    key = jax.random.PRNGKey(1)
    n_dev = len(jax.devices())
    shard = f"{n_dev} devices ({args.seq // n_dev} tokens/device)"
    plane = (shard if args.attention != "flash"
             else "single device, kernels" if n_dev == 1
             else f"ring x flash kernels over {shard}")
    print(f"{args.attention} attention, seq {args.seq} over {plane}")
    for i in range(args.steps):
        key, k = jax.random.split(key)
        tokens = make_batch(k)
        params, opt_state, loss = step(params, opt_state, tokens)
        if i % max(1, args.steps // 10) == 0 or i == args.steps - 1:
            l1, l2 = half_losses(params, tokens)
            print(f"step {i:4d}  loss {float(loss):5.3f}  "
                  f"half1 {float(l1):5.3f} (random={jnp.log(args.vocab):.3f})  "
                  f"half2 {float(l2):5.3f} <- induction", flush=True)
    print("long-context training done")


if __name__ == "__main__":
    main()

"""Parzen-window density estimation with hyperparameter search over the
pool — the reference's second classic demo (reference:
examples/parzen_estimation.py): evaluate many window widths in parallel,
pick the best by cross-validated log-likelihood.

Run:  python examples/parzen_estimation.py [--device]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time

import numpy as np


def parzen_loglik(args):
    """Leave-one-out log-likelihood of a gaussian Parzen window."""
    h, data = args
    n = len(data)
    total = 0.0
    for i in range(n):
        diff = np.delete(data, i) - data[i]
        kernel = np.exp(-0.5 * (diff / h) ** 2) / (h * np.sqrt(2 * np.pi))
        total += np.log(kernel.mean() + 1e-12)
    return total / n


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--samples", type=int, default=400)
    parser.add_argument("--widths", type=int, default=24)
    parser.add_argument("--device", action="store_true")
    args = parser.parse_args()
    if args.widths < 1:
        parser.error("--widths must be >= 1")

    import fiber_tpu

    rng = np.random.default_rng(0)
    data = np.concatenate([
        rng.normal(-2.0, 0.6, args.samples // 2),
        rng.normal(1.5, 1.0, args.samples // 2),
    ]).astype(np.float32)
    widths = np.logspace(-2, 0.7, args.widths).astype(np.float32)

    if args.device:
        import jax
        import jax.numpy as jnp

        from fiber_tpu.meta import meta

        data_j = jnp.asarray(data)

        @meta(device=True)
        def loglik_dev(h):
            diff = data_j[None, :] - data_j[:, None]
            k = jnp.exp(-0.5 * (diff / h) ** 2) / (h * jnp.sqrt(2 * jnp.pi))
            # zero the self-kernel for leave-one-out
            k = k * (1 - jnp.eye(len(data_j)))
            dens = k.sum(axis=1) / (len(data_j) - 1)
            return jnp.mean(jnp.log(dens + 1e-12))

        with fiber_tpu.Pool(args.workers) as pool:
            t0 = time.time()
            scores = pool.map(loglik_dev, widths)
            elapsed = time.time() - t0
        scores = [float(s) for s in scores]
    else:
        with fiber_tpu.Pool(args.workers) as pool:
            t0 = time.time()
            scores = pool.map(
                parzen_loglik, [(float(h), data) for h in widths]
            )
            elapsed = time.time() - t0

    best = int(np.argmax(scores))
    print(f"evaluated {len(widths)} window widths in {elapsed:.2f}s")
    print(f"best h = {widths[best]:.4f}  (loglik {scores[best]:.4f})")


if __name__ == "__main__":
    sys.exit(main())

"""ES over a pool of workers evaluating a PURE-PYTHON simulator — the
reference's actual workflow, end to end.

The reference's gecco-2020 ES (its headline example) samples
perturbations centrally and farms evaluation through
``fiber.Pool(40).map`` of arbitrary Python — gym envs, C simulators,
anything unpicklable by XLA (/root/reference/examples/gecco-2020/es.py).
This example is that loop on fiber_tpu: ``AskTellES`` does the sampling
and update as jitted device programs, and a ``Pool`` (resilient,
error-handled) evaluates a hand-written pure-Python CartPole in worker
processes — no jax anywhere in the eval path.

When your eval IS jittable, use ``EvolutionStrategy`` instead and the
whole generation stays on the mesh (examples/es_cartpole.py).

Run:  python examples/es_pool_gym.py [--workers 4] [--pop 64] [--gens 10]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import math
import random


def simulate_cartpole(theta) -> float:
    """Pure-Python CartPole with a linear policy — stands in for a gym
    env: no jax, no numpy vectorization, just the kind of arbitrary
    host code the reference's pools were built to evaluate."""
    rng = random.Random(12345)
    x, v, a, w = [0.02 * (rng.random() - 0.5) for _ in range(4)]
    g, mc, mp_, lp, dt = 9.8, 1.0, 0.1, 0.5, 0.02
    steps = 0
    for _ in range(200):
        obs = (x, v, a, w)
        score = sum(t * o for t, o in zip(theta, obs))
        force = 10.0 if score > 0 else -10.0
        cosa, sina = math.cos(a), math.sin(a)
        tmp = (force + mp_ * lp * w * w * sina) / (mc + mp_)
        aacc = (g * sina - cosa * tmp) / (
            lp * (4.0 / 3.0 - mp_ * cosa * cosa / (mc + mp_)))
        xacc = tmp - mp_ * lp * aacc * cosa / (mc + mp_)
        x, v = x + dt * v, v + dt * xacc
        a, w = a + dt * w, w + dt * aacc
        steps += 1
        if abs(x) > 2.4 or abs(a) > 0.209:
            break
    return float(steps)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--pop", type=int, default=64)
    parser.add_argument("--gens", type=int, default=10)
    args = parser.parse_args()

    import jax

    import fiber_tpu
    from fiber_tpu.ops import AskTellES

    es = AskTellES(dim=4, pop_size=args.pop, sigma=0.5, lr=0.3)
    key = jax.random.PRNGKey(0)

    with fiber_tpu.Pool(args.workers) as pool:
        for gen in range(args.gens):
            key, k = jax.random.split(key)
            thetas = es.ask(k)
            fits = pool.map(simulate_cartpole,
                            [t.tolist() for t in thetas])
            stats = es.tell(fits)
            print(f"gen {gen}: mean {stats['mean_fitness']:6.1f}  "
                  f"max {stats['max_fitness']:6.1f}", flush=True)

    final = simulate_cartpole([float(t) for t in es.params])
    print(f"final policy survives {final:.0f}/200 steps")
    print("pool-evaluated ES done")


if __name__ == "__main__":
    main()

"""Novelty-search ES on the deceptive maze — the domain family these
algorithms were built for.

``DeceptiveMaze``: the goal is directly above the start, behind a wall;
the fitness gradient presses straight into the wall, and the only way
through is around either end — i.e. through states that score WORSE
first. Plain ES converges to the wall and stays there forever. The
NS-ES family (fiber_tpu.ops.NoveltyES) blends fitness ranks with
*behavior novelty* ranks (behavior = final position, scored against a
device-resident archive of everywhere the search has ended up before),
so the population is constantly pushed toward places it has not been —
including around the wall.

The reference framework powered exactly this research line at scale
(its examples hand-roll OpenAI-ES over fiber.Pool,
examples/gecco-2020/); here each variant's whole generation — rollouts,
k-NN novelty, rank blending, update, archive admission — is one SPMD
program on the mesh.

Deceptive domains are scored by the best candidate ever found (the
searcher's job is to FIND the goal; the center stalling at the wall is
the pathology being demonstrated).

Run:  python examples/novelty_maze.py [--pop 256] [--gens 30]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pop", type=int, default=256)
    parser.add_argument("--gens", type=int, default=30)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp

    from fiber_tpu.models import DeceptiveMaze, MLPPolicy
    from fiber_tpu.ops import EvolutionStrategy, NoveltyES

    policy = MLPPolicy(DeceptiveMaze.obs_dim, DeceptiveMaze.act_dim,
                       hidden=(16,))
    p0 = policy.init(jax.random.PRNGKey(0))

    def fitness_fn(theta, key):
        return DeceptiveMaze.rollout(policy.apply, theta, key)

    def eval_bc_fn(theta, key):
        pos = DeceptiveMaze.rollout_xy(policy.apply, theta, key)
        goal = jnp.asarray(DeceptiveMaze.GOAL)
        return -jnp.sqrt(jnp.sum((pos - goal) ** 2)), pos

    def best_ever(stepper, state, key, gens):
        best = -float("inf")
        for _ in range(gens):
            key, k = jax.random.split(key)
            state, stats = stepper(state, k)
            best = max(best, float(jax.device_get(stats)[1]))
        return best, state

    es = EvolutionStrategy(fitness_fn, dim=policy.dim,
                           pop_size=args.pop, sigma=0.1, lr=0.05)
    es_best, _ = best_ever(es.step, p0, jax.random.PRNGKey(1),
                           args.gens)

    results = [("plain ES", es_best, None)]
    for w, adaptive, label in [
        (0.0, False, "NS-ES   (pure novelty)"),
        (0.5, False, "NSR-ES  (half blend)"),
        (1.0, True, "NSRA-ES (adaptive)"),
    ]:
        nes = NoveltyES(eval_bc_fn, dim=policy.dim, bc_dim=2,
                        pop_size=args.pop, sigma=0.1, lr=0.05,
                        archive_size=128, k=10,
                        reward_weight=w, adaptive=adaptive,
                        weight_delta=0.1, patience=5)
        state = nes.init_state(p0, jax.random.PRNGKey(2))
        nbest, state = best_ever(nes.step, state, jax.random.PRNGKey(3),
                                 args.gens)
        results.append((label, nbest, float(state.w)))

    print("best-ever candidate fitness (0 = goal reached; the wall")
    print("pins plain ES at -1.0 — it never finds the way around):")
    for label, best, w in results:
        tail = "" if w is None else f"   [final reward weight {w:.2f}]"
        print(f"  {label:24s} {best:8.3f}{tail}")
    print("novelty search done")


if __name__ == "__main__":
    main()

"""The full pod topology, end-to-end: Ring rank processes launched as
CLUSTER JOBS through the tpu backend's host agents, joined into ONE
multi-process JAX mesh, running a fused EvolutionStrategy over it.

This is the composition the framework exists for (reference: ring ranks
as real cluster jobs — fiber/experimental/ring.py:103-129 over
kubernetes_backend.py:104-174 — which then hand off to
torch.distributed; here the hand-off is jax.distributed + lax
collectives). On a real pod slice each rank lands on a TPU-VM host and
the mesh rides ICI; with --sim the identical code runs on simulated
hosts and a virtual CPU mesh.

Run:  python examples/pod_es_ring.py --sim 2          # simulated hosts
      FIBER_BACKEND=tpu FIBER_TPU_HOSTS=h1,h2 python examples/pod_es_ring.py

To force the sim run onto a virtual CPU mesh (no accelerator), export
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4``
(and on machines with a PJRT tunnel plugin, clear its trigger env so
rank interpreters boot clean). Rank stdout lands in the per-job agent
logs — fetch with ``fiber-tpu logs <jid>``; rank 0's generation table
shows there.
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse


def train_rank(rank, size):
    """Runs identically on every rank AFTER jax.distributed joined them:
    one SPMD ES program over the global mesh."""
    import numpy as np

    import jax

    assert jax.process_count() == size
    from jax.sharding import Mesh

    from fiber_tpu.models import CartPole, MLPPolicy
    from fiber_tpu.ops import EvolutionStrategy

    mesh = Mesh(np.array(jax.devices()), ("pool",))
    policy = MLPPolicy(CartPole.obs_dim, CartPole.act_dim, hidden=(16,))

    def eval_fn(theta, key):
        return CartPole.rollout(policy.act, theta, key, max_steps=100)

    es = EvolutionStrategy(
        eval_fn, dim=policy.dim, pop_size=8 * len(jax.devices()),
        sigma=0.1, lr=0.03, mesh=mesh,
    )
    params = policy.init(jax.random.PRNGKey(0))
    params, stats = es.run_fused(params, jax.random.PRNGKey(1), 5)
    stats = jax.device_get(stats)
    if rank == 0:
        for g, (mean_f, max_f, _) in enumerate(stats):
            print(f"gen {g}: mean fitness {mean_f:8.2f}  max {max_f:8.2f}")
    jax.distributed.shutdown()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=2,
                        help="ring size (one rank per pod host)")
    parser.add_argument("--sim", type=int, default=0, metavar="N",
                        help="run against N simulated localhost agents")
    args = parser.parse_args()

    if args.sim:
        os_env = _os.environ
        os_env["FIBER_BACKEND"] = "tpu"
        os_env["FIBER_TPU_HOSTS"] = f"sim:{args.sim}"

    import fiber_tpu  # noqa: F401  (backend selected by env)
    from fiber_tpu.parallel.ring import Ring, jax_distributed_initializer

    ring = Ring(args.size, train_rank,
                initializer=jax_distributed_initializer)
    ring.run()
    print("all ranks joined cleanly")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Large-batch ES with a ConvNet policy on pixel observations — the
reference's "Atari ES" configuration shape (BASELINE.json), with a
procedural pixel env so the entire rollout (render → conv policy → move)
compiles into one XLA program. Convs are the MXU path: the policy forward
is where the FLOPs are.

Run:  python examples/es_conv_pixels.py [--pop 256] [--gens 20]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pop", type=int, default=256)
    parser.add_argument("--gens", type=int, default=20)
    parser.add_argument("--steps", type=int, default=40)
    args = parser.parse_args()

    import jax

    from fiber_tpu.models import ConvPolicy
    from fiber_tpu.models.envs import PixelChase
    from fiber_tpu.ops import EvolutionStrategy

    policy = ConvPolicy(PixelChase.obs_shape, PixelChase.act_dim,
                        channels=(8, 16), hidden=64)
    print(f"conv policy params: {policy.dim:,}")

    def eval_fn(theta, key):
        return PixelChase.rollout(policy.act, theta, key,
                                  max_steps=args.steps)

    es = EvolutionStrategy(eval_fn, dim=policy.dim, pop_size=args.pop,
                           sigma=0.05, lr=0.02)
    params = policy.init(jax.random.PRNGKey(0))

    t0 = time.time()
    params, history = es.run(params, jax.random.PRNGKey(1),
                             generations=args.gens,
                             log_every=max(1, args.gens // 5))
    elapsed = time.time() - t0
    for gen, mean, best in history:
        print(f"gen {gen:4d}  mean {mean:8.3f}  best {best:8.3f}")
    evals = es.pop_size * args.gens
    print(f"{evals} conv-policy evals in {elapsed:.1f}s "
          f"= {evals / elapsed:,.0f} evals/s")


if __name__ == "__main__":
    sys.exit(main())

"""POET on parameterized CartPole physics — env/agent co-evolution with
the whole data path on the device mesh (reference workload:
examples/gecco-2020 POET on BipedalWalker terrains over fiber.Pool).

Run:  python examples/poet_cartpole.py [--iters 10]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--pop", type=int, default=256)
    parser.add_argument("--pairs", type=int, default=6)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--env", default="cartpole",
                        choices=("cartpole", "hill", "biped"),
                        help="co-evolution domain (biped = the published "
                             "POET walker-on-obstacle-course shape)")
    parser.add_argument("--mc-low", type=float, default=None,
                        help="minimal-criterion floor for admitting new "
                             "envs (units = the domain's fitness: "
                             "survival steps for cartpole, metres for "
                             "the walkers; default 10.0, walkers 0.5)")
    parser.add_argument("--mc-high", type=float, default=None,
                        help="minimal-criterion ceiling (reject envs the "
                             "incumbent already solves this well); "
                             "cartpole defaults to 0.9*steps, walkers "
                             "to a distance matched to their speed "
                             "scale")
    args = parser.parse_args()
    # Walker fitness is metres, not survival steps: both minimal-
    # criterion bounds need distance-scale defaults or the 'not
    # trivially easy' half never engages.
    if args.mc_low is None:
        args.mc_low = 10.0 if args.env == "cartpole" else 0.5
    if args.mc_high is None and args.env != "cartpole":
        # ~90% of a good walker's reachable distance (hill walkers move
        # ~3x faster than the biped's ~2 m/s at dt=0.05 vs 0.025)
        per_step = 0.15 if args.env == "hill" else 0.045
        args.mc_high = per_step * args.steps

    import jax

    from fiber_tpu.models import MLPPolicy
    from fiber_tpu.models.envs import (
        ParamBipedWalker,
        ParamCartPole,
        ParamHillWalker,
    )
    from fiber_tpu.ops.poet import POET

    env_cls = {"cartpole": ParamCartPole, "hill": ParamHillWalker,
               "biped": ParamBipedWalker}[args.env]
    policy = MLPPolicy(env_cls.obs_dim, env_cls.act_dim,
                       hidden=(16,))
    poet = POET(
        env_cls, policy,
        pop_size=args.pop, max_pairs=args.pairs,
        rollout_steps=args.steps, mc_low=args.mc_low,
        mc_high=args.mc_high,
    )
    t0 = time.time()
    history = poet.run(jax.random.PRNGKey(0), args.iters, es_steps=4,
                       log=print)
    elapsed = time.time() - t0
    final = history[-1]
    total_evals = sum(
        h["pairs"] * poet.pop_size * 4 for h in history
    )
    print(
        f"\n{final['pairs']} co-evolved (env, agent) pairs; final mean "
        f"fitness {final['mean_fitness']:.1f}/{args.steps}; "
        f"~{total_evals:,} policy evals in {elapsed:.1f}s "
        f"({total_evals / elapsed:,.0f} evals/s)"
    )


if __name__ == "__main__":
    sys.exit(main())

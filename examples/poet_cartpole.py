"""POET on parameterized CartPole physics — env/agent co-evolution with
the whole data path on the device mesh (reference workload:
examples/gecco-2020 POET on BipedalWalker terrains over fiber.Pool).

Run:  python examples/poet_cartpole.py [--iters 10]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--pop", type=int, default=256)
    parser.add_argument("--pairs", type=int, default=6)
    parser.add_argument("--steps", type=int, default=200)
    args = parser.parse_args()

    import jax

    from fiber_tpu.models import MLPPolicy
    from fiber_tpu.models.envs import ParamCartPole
    from fiber_tpu.ops.poet import POET

    policy = MLPPolicy(ParamCartPole.obs_dim, ParamCartPole.act_dim,
                       hidden=(16,))
    poet = POET(
        ParamCartPole, policy,
        pop_size=args.pop, max_pairs=args.pairs,
        rollout_steps=args.steps,
    )
    t0 = time.time()
    history = poet.run(jax.random.PRNGKey(0), args.iters, es_steps=4,
                       log=print)
    elapsed = time.time() - t0
    final = history[-1]
    total_evals = sum(
        h["pairs"] * poet.pop_size * 4 for h in history
    )
    print(
        f"\n{final['pairs']} co-evolved (env, agent) pairs; final mean "
        f"fitness {final['mean_fitness']:.1f}/{args.steps}; "
        f"~{total_evals:,} policy evals in {elapsed:.1f}s "
        f"({total_evals / elapsed:,.0f} evals/s)"
    )


if __name__ == "__main__":
    sys.exit(main())

"""Queue micro-benchmark: msgs/sec + effective Mbps through a SimpleQueue
between two processes (reference: examples/bench_queue.py).

Run:  python examples/bench_queue.py [--msgs 20000] [--size 1024]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def echo_worker(q_in, q_out, n):
    for _ in range(n):
        q_out.put(q_in.get())


def drain_worker(q_in, q_done, n):
    """One-way consumer: drain n messages, then report completion."""
    for _ in range(n):
        q_in.get()
    q_done.put("done")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--msgs", type=int, default=20_000)
    parser.add_argument("--size", type=int, default=1024)
    parser.add_argument("--prefetch", type=int, default=1,
                        help="consumer credit window (1 = pure "
                             "demand-driven; N pipelines N messages)")
    parser.add_argument("--stream", action="store_true",
                        help="one-way streaming throughput instead of "
                             "round-trips (round-trips measure latency; "
                             "this measures the pipe's actual rate)")
    args = parser.parse_args()

    import fiber_tpu

    if args.stream:
        q_in = fiber_tpu.SimpleQueue(prefetch=args.prefetch)
        q_done = fiber_tpu.SimpleQueue()
        p = fiber_tpu.Process(target=drain_worker,
                              args=(q_in, q_done, args.msgs))
        p.start()
        payload = b"x" * args.size
        t0 = time.time()
        for _ in range(args.msgs):
            q_in.put(payload)
        assert q_done.get(60) == "done"
        elapsed = time.time() - t0
        p.join(30)
        rate = args.msgs / elapsed
        mbps = rate * args.size * 8 / 1e6
        print(f"{args.msgs} one-way msgs of {args.size}B in "
              f"{elapsed:.2f}s: {rate:,.0f} msgs/s, {mbps:,.1f} Mbps")
        q_in.close()
        q_done.close()
        return 0

    q_in, q_out = fiber_tpu.SimpleQueue(), fiber_tpu.SimpleQueue()
    p = fiber_tpu.Process(target=echo_worker,
                          args=(q_in, q_out, args.msgs))
    p.start()

    payload = b"x" * args.size
    t0 = time.time()
    inflight = 0
    sent = received = 0
    while received < args.msgs:
        while sent < args.msgs and inflight < 512:
            q_in.put(payload)
            sent += 1
            inflight += 1
        q_out.get()
        received += 1
        inflight -= 1
    elapsed = time.time() - t0
    p.join(30)

    rate = args.msgs / elapsed
    mbps = rate * args.size * 8 / 1e6
    print(f"{args.msgs} round-trips of {args.size}B in {elapsed:.2f}s: "
          f"{rate:,.0f} msgs/s, {mbps:,.1f} Mbps effective")
    q_in.close()
    q_out.close()


if __name__ == "__main__":
    sys.exit(main())

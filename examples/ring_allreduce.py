"""Data-parallel SGD over a Ring — the reference's examples/ring.py without
torch/gloo: gradients are averaged with fiber_tpu's own host ring
allreduce (and lower to ``lax.psum`` on a pod slice via
``jax_distributed_initializer``).

Run:  python examples/ring_allreduce.py [--size 2]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys


def sgd_rank(rank, size):
    import numpy as np

    from fiber_tpu.parallel.ring import current_ring

    ring = current_ring()
    rng = np.random.default_rng(rank)
    # toy least squares: y = Xw*, each rank holds a shard of the data
    true_w = np.arange(8, dtype=np.float32)
    X = rng.normal(size=(256, 8)).astype(np.float32)
    y = X @ true_w
    w = np.zeros(8, dtype=np.float32)
    for step in range(60):
        grad = 2.0 * X.T @ (X @ w - y) / len(X)
        grad = ring.allreduce(grad, op="mean")   # <- the collective
        w -= 0.05 * grad
    err = float(np.linalg.norm(w - true_w))
    print(f"rank {rank}/{size}: ||w - w*|| = {err:.4f}")
    assert err < 0.05, err
    ring.close()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=2)
    args = parser.parse_args()

    from fiber_tpu.parallel import Ring

    Ring(args.size, sgd_rank).run()
    print("all ranks converged")


if __name__ == "__main__":
    sys.exit(main())

"""The smallest possible tour: Process, Queue, Pipe, Manager
(reference: examples/basic_process.py, basic_queue.py, shared_data.py).

Run:  python examples/basics.py
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import sys


def greeter(name, q):
    q.put(f"hello {name} from a fiber_tpu process")


def doubler(conn):
    while True:
        item = conn.recv()
        if item is None:
            return
        conn.send(item * 2)


def main():
    import fiber_tpu

    # Process + SimpleQueue
    q = fiber_tpu.SimpleQueue()
    p = fiber_tpu.Process(target=greeter, args=("world", q))
    p.start()
    print(q.get(30))
    p.join(30)

    # Pipe
    here, there = fiber_tpu.Pipe()
    p = fiber_tpu.Process(target=doubler, args=(there,))
    p.start()
    here.send(21)
    print("21 doubled remotely ->", here.recv(30))
    here.send(None)
    p.join(30)

    # Manager shared state
    manager = fiber_tpu.Manager()
    shopping = manager.list(["eggs"])
    shopping.append("spam")
    print("shared list ->", list(shopping))
    manager.shutdown()


if __name__ == "__main__":
    sys.exit(main())

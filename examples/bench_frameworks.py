"""Framework-overhead benchmark: Pool throughput on fixed-duration tasks
versus stdlib multiprocessing (reference: examples/bench_frameworks.py —
the headline comparison in the reference docs: near-parity with
multiprocessing at 1 ms / 10 ms / 100 ms task durations).

Run:  python examples/bench_frameworks.py [--workers 5]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def timed_task(duration):
    time.sleep(duration)
    return duration


def bench_pool(make_pool, n_tasks, duration, workers):
    with make_pool(workers) as pool:
        # warmup: make sure all workers are up so steady-state throughput
        # is measured (mp's map implicitly waits for its eager workers)
        pool.map(timed_task, [0.0] * workers)
        if hasattr(pool, "wait_workers"):
            pool.wait_workers(timeout=60)
        t0 = time.time()
        pool.map(timed_task, [duration] * n_tasks)
        return time.time() - t0


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--workers", type=int, default=5)
    args = parser.parse_args()

    import multiprocessing

    import fiber_tpu

    print(f"{'duration':>10} {'tasks':>7} {'ideal':>8} "
          f"{'fiber_tpu':>10} {'mp':>8} {'overhead_vs_mp':>14}")
    for duration, n_tasks in ((0.1, 50), (0.01, 500), (0.001, 1000)):
        ideal = duration * n_tasks / args.workers
        fib = bench_pool(
            lambda w: fiber_tpu.Pool(w), n_tasks, duration, args.workers
        )
        mp = bench_pool(
            lambda w: multiprocessing.get_context("spawn").Pool(w),
            n_tasks, duration, args.workers,
        )
        print(f"{duration * 1000:>8.0f}ms {n_tasks:>7} {ideal:>7.2f}s "
              f"{fib:>9.2f}s {mp:>7.2f}s {fib / mp:>13.2f}x")


if __name__ == "__main__":
    sys.exit(main())

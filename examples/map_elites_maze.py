"""MAP-Elites illuminating the deceptive maze.

Quality-diversity's answer to deception: instead of fighting the
misleading fitness gradient (the novelty-search story,
examples/novelty_maze.py), MAP-Elites grids the behavior space (final
positions) and keeps the best policy for every cell it ever reaches.
Coverage spreads outward cell by cell — around the wall as a side
effect — and "solve the maze" falls out as the elite of the goal's
cell. The whole loop (parent selection, perturbation, evaluation,
segment-max insertion) is one jitted SPMD step on the mesh.

Run:  python examples/map_elites_maze.py [--gens 60] [--batch 256]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gens", type=int, default=60)
    parser.add_argument("--batch", type=int, default=256)
    parser.add_argument("--cells", type=int, default=12)
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fiber_tpu.models import DeceptiveMaze, MLPPolicy
    from fiber_tpu.ops import MAPElites

    policy = MLPPolicy(DeceptiveMaze.obs_dim, DeceptiveMaze.act_dim,
                       hidden=(16,))
    goal = jnp.asarray(DeceptiveMaze.GOAL)

    def eval_fn(theta, key):
        pos = DeceptiveMaze.rollout_xy(policy.apply, theta, key)
        return -jnp.sqrt(jnp.sum((pos - goal) ** 2)), pos

    me = MAPElites(eval_fn, dim=policy.dim, bc_dim=2,
                   bc_low=(-4.0, -4.0), bc_high=(4.0, 4.0),
                   cells_per_dim=args.cells, batch_size=args.batch,
                   sigma=0.2)
    state = me.init_state(policy.init(jax.random.PRNGKey(0)),
                          jax.random.PRNGKey(1))

    key = jax.random.PRNGKey(2)
    for gen in range(args.gens):
        key, k = jax.random.split(key)
        state, stats = me.step(state, k)
        if gen % max(1, args.gens // 6) == 0 or gen == args.gens - 1:
            qd, cov, best = (float(stats[0]), float(stats[1]),
                             float(stats[2]))
            print(f"gen {gen:3d}  coverage {cov:5.1%}  "
                  f"best fitness {best:6.3f}  qd {qd:8.1f}", flush=True)

    # The maze is "solved" if some cell's elite ends within ~0.5 of
    # the goal (fitness > -0.5) — past the wall.
    best_fit = float(jax.device_get(state.fitness.max()))
    beyond = np.asarray(jax.device_get(
        (state.behaviors[:, 1] > 1.0)
        & jnp.isfinite(state.fitness))).sum()
    print(f"cells illuminated beyond the wall (y > 1): {int(beyond)}")
    print(f"best elite fitness: {best_fit:.3f} "
          f"({'maze solved' if best_fit > -0.5 else 'not solved yet'})")
    print("map-elites done")


if __name__ == "__main__":
    main()

"""OpenAI-ES on CartPole, fully on-device — the north-star workload
(reference: examples/gecco-2020/es.py is a fiber.Pool(40).map loop; here
the whole generation is one SPMD step on the mesh).

Run:  python examples/es_cartpole.py [--pop 1024] [--gens 50]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pop", type=int, default=1024)
    parser.add_argument("--gens", type=int, default=50)
    parser.add_argument("--steps", type=int, default=500)
    parser.add_argument("--hidden", type=int, default=32)
    parser.add_argument("--optimizer", default="sgd",
                        choices=("sgd", "adam"))
    parser.add_argument("--algo", default="es",
                        choices=("es", "pgpe", "cma", "fullcma"),
                        help="algorithm family: OpenAI-ES (default), "
                             "PGPE, sep-CMA-ES, or full-covariance "
                             "CMA-ES")
    parser.add_argument("--fused", action="store_true",
                        help="run generations as fused lax.scan chunks")
    args = parser.parse_args()

    import jax

    from fiber_tpu.models import CartPole, MLPPolicy
    from fiber_tpu.ops import EvolutionStrategy

    policy = MLPPolicy(CartPole.obs_dim, CartPole.act_dim,
                       hidden=(args.hidden, args.hidden))

    def eval_fn(theta, key):
        return CartPole.rollout(policy.act, theta, key,
                                max_steps=args.steps)

    if args.algo != "es":
        if args.optimizer != "sgd":
            parser.error("--optimizer applies only to --algo es")
        from fiber_tpu.ops import CMAES, PGPE, SepCMAES

        cls = {"pgpe": PGPE, "cma": SepCMAES,
               "fullcma": CMAES}[args.algo]
        opt = cls(eval_fn, dim=policy.dim, pop_size=args.pop)
        state = opt.init_state(policy.init(jax.random.PRNGKey(0)))
        t0 = time.time()
        if args.fused:
            # One XLA program for all generations (the shared fused
            # runner every state-tuple family now carries).
            state, stats_seq = opt.run_fused(
                state, jax.random.PRNGKey(1), args.gens)
            hist = list(jax.device_get(stats_seq))
        else:
            state, hist = opt.run(state, jax.random.PRNGKey(1),
                                  args.gens)
        jax.block_until_ready(state[0])
        elapsed = time.time() - t0
        every = max(1, args.gens // 10)
        for g, stats in enumerate(hist):
            if g % every == 0 or g == args.gens - 1:
                s = jax.device_get(stats)
                print(f"gen {g:4d}  mean {float(s[0]):8.2f}  "
                      f"best {float(s[1]):8.2f}")
        evals = opt.pop_size * args.gens
        print(f"{evals} policy evals in {elapsed:.1f}s "
              f"= {evals / elapsed:,.0f} evals/s [{args.algo}]")
        return 0

    es = EvolutionStrategy(eval_fn, dim=policy.dim, pop_size=args.pop,
                           sigma=0.1, lr=0.03, optimizer=args.optimizer)
    params = policy.init(jax.random.PRNGKey(0))
    key = jax.random.PRNGKey(1)

    t0 = time.time()
    if args.fused:
        # Chunks of generations compile into single XLA programs; one
        # log line per chunk.
        chunk = max(1, args.gens // 10)
        history = []
        done = 0
        while done < args.gens:
            n = min(chunk, args.gens - done)
            key, k = jax.random.split(key)
            params, stats_seq = es.run_fused(params, k, n)
            last = jax.device_get(stats_seq)[-1]
            done += n
            history.append((done - 1, float(last[0]), float(last[1])))
    else:
        params, history = es.run(params, key, generations=args.gens,
                                 log_every=max(1, args.gens // 10))
    elapsed = time.time() - t0

    for gen, mean, best in history:
        print(f"gen {gen:4d}  mean {mean:8.2f}  best {best:8.2f}")
    evals = es.pop_size * args.gens
    print(f"{evals} policy evals in {elapsed:.1f}s "
          f"= {evals / elapsed:,.0f} evals/s "
          f"({evals * args.steps / elapsed:,.0f} env-steps/s)")


if __name__ == "__main__":
    sys.exit(main())

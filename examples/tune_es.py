"""Sweep ES population sizes on the attached accelerator and report the
best operating point (evals/sec rises with population until the chip
saturates; the north-star metric rewards raw eval throughput).

Run:  python examples/tune_es.py [--pops 2048,4096,8192,16384]
      [--steps 500] [--gens 5] [--json OUT.json]

Used by the round harness to pick bench.py's --pop on real hardware.
"""

import argparse
import json
import os as _os
import sys as _sys
import time

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pops", default="2048,4096,8192,16384")
    parser.add_argument("--steps", type=int, default=500)
    parser.add_argument("--gens", type=int, default=5)
    parser.add_argument("--platform", default="")
    parser.add_argument("--json", default="")
    args = parser.parse_args()
    if args.platform:
        _os.environ["JAX_PLATFORMS"] = args.platform
        if args.platform == "cpu":
            _os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    import jax

    if args.platform:
        # sitecustomize may already have imported jax in this
        # interpreter; the env var alone is too late.
        jax.config.update("jax_platforms", args.platform)
    import numpy as np
    from jax.sharding import Mesh

    from fiber_tpu.models import CartPole, MLPPolicy
    from fiber_tpu.ops import EvolutionStrategy

    devices = jax.devices()
    mesh = Mesh(np.asarray(devices), ("pool",))
    policy = MLPPolicy(CartPole.obs_dim, CartPole.act_dim, hidden=(32, 32))

    def eval_fn(theta, key):
        return CartPole.rollout(policy.act, theta, key,
                                max_steps=args.steps)

    rows = []
    for pop in (int(p) for p in args.pops.split(",")):
        es = EvolutionStrategy(eval_fn, dim=policy.dim, pop_size=pop,
                               sigma=0.1, lr=0.03, mesh=mesh)
        params = policy.init(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(1)
        t0 = time.perf_counter()
        params, stats = es.run_fused(params, key, args.gens)
        jax.block_until_ready(stats)
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        params, stats = es.run_fused(params, jax.random.PRNGKey(2),
                                     args.gens)
        jax.block_until_ready(stats)
        dt = time.perf_counter() - t0
        evals_s = es.pop_size * args.gens / dt
        rows.append({
            "pop": es.pop_size,
            "evals_per_sec": round(evals_s, 1),
            "env_steps_per_sec": round(evals_s * args.steps, 1),
            "steady_s": round(dt, 3),
            "compile_s": round(compile_s, 1),
        })
        print(f"pop={es.pop_size:6d}  {evals_s:10.1f} evals/s  "
              f"(steady {dt:.3f}s, compile {compile_s:.1f}s)", flush=True)

    best = max(rows, key=lambda r: r["evals_per_sec"])
    out = {
        "platform": devices[0].platform,
        "n_devices": len(devices),
        "episode_steps": args.steps,
        "generations": args.gens,
        "rows": rows,
        "best_pop": best["pop"],
        "best_evals_per_sec": best["evals_per_sec"],
    }
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as fh:
            json.dump(out, fh, indent=1)
    return 0


if __name__ == "__main__":
    _sys.exit(main())

"""Async manager demo: N slow environment servers stepped in parallel
(reference: examples/async_manager.py — the docs report 3.72s sync vs
1.68s async for 4 envs).

Run:  python examples/async_manager.py [--envs 4]
"""

import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))


import argparse
import sys
import time


class SlowEnv:
    """Stands in for a CartPole env whose step costs ~50 ms."""

    def __init__(self):
        self.t = 0

    def step(self, action):
        time.sleep(0.05)
        self.t += 1
        return self.t, float(action) * 0.1


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--envs", type=int, default=4)
    parser.add_argument("--steps", type=int, default=20)
    args = parser.parse_args()

    from fiber_tpu.managers import (
        AsyncBaseProxy,
        AsyncManager,
        MakeProxyType,
        SyncManager,
    )

    SyncManager.register("SlowEnv", SlowEnv,
                         MakeProxyType("SlowEnvProxy", ("step",)))
    AsyncManager.register(
        "SlowEnv", SlowEnv,
        MakeProxyType("AsyncSlowEnvProxy", ("step",), base=AsyncBaseProxy),
    )

    sync = SyncManager()
    sync.start()
    envs = [sync.SlowEnv() for _ in range(args.envs)]
    t0 = time.time()
    for _ in range(args.steps):
        for env in envs:
            env.step(1)
    sync_s = time.time() - t0
    sync.shutdown()

    amgr = AsyncManager()
    amgr.start()
    envs = [amgr.SlowEnv() for _ in range(args.envs)]
    t0 = time.time()
    for _ in range(args.steps):
        futures = [env.step(1) for env in envs]
        for fut in futures:
            fut.get(30)
    async_s = time.time() - t0
    amgr.shutdown()

    print(f"{args.envs} envs x {args.steps} steps: "
          f"sync {sync_s:.2f}s vs async {async_s:.2f}s "
          f"({sync_s / async_s:.2f}x speedup)")


if __name__ == "__main__":
    sys.exit(main())

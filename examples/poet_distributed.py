"""POET with its per-pair ES inner loops farmed over a fiber_tpu Pool —
the reference's gecco-2020 architecture (a 46-line ES loop over
fiber.Pool(40).map on BipedalWalker terrains) rebuilt on this framework:
the master owns the POET state machine (mutation, minimal criterion,
novelty archive, transfer) while each worker process runs a compiled
device-plane EvolutionStrategy for its assigned (env, agent) pair.

This composes the two planes: host-plane fault-tolerant task parallelism
(ResilientPool — a dead worker's pair is resubmitted automatically) and
device-plane SPMD evaluation inside every worker. On a pod you'd point
FIBER_BACKEND=tpu / FIBER_TPU_HOSTS at the slice and each host optimizes
pairs on its own chips; locally the workers share the CPU mesh.

Run:  python examples/poet_distributed.py [--iters 5] [--workers 2]
"""

import argparse
import os as _os
import sys as _sys

_sys.path.insert(
    0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import sys
import time

# Per-process caches: one compiled ES (and policy) per worker process,
# shared across every pair and iteration that process serves.
_WORKER_ES = {}


def es_worker(payload):
    """Run ``es_steps`` ES generations for one (env, agent) pair.

    ``payload`` is plain picklable data: (theta, env_params, seed,
    conf) with conf = (hidden, pop, rollout_steps, es_steps, sigma, lr).
    Returns (new_theta ndarray, fitness float).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fiber_tpu.models import MLPPolicy
    from fiber_tpu.models.envs import ParamCartPole
    from fiber_tpu.ops import EvolutionStrategy

    theta, env_params, seed, conf = payload
    hidden, pop, rollout_steps, es_steps, sigma, lr = conf

    es_entry = _WORKER_ES.get(conf)
    if es_entry is None:
        policy = MLPPolicy(ParamCartPole.obs_dim, ParamCartPole.act_dim,
                           hidden=hidden)
        env_dim = len(ParamCartPole.DEFAULT)

        def eval_fn(theta_and_env, key):
            th = theta_and_env[: policy.dim]
            ep = theta_and_env[policy.dim:]
            return ParamCartPole.rollout_p(
                policy.act, ep, th, key, max_steps=rollout_steps
            )

        es = EvolutionStrategy(
            eval_fn, dim=policy.dim + env_dim, pop_size=pop,
            sigma=sigma, lr=lr,
        )
        es_entry = (es, policy)
        _WORKER_ES[conf] = es_entry
    es, policy = es_entry

    combined = jnp.concatenate(
        [jnp.asarray(theta), jnp.asarray(env_params)]
    )
    key = jax.random.PRNGKey(seed)
    stats = None
    for _ in range(es_steps):
        key, sub = jax.random.split(key)
        combined, stats = es.step(combined, sub)
        # The env tail is part of the ES vector for compile sharing but
        # must not drift — the pair's environment is fixed.
        combined = combined.at[policy.dim:].set(jnp.asarray(env_params))
    fitness = float(jax.device_get(stats)[0])
    return np.asarray(combined[: policy.dim]), fitness


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--workers", type=int, default=2)
    parser.add_argument("--pop", type=int, default=256)
    parser.add_argument("--pairs", type=int, default=4)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--es-steps", type=int, default=3)
    args = parser.parse_args()

    import jax
    import numpy as np

    import fiber_tpu
    from fiber_tpu.models import MLPPolicy
    from fiber_tpu.models.envs import ParamCartPole
    from fiber_tpu.ops.poet import POET

    hidden = (16,)
    policy = MLPPolicy(ParamCartPole.obs_dim, ParamCartPole.act_dim,
                       hidden=hidden)
    poet = POET(ParamCartPole, policy, pop_size=args.pop,
                max_pairs=args.pairs, rollout_steps=args.steps)
    conf = (hidden, args.pop, args.steps, args.es_steps, poet.sigma,
            poet.lr)

    key = jax.random.PRNGKey(0)
    t0 = time.time()
    total_evals = 0
    with fiber_tpu.Pool(args.workers) as pool:
        for it in range(args.iters):
            # 1. Optimize every active pair IN PARALLEL across the pool
            #    (the reference farms exactly this loop over its Pool).
            key, sub = jax.random.split(key)
            seeds = np.random.default_rng(
                int(jax.device_get(jax.random.randint(
                    sub, (), 0, 2**31 - 1)))
            ).integers(0, 2**31 - 1, size=len(poet.envs))
            payloads = [
                (np.asarray(poet.agents[i]), np.asarray(poet.envs[i]),
                 int(seeds[i]), conf)
                for i in range(len(poet.envs))
            ]
            results = pool.map(es_worker, payloads, chunksize=1)
            for i, (theta, fitness) in enumerate(results):
                poet.agents[i] = jax.numpy.asarray(theta)
            total_evals += len(payloads) * args.pop * args.es_steps
            fits = [round(f, 1) for _, f in results]

            # 2./3. Transfer + env mutation stay on the master (tiny).
            key, k_t, k_s = jax.random.split(key, 3)
            transfers = poet.transfer(k_t)
            total_evals += poet.last_transfer_evals
            spawned = poet.try_spawn_envs(k_s)
            print(f"iter {it}: pairs={len(poet.envs)} fitness={fits} "
                  f"transfers={transfers} spawned={spawned}", flush=True)

    elapsed = time.time() - t0
    print(f"\n{len(poet.envs)} pairs co-evolved; ~{total_evals:,} policy "
          f"evals in {elapsed:.1f}s ({total_evals / elapsed:,.0f} evals/s) "
          f"across {args.workers} pool workers")
    return 0


if __name__ == "__main__":
    sys.exit(main())

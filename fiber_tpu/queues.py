"""Distributed Pipes and Queues over the host-plane transport.

Reference parity: fiber/queues.py. The key property (ZConnection
semantics, queues.py:86-249 in the reference): connection objects are
**picklable** — they serialize to (mode, address) and lazily re-dial the
device after deserialization in another process, so queues/pipes can be
passed freely as Process args, through other queues, or into plain
multiprocessing children.

Every queue/pipe is anchored by a ``Device`` forwarder in the creating
process, giving both ends a stable address to dial (reference:
fiber/queues.py:15-23 design note).
"""

from __future__ import annotations

import queue as pyqueue
import threading
from typing import Any, Optional, Tuple

from fiber_tpu import serialization
from fiber_tpu.transport import Device, Endpoint, TransportClosed
from fiber_tpu.utils.logging import get_logger

logger = get_logger()


def _listen_ip() -> str:
    from fiber_tpu.backends import get_backend

    ip, _, _ = get_backend().get_listen_addr()
    return ip


class Connection:
    """A picklable, lazily-connecting message connection.

    API mirrors ``multiprocessing.connection.Connection``: send/recv
    (pickled objects), send_bytes/recv_bytes, poll, fileno, close.
    """

    def __init__(self, mode: str, addr: str, prefetch: int = 1) -> None:
        self._mode = mode
        self._addr = addr
        self._prefetch = max(1, int(prefetch))
        self._ep: Optional[Endpoint] = None
        self._lock = threading.Lock()

    # -- wiring -----------------------------------------------------------
    def _endpoint(self):
        """The underlying transport: the native C client (framing + socket
        + credit protocol in one ctypes call per op) when available, else
        a Python Endpoint. Both expose send/recv/poll/fileno/close."""
        if self._ep is None:
            with self._lock:
                if self._ep is None:
                    self._ep = self._connect_impl()
        return self._ep

    def _connect_impl(self):
        from fiber_tpu.transport.tcp import connect_transport

        return connect_transport(self._mode, self._addr,
                                 prefetch=self._prefetch)

    # -- data -------------------------------------------------------------
    def send_bytes(self, payload: bytes) -> None:
        self._endpoint().send(payload)

    def recv_bytes(self, timeout: Optional[float] = None) -> bytes:
        return self._endpoint().recv(timeout)

    def send(self, obj: Any) -> None:
        self.send_bytes(serialization.dumps(obj))

    def recv(self, timeout: Optional[float] = None) -> Any:
        return serialization.loads(self.recv_bytes(timeout))

    def poll(self, timeout: Optional[float] = 0.0) -> bool:
        """True if a message is ready (or arrives within ``timeout``).

        ``poll(0)`` only reports messages already delivered locally: on a
        demand-driven (connected read) end it does NOT request a frame
        from the producer, so a consumer that only ever zero-timeout
        polls will never observe data on an idle connection. Poll with a
        timeout (or call ``recv``) to express demand — polling is not
        consuming, and a pure ``empty()``-style loop must not pull frames
        toward an endpoint that may never read them."""
        return self._endpoint().poll(timeout)

    def fileno(self) -> int:
        return self._endpoint().fileno()

    def close(self) -> None:
        if self._ep is not None:
            self._ep.close()
            self._ep = None
        # Creator-side ends co-own the anchoring device: when the last
        # locally-created end closes, the device (listeners + pump threads)
        # is released too. Unpickled remote copies never carry _device and
        # never tear the pipe down.
        device_ref = getattr(self, "_device_ref", None)
        if device_ref is not None:
            self._device_ref = None
            device_ref.release()

    # -- pickling ---------------------------------------------------------
    def __getstate__(self):
        return (self._mode, self._addr, self._prefetch)

    def __setstate__(self, state) -> None:
        # Older pickles carry (mode, addr); newer add prefetch.
        if len(state) == 2:
            self._mode, self._addr = state
            self._prefetch = 1
        else:
            self._mode, self._addr, self._prefetch = state
        self._ep = None
        self._lock = threading.Lock()

    def __repr__(self) -> str:
        return f"Connection(mode={self._mode!r}, addr={self._addr!r})"


class _DeviceRef:
    """Refcount so a device closes when the last creator-side user of it
    is closed."""

    def __init__(self, device: Device, count: int) -> None:
        self._device = device
        self._count = count
        self._lock = threading.Lock()

    def release(self) -> None:
        with self._lock:
            self._count -= 1
            if self._count > 0:
                return
        self._device.close()


def Pipe(duplex: bool = True) -> Tuple[Connection, Connection]:
    """A pipe whose two ends are picklable and machine-portable
    (reference: fiber/queues.py:262-281).

    duplex=True: both ends send and receive. duplex=False: returns
    (receive_end, send_end) like multiprocessing.
    """
    ip = _listen_ip()
    if duplex:
        device = Device("rw", "rw", ip)
        c1 = Connection("rw", device.in_addr)
        c2 = Connection("rw", device.out_addr)
    else:
        device = Device("r", "w", ip)
        c1 = Connection("r", device.out_addr)   # receive end
        c2 = Connection("w", device.in_addr)    # send end
    # Anchor the device in the creating process; it dies when both
    # creator-side ends are closed (or with the process).
    ref = _DeviceRef(device, 2)
    c1._device_ref = ref  # type: ignore[attr-defined]
    c2._device_ref = ref  # type: ignore[attr-defined]
    return c1, c2


class SimpleQueue:
    """Multi-producer multi-consumer distributed queue.

    Producers PUSH to the device's in-address; the device PUSHes to
    consumers **round-robin** (the load-balancing contract of the
    reference's push queue, fiber/queues.py:284-352, tested for exact
    fairness by the reference suite).
    """

    def __init__(self, prefetch: int = 1) -> None:
        # prefetch=1 (default): pure demand-driven delivery — a dead
        # consumer never has undelivered messages parked in its socket
        # (the loss-free contract). prefetch=N>1: each consumer keeps a
        # bounded window of N messages in flight — much higher one-way
        # throughput, at the cost of up to N messages parked in a
        # consumer that dies mid-stream.
        self.prefetch = max(1, int(prefetch))
        ip = _listen_ip()
        self._device: Optional[Device] = Device("r", "w", ip)
        self._in_addr = self._device.in_addr
        self._out_addr = self._device.out_addr
        self._writer: Optional[Connection] = None
        self._reader: Optional[Connection] = None

    # -- lazy per-process connections -------------------------------------
    def _get_writer(self) -> Connection:
        if self._writer is None:
            self._writer = Connection("w", self._in_addr)
        return self._writer

    def _get_reader(self) -> Connection:
        if self._reader is None:
            self._reader = Connection("r", self._out_addr,
                                      prefetch=self.prefetch)
        return self._reader

    # -- queue API --------------------------------------------------------
    def put(self, obj: Any) -> None:
        self._get_writer().send(obj)

    def get(self, timeout: Optional[float] = None) -> Any:
        try:
            return self._get_reader().recv(timeout)
        except TimeoutError:
            raise pyqueue.Empty from None

    def empty(self) -> bool:
        """Approximate: True if no message is locally available.

        Like ``Connection.poll(0)``, this never requests a frame from the
        producer — an ``empty()``-only loop on an idle connected reader
        stays True forever; interleave ``get`` (or a timed ``poll``) to
        actually pull messages."""
        return not self._get_reader().poll(0.0)

    def wait_consumers(self, n: int, timeout: Optional[float] = None) -> bool:
        """Block until n consumers have dialed in (only callable in the
        creating process; used to make round-robin fan-out exact)."""
        if self._device is None:
            raise ValueError("wait_consumers: not the creating process")
        return self._device.wait_out_peers(n, timeout)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        if self._reader is not None:
            self._reader.close()
            self._reader = None
        if self._device is not None:
            self._device.close()
            self._device = None

    # -- pickling ---------------------------------------------------------
    def __getstate__(self):
        return (self._in_addr, self._out_addr, self.prefetch)

    def __setstate__(self, state) -> None:
        if len(state) == 2:  # older pickles
            self._in_addr, self._out_addr = state
            self.prefetch = 1
        else:
            self._in_addr, self._out_addr, self.prefetch = state
        self._device = None
        self._writer = None
        self._reader = None

    def __repr__(self) -> str:
        return f"SimpleQueue(in={self._in_addr!r}, out={self._out_addr!r})"

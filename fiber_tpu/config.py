"""Layered configuration for fiber_tpu.

Three layers with rising priority (reference parity: fiber/config.py:15-65):

1. config file — ``.fiberconfig`` in the current working directory (or a
   path passed as ``conf_file=``), INI format, ``[default]`` section;
2. environment — ``FIBER_<KEY>`` variables;
3. code — ``fiber_tpu.init(key=value)`` / ``fiber_tpu.config.init(...)``.

Unknown keys in the config file raise ``ValueError`` (reference:
fiber/config.py:149-153). The resolved config is serialized into the spawn
preparation data and re-applied inside every child process so the whole
process tree sees one config (reference: fiber/spawn.py:59-60).
"""

from __future__ import annotations

import configparser
import copy
import os
from typing import Any, Dict, Optional

DEFAULT_CONFIG_FILE = ".fiberconfig"
ENV_PREFIX = "FIBER_"

#: Default values; also the schema (key set + types) for file/env coercion.
DEFAULTS: Dict[str, Any] = {
    # --- scheduling / backend ---
    "backend": "",           # "" = auto-select (local unless on a TPU pod)
    "image": "",             # container/VM image for remote backends
    "cpu_per_job": 1,        # local worker processes packed per job
    "mem_per_job": 0,        # MB; 0 = backend default
    # --- logging ---
    "log_level": "INFO",
    "log_file": "/tmp/fiber_tpu.log",   # "stdout" = log to stdout
    # --- control plane (admin channel) ---
    "ipc_active": True,      # worker dials master (False: master dials worker)
    "ipc_admin_master_port": 0,     # 0 = random
    "ipc_admin_worker_port": 8000,  # used only in passive mode
    # --- health plane (docs/robustness.md) ---
    # Worker/agent heartbeat period, seconds; 0 disables heartbeats AND
    # the deadline failure detector (silence then only surfaces via TCP
    # or process reaping).
    "heartbeat_interval": 1.0,
    # Seconds of peer silence before the failure detector declares it
    # dead and triggers the pool's resubmit path. Must comfortably
    # exceed heartbeat_interval (10x by default).
    "suspect_timeout": 10.0,
    # Consecutive spawn failures that open the per-target circuit
    # breaker; while open, the pool stops hammering the target.
    "spawn_breaker_threshold": 3,
    # First open period, seconds (doubles per re-open, + jitter) and its
    # cap. Deliberately small: the terminal _SPAWN_FAIL_LIMIT escalation
    # in pool.py must still fire within ~a minute on a dead backend.
    "spawn_breaker_backoff": 0.25,
    "spawn_breaker_backoff_max": 2.0,
    # --- scheduler plane (docs/scheduling.md) ---
    # Pool handout policy: "adaptive" = locality-aware placement + fair
    # multi-map queueing (and, when enabled below, straggler
    # speculation); "fifo" = the reference's plain arrival-order
    # handout (also the bench.py --sched A/B baseline).
    "sched_policy": "adaptive",
    # Prefer handing ref-bearing chunks to workers on hosts whose store
    # already caches the referenced objects.
    "locality_enabled": True,
    # Launch a speculative duplicate of a straggling chunk (first
    # result wins; the loser is discarded idempotently). Off by
    # default: duplicates are only safe for idempotent task functions
    # WITHOUT side effects — stricter than the resilient pool's
    # baseline contract, which duplicates only on worker death.
    "speculation_enabled": False,
    # A dispatched chunk older than this multiple of its map's median
    # service time (with spare workers idle and the queue drained) is
    # speculated.
    "speculation_quantile": 4.0,
    # --- hierarchical dispatch (docs/architecture.md) ---
    # "direct": the master hands one chunk per worker request (the
    # reference shape). "hier": packed workers (cpu_per_job > 1,
    # ResilientPool) promote their packing parent to a per-host
    # sub-master — the master hands out whole chunk RANGES (one frame,
    # encoded once) and the sub-master fans individual chunks to its
    # local workers and streams results back aggregated, so master
    # frame count and encode CPU scale with hosts rather than workers.
    # A sub-master death degrades respawned hosts to "direct".
    "dispatch_mode": "direct",
    # Upper bound on chunks handed out per range frame in "hier" mode.
    "dispatch_range_chunks": 16,
    # --- data plane ---
    "use_push_queue": True,
    # --- transport I/O core (docs/transport.md) ---
    # "selector": one selectors-driven poller thread per process owns
    # every channel socket — non-blocking incremental frame decode,
    # scatter-gather (sendmsg) sends, small-frame coalescing; socket
    # threads are O(1) in connection count. "threads": the blocking
    # thread-per-connection fallback (one reader thread per channel).
    # "shm": same-host zero-copy — each connection auto-negotiates a
    # pair of mmap'd ring buffers when both peers share a host key
    # (frames move through /dev/shm with one copy per side) and falls
    # back to plain TCP otherwise; counters and chaos semantics are
    # identical across all three engines (docs/transport.md).
    "transport_io": "selector",
    # Per-direction shm ring capacity in KiB (transport_io="shm"). Each
    # negotiated channel maps two rings of this size; frames larger
    # than the ring stream through it in chunks.
    "transport_shm_ring_kb": 4096,
    # Upper bound on bytes the selector loop gathers into one coalesced
    # sendmsg flush; small control frames (credit, hb, spans, storemiss)
    # queued between poller wakeups leave in a single syscall up to this
    # size. Large payloads are never split — a frame bigger than the cap
    # still goes out as one vectored send.
    "transport_coalesce_max": 256 * 1024,
    # Standing credit window a bound r-endpoint grants each peer (fan-in
    # ingress like pool result streams): how many frames a sender may
    # run ahead of the consumer. Large enough to never throttle by
    # default; lower it to bound per-peer master memory (window x frame
    # size) — bench.py --transport also lowers it to pace its pushers
    # into a steady stream.
    "transport_credit_window": 4096,
    # --- object store (docs/objectstore.md) ---
    # By-reference task data plane: pool args/results whose serialized
    # size exceeds store_inline_max bytes travel as ObjectRefs through
    # the per-host object store instead of riding every task frame.
    # 0 disables the store (everything ships inline), as does
    # store_enabled=False.
    "store_enabled": True,
    "store_inline_max": 512 * 1024,
    # Host-RAM LRU capacity of the local store, MB; colder objects spill
    # to disk under store_dir.
    "store_capacity_mb": 512,
    # Content-addressed object directory shared by every fiber process
    # on a host (fetch dedup + spill). "" = <staging root>/objects,
    # where the staging root is FIBER_AGENT_STAGING or
    # ~/.fiber_tpu/staging (utils/staging.py / host_agent.py).
    "store_dir": "",
    # Device-resident store tier (docs/objectstore.md "Device tier"):
    # device-destined payloads are cached ON the accelerator (digest ->
    # replicated jax.Array + sharding metadata) so repeat resolutions
    # of the same content ride ICI instead of re-paying wire + H2D.
    # Demoted to the host tiers by the `hbm_fill` watchdog rule
    # (closed-loop remediation; re-promoted when the rule clears).
    "store_device_enabled": True,
    # HBM budget of the device tier, MB. Colder entries are dropped LRU
    # past it (safe: the host RAM/disk tiers still hold the bytes);
    # pinned entries are untouchable.
    "store_device_capacity_mb": 256,
    # --- streaming data plane (docs/streaming.md) ---
    # Windowed streaming admission for imap/imap_unordered: the master
    # pulls from the caller's iterator lazily and keeps at most
    # stream_window chunks encoded + in flight + un-yielded at any
    # instant, so master memory is O(window) instead of O(n). A slow
    # consumer parks admission (condition-variable), which parks
    # dispatch, which lets transport credits drain — backpressure is
    # end-to-end. Off, imap still avoids materializing the iterable but
    # admission is unwindowed (legacy posture; the ledger path then
    # needs a full materialization for its fixed task digest).
    "stream_enabled": True,
    # Admission window in CHUNKS (not tasks): encoded-but-unyielded
    # chunks the master will hold at once. Also the policy plane's
    # `queue_growth` -> shrink_stream_window knob target. 128 keeps
    # streamed throughput within a few percent of a materialized map
    # (each admission park/wake cycle briefly starves dispatch, so the
    # window must cover several consumer batches); halve it per level
    # of memory pressure instead of shrinking the default.
    "stream_window": 128,
    # --- durability (docs/robustness.md "Durable maps") ---
    # Write-ahead map ledger: Pool.map(..., job_id=...) journals the
    # task spec + every completed chunk's result digest under
    # ledger_dir, making the map resumable across master crashes
    # (`fiber-tpu resume <job_id>` / re-calling map with the job_id).
    # Off, job_id is accepted but nothing is journaled.
    "ledger_enabled": True,
    # Ledger directory. "" = <staging root>/ledger, beside the objects/
    # cache the journaled result payloads persist into.
    "ledger_dir": "",
    # Accumulation window of the ledger writer thread, seconds: chunk
    # records queued within it land in one write + one fsync. The hot
    # result loop only ever pays a buffered append.
    "ledger_fsync_s": 0.05,
    # Re-replicate precious digests (ledger-journaled results, active
    # broadcasts) to a second healthy host when the health plane
    # declares their holder suspect — recovery then never needs the
    # dead host.
    "store_replicate": True,
    # Strip accelerator runtime preloads from spawned host workers (faster
    # interpreter boot; only for workers that never touch the device).
    "worker_lite": False,
    # --- telemetry plane (docs/observability.md) ---
    # Master switch for the metrics registry + span tracing. Off, every
    # instrument call is a single attribute check and nothing is
    # recorded.
    "telemetry_enabled": True,
    # Fraction of Pool maps that get a trace id stamped into their task
    # envelopes (workers then record + ship spans for those chunks).
    # 1.0 = trace everything (default; the bench pins full-tracing
    # overhead < 5% on the small-task microbench), 0.0 = metrics only.
    "trace_sample_rate": 1.0,
    # Per-process finished-span ring buffer: oldest spans fall out past
    # this many (bounds memory on long-lived masters/workers).
    "span_buffer_size": 4096,
    # Port for the authenticated Prometheus exposition endpoint
    # (telemetry.serve_metrics / the host agent's sidecar). 0 = off.
    "metrics_port": 0,
    # Flight recorder (docs/observability.md): per-process ring buffer
    # of structured plane events (pool/sched/store/transport/health) —
    # the black box `fiber-tpu explain`, postmortem bundles and the
    # cluster bench read. Near-zero when off; fully on it is gated
    # <= 5% by `make bench-telemetry`'s flightrec arm. Requires
    # telemetry_enabled too (one master switch for the whole plane).
    "flightrec_enabled": True,
    # Events kept in the ring before the oldest fall out (each is a
    # small dict; 2048 bounds a long-lived master to ~1 MB).
    "flightrec_buffer_size": 2048,
    # --- continuous monitor plane (docs/observability.md) ---
    # Per-process sampler thread that snapshots the hot instruments
    # (tasks/s, bytes/s, queue depth, inflight, heartbeat age) every
    # monitor_interval_s into bounded time-series rings, and the
    # anomaly watchdog that rides it. Off: no thread, no rings, the
    # only cost is one check per telemetry.refresh(). Requires
    # telemetry_enabled (one master switch for the plane).
    "monitor_enabled": True,
    "monitor_interval_s": 1.0,
    # Points kept per series ring (600 x 1s = a 10-minute window).
    "monitor_history": 600,
    # Wall-clock sampling profiler (telemetry/profiler.py): > 0 arms a
    # per-process sampler at this many stack samples per second,
    # aggregated as flamegraph folded stacks; pool workers ship theirs
    # back on the result stream. 0 (default) = off, zero cost. The
    # armed cost is gated <= 5% by `make bench-telemetry`'s profiler
    # arm at ~100 Hz.
    "profiler_hz": 0.0,
    # Anomaly watchdog rules (telemetry/monitor.py). tasks/s dropping
    # more than this fraction below its trailing-window mean (with
    # work in flight) raises `throughput_drop`:
    "anomaly_drop_pct": 0.5,
    # Consecutive samples of monotonic queue-depth growth that raise
    # `queue_growth`:
    "anomaly_queue_intervals": 5,
    # Transport egress queue bytes (MB) past which `tx_queue_high`
    # raises (half the 32 MiB per-channel TX_HIGH_WATER block):
    "anomaly_tx_queue_mb": 16.0,
    # Store disk-tier fill fraction (of max_disk_bytes) past which
    # `store_disk_fill` raises:
    "anomaly_disk_fill_pct": 0.9,
    # --- device telemetry plane (docs/observability.md) ---
    # Transfer accounting at the host->device boundary (store resolve,
    # deserialize, device_map plan, checkpoint restore), jax.monitoring
    # compile listeners, HBM/live-array gauges and the live pool_map_mfu
    # gauge. Requires telemetry_enabled; off, every hook is one
    # attribute check. Gated <= 5% by `make bench-telemetry`'s device
    # arm.
    "device_telemetry_enabled": True,
    # Recompiles of ONE fingerprint inside the window that raise the
    # `recompile_storm` watchdog rule (shape churn, not progress):
    "anomaly_recompile_count": 4,
    "anomaly_recompile_window_s": 30.0,
    # HBM fill fraction (bytes_in_use / bytes_limit, when the device
    # reports memory_stats) past which `hbm_fill` raises:
    "anomaly_hbm_fill_pct": 0.92,
    # --- policy plane (docs/observability.md "Autonomous operations") ---
    # Watchdog anomalies -> remediation actions (telemetry/policy.py):
    # every action is a `policy` flight event linked to its anomaly via
    # cause_id, and policy_verify_s later the engine re-samples the
    # rule and records the outcome (resolved/persisted/worsened).
    # Requires telemetry_enabled.
    "policy_enabled": True,
    # Record what WOULD be done without acting (planning/audit mode).
    "policy_dry_run": False,
    # Per-rule cooldown between repeated actions, seconds (a flapping
    # rule must not re-fire its remediation every edge). The hbm_fill
    # demote/promote pair is exempt: its hysteresis is the watchdog
    # edge itself.
    "policy_cooldown_s": 30.0,
    # Delay before the engine re-samples a rule and classifies its
    # action's outcome:
    "policy_verify_s": 3.0,
    # Comma-separated rule allowlist for the engine; "all" = every
    # registered policy.
    "policy_rules": "all",
    # --- accounting plane (docs/observability.md "Resource accounting") ---
    # Per-map/per-tenant cost attribution: billing keys ride the task
    # envelope tail, workers ship cumulative ("cost", ...) frames, and
    # Pool.cost()/`fiber-tpu cost` render per-job CostReports. Requires
    # telemetry_enabled; off, every hook is one attribute check. Gated
    # <= 5% by `make bench-accounting`.
    "accounting_enabled": True,
    # Tenant label billed for every map this process submits (the serve
    # tier will stamp it per client); bounded per-job metric labels ride
    # it (cost_tasks_total{tenant=,job=}).
    "tenant": "default",
    # Per-job cost record directory. "" = <staging root>/costs, beside
    # the ledger/ directory `fiber-tpu jobs` reads.
    "cost_dir": "",
    # --- serving tier (docs/serving.md) ---
    # `fiber-tpu serve` daemon: a long-lived multi-tenant front door
    # multiplexing many clients' jobs onto one shared pool, with
    # admission control, budget preemption and a warm worker pool.
    # RPC port the daemon listens on (authenticated with
    # FIBER_CLUSTER_KEY, same plane as the host agents).
    "serve_port": 7070,
    # Worker-slot ceiling for the shared pool; 0 = cpu_count().
    "serve_processes": 0,
    # Warm pool floor: standby workers kept spawned even when idle, so
    # a newly admitted tenant's first chunk skips cold spawn latency.
    "serve_warm_floor": 2,
    # Warm pool ceiling; 0 = serve_processes (fully elastic in range).
    "serve_warm_ceiling": 0,
    # Idle seconds (zero in-flight + zero queued chunks) before the
    # warm pool scales back down to the floor.
    "serve_warm_idle_s": 5.0,
    # Daemon housekeeping tick, seconds: admission escalation sweep +
    # warm pool scaling decisions.
    "serve_tick_s": 0.5,
    # Per-tenant admission quotas; 0 = unlimited. Checked at submit
    # against the accounting plane's live cost vectors.
    "serve_tenant_jobs": 0,        # concurrent running jobs per tenant
    "serve_tenant_tasks": 0,       # cumulative submitted tasks per tenant
    "serve_tenant_cpu_s": 0.0,     # cumulative worker CPU seconds per tenant
    # Watchdog anomaly rules whose STANDING (active) state refuses new
    # admissions; comma-separated.
    "serve_deny_rules": "store_disk_fill,hbm_fill",
    # Grace period, seconds, between a tenant's budget_exceeded anomaly
    # (WDRR throttle, the policy plane's first response) and escalation
    # to actual preemption (job parked resumable, chunks reclaimed).
    "serve_preempt_grace_s": 2.0,
    # Serve-tier job journal directory. "" = <staging root>/serve,
    # beside ledger/ and costs/.
    "serve_dir": "",
    # --- observability archive (docs/observability.md "SLOs and the
    # archive") ---
    # Persist monitor samples, flight/anomaly/policy events and per-job
    # cost/SLO observations into time-partitioned segment files each
    # sampler tick. Off by default: the serve daemon arms it
    # process-locally on startup (ARCHIVE.enable()), so pool workers
    # never inherit an archive writer through config adoption; set True
    # to archive any process.
    "archive_enabled": False,
    # Archive directory. "" = <staging root>/archive, beside ledger/,
    # costs/ and serve/.
    "archive_dir": "",
    # Segment roll interval, seconds: one file per window keeps
    # time-range queries from scanning the whole history.
    "archive_segment_s": 300.0,
    # Longest interval, seconds, an accepted record may sit in the OS
    # page cache before fsync (the ledger_fsync_s posture: batched
    # durability, bounded loss window).
    "archive_fsync_s": 0.2,
    # Retention horizon, seconds: segments whose window ended earlier
    # are pruned on roll.
    "archive_retention_s": 604800.0,
    # Size cap, MB: oldest segments pruned first once the archive
    # exceeds it.
    "archive_max_mb": 256,
    # --- per-tenant SLOs (serve daemon; docs/observability.md) ---
    # Declarative targets over the serve tier's per-tenant SLIs. A
    # latency/queue target of 0 disables that objective; the error-rate
    # objective is always on (its budget is serve_slo_error_pct). The
    # burn-rate evaluation is multi-window: `slo_burn` raises only when
    # BOTH the fast and the slow window burn past serve_slo_burn.
    "serve_slo_latency_s": 0.0,    # submit->done latency target, seconds
    "serve_slo_queue_s": 0.0,      # queue-wait target, seconds
    "serve_slo_p": 0.95,           # percentile the latency targets bound
    "serve_slo_error_pct": 0.01,   # error budget: allowed bad-job fraction
    "serve_slo_window_s": 3600.0,  # slow burn window, seconds
    "serve_slo_fast_window_s": 300.0,  # fast burn window, seconds
    "serve_slo_burn": 2.0,         # burn-rate threshold (both windows)
    # --- TPU backend ---
    "tpu_name": "",
    "tpu_zone": "",
    "tpu_project": "",
    "tpu_hosts": "",          # comma-separated host list override / sim hosts
    # Ship the master's cwd source tree to cluster hosts at spawn (the
    # Docker-image role in the reference): "auto" = on for backends with
    # staging support (tpu agents), "off" = never.
    "code_staging": "auto",
    "mesh_shape": "",         # e.g. "8" or "4x2"; "" = all local devices
    # --- misc ---
    "debug": False,
}

_VALID_KEYS = frozenset(DEFAULTS)


def _coerce(key: str, value: Any) -> Any:
    """Coerce a string from file/env to the type of the default value."""
    default = DEFAULTS[key]
    if isinstance(value, str):
        if isinstance(default, bool):
            return value.strip().lower() in ("1", "true", "yes", "on")
        if isinstance(default, int) and not isinstance(default, bool):
            return int(value)
        if isinstance(default, float):
            return float(value)
    return value


class Config:
    """A resolved configuration: defaults < file < env < code kwargs."""

    def __init__(self, conf_file: Optional[str] = None, **kwargs: Any) -> None:
        self._values: Dict[str, Any] = copy.deepcopy(DEFAULTS)
        self._load_file(conf_file)
        self._load_env()
        self.update(**kwargs)

    def _load_file(self, conf_file: Optional[str]) -> None:
        path = conf_file or os.path.join(os.getcwd(), DEFAULT_CONFIG_FILE)
        if not os.path.exists(path):
            if conf_file:
                raise ValueError(f"config file not found: {conf_file}")
            return
        parser = configparser.ConfigParser()
        parser.read(path)
        if not parser.has_section("default"):
            return
        for key, raw in parser.items("default"):
            if key not in _VALID_KEYS:
                raise ValueError(
                    f"invalid key in config file {path!r}: {key!r}"
                )
            self._values[key] = _coerce(key, raw)

    def _load_env(self) -> None:
        for key in _VALID_KEYS:
            env = os.environ.get(ENV_PREFIX + key.upper())
            if env is not None:
                self._values[key] = _coerce(key, env)

    def update(self, **kwargs: Any) -> None:
        for key, value in kwargs.items():
            if key == "conf_file":
                continue
            if key not in _VALID_KEYS:
                raise ValueError(f"invalid config key: {key!r}")
            self._values[key] = _coerce(key, value)

    def __getattr__(self, key: str) -> Any:
        try:
            return self.__dict__["_values"][key]
        except KeyError:
            raise AttributeError(key) from None

    def __setattr__(self, key: str, value: Any) -> None:
        if key.startswith("_"):
            super().__setattr__(key, value)
        else:
            self.update(**{key: value})

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._values)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Config({self._values!r})"


_current: Config = Config()


def get() -> Config:
    """Return the process-wide config object."""
    return _current


def init(conf_file: Optional[str] = None, **kwargs: Any) -> Config:
    """Rebuild the process-wide config: defaults < file < env < kwargs."""
    global _current
    _current = Config(conf_file=conf_file, **kwargs)
    return _current


def init_from(values: Dict[str, Any]) -> Config:
    """Adopt a fully-resolved config dict (used by the worker bootstrap so a
    child sees exactly the parent's config — reference: fiber/spawn.py:59-60).
    """
    global _current
    cfg = Config.__new__(Config)
    cfg._values = copy.deepcopy(DEFAULTS)
    cfg._values.update({k: v for k, v in values.items() if k in _VALID_KEYS})
    _current = cfg
    return _current


def reset() -> Config:
    """Reset to pure defaults (no file/env), mainly for tests."""
    global _current
    cfg = Config.__new__(Config)
    cfg._values = copy.deepcopy(DEFAULTS)
    _current = cfg
    return _current


def __getattr__(name: str) -> Any:
    """Module-level attribute access proxies the current config
    (``fiber_tpu.config.backend`` etc., reference exposes module globals)."""
    if name in _VALID_KEYS:
        return getattr(_current, name)
    raise AttributeError(name)

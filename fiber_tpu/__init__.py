"""fiber_tpu — a TPU-native distributed computing framework.

fiber_tpu re-creates the capabilities of uber/fiber (a Python
``multiprocessing``-compatible API over cluster schedulers — reference
surveyed in SURVEY.md) as a brand-new framework whose first-class target is
a Cloud TPU pod slice:

* The **host plane** — ``Process``, ``Pool``, ``SimpleQueue``, ``Pipe``,
  ``Manager`` — runs arbitrary Python task-parallel workloads across
  TPU-VM hosts (or local subprocesses) over a framed-TCP transport
  (reference parity: fiber/context.py, fiber/pool.py, fiber/queues.py).
* The **device plane** — ``fiber_tpu.parallel`` / ``fiber_tpu.ops`` —
  lowers ``Pool.map`` of jittable functions to a ``shard_map``
  scatter → XLA-compiled worker → gather over a ``jax.sharding.Mesh``,
  and lowers ``Ring`` allreduce to ``jax.lax.psum`` over ICI.

Public API parity with the reference package root (fiber/__init__.py:65-68
hoists the context attributes; we do the same explicitly).
"""

import os as _os

__version__ = "0.1.0"

from fiber_tpu import config  # noqa: F401
from fiber_tpu.meta import meta  # noqa: F401
from fiber_tpu.telemetry.accounting import CostBudget  # noqa: F401
from fiber_tpu.context import FiberContext as _FiberContext

_default_context = _FiberContext()

# Hoisted context API (reference: fiber/__init__.py:65-68).
Process = _default_context.Process
Pool = _default_context.Pool
Manager = _default_context.Manager
AsyncManager = _default_context.AsyncManager
SimpleQueue = _default_context.SimpleQueue
Pipe = _default_context.Pipe
cpu_count = _default_context.cpu_count
current_process = _default_context.current_process
active_children = _default_context.active_children
get_context = _default_context.get_context

in_worker = _os.environ.get("FIBER_WORKER", "") not in ("", "0")


def init(**kwargs):
    """(Re)initialize fiber_tpu: apply config overrides and reset logging.

    Reference parity: fiber/__init__.py:54-62 + fiber/init.py:52-73.
    """
    from fiber_tpu.utils import logging as _fl
    from fiber_tpu import telemetry as _telemetry

    config.init(**kwargs)
    _fl.init_logger(config.get())
    _telemetry.refresh()


def reset():
    """Reset config back to defaults (then env/file reapply on next init)."""
    config.reset()


# Master-process logger init at import, mirroring fiber/__init__.py:36-41:
# workers re-init inside the spawn bootstrap with the shipped config instead.
if not in_worker:
    from fiber_tpu.utils import logging as _fl

    _fl.init_logger(config.get())
del _os

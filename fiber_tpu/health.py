"""Cluster health plane: heartbeats, deadline failure detection, and
spawn circuit breaking.

The process layer already survives worker death the kernel reports
(pool.py resubmission, launcher job polling). This module adds the layer
above it — failures the kernel does NOT report promptly: a hung host, a
frozen process, a network path silently blackholed. Three primitives,
mirrored from production training/inference stacks:

* :class:`Heartbeater` — emits a beat on an existing channel every
  ``heartbeat_interval`` seconds from a daemon thread. Pool workers ride
  their result stream (the master's ``_result_loop`` already fair-merges
  it); no extra sockets.
* :class:`FailureDetector` — deadline-based: a peer silent for
  ``suspect_timeout`` seconds is declared dead *before* TCP notices
  (TCP keepalive defaults to minutes; a SIGSTOP'd peer never FINs).
  The pool's declaration handler runs the SAME reclaim path as an
  observed process death, so resubmission semantics cannot diverge.
  Declaring a live-but-slow peer dead is safe by construction there:
  resilient-pool tasks are idempotent and duplicate results dedupe.
* :class:`CircuitBreaker` — per-key (host / backend) spawn gate with
  exponential backoff + jitter. Replaces hammering a refusing backend
  every maintenance tick; the terminal ``_SPAWN_FAIL_LIMIT`` escalation
  in pool.py stays as the loud failure of last resort.

Knobs live in config.py (``heartbeat_interval``, ``suspect_timeout``,
``spawn_breaker_*``) and are documented in docs/robustness.md.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, Optional

from fiber_tpu import telemetry
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.utils.logging import get_logger

logger = get_logger()


def _peer_label(peer) -> str:
    """Flight-event-safe peer name (pool idents are raw bytes)."""
    return peer.hex() if isinstance(peer, (bytes, bytearray)) else str(peer)

# Health-plane observability (docs/observability.md): breaker/suspect
# state changes are exported metrics, not just log lines.
_m_heartbeats = telemetry.counter(
    "health_heartbeats_emitted", "Heartbeats emitted by this process")
_m_suspects = telemetry.counter(
    "health_suspects_declared",
    "Peers declared dead by the deadline failure detector")
_m_revived = telemetry.counter(
    "health_peers_revived", "Suspected peers revived by a later beat")
_m_breaker_opens = telemetry.counter(
    "health_breaker_opens", "Circuit-breaker open transitions")
_g_breaker_open = telemetry.gauge(
    "health_breaker_open_keys", "Keys currently held open by a breaker")

#: Live failure detectors in this process. The monitor plane
#: (telemetry/timeseries + the anomaly watchdog) reads per-peer
#: heartbeat AGES through this registry so a peer drifting toward its
#: suspect deadline is visible *before* the declaration fires. Weak:
#: a stopped pool's detector must not be pinned alive by telemetry.
DETECTORS: "weakref.WeakSet[FailureDetector]" = weakref.WeakSet()


def heartbeat_ages() -> Dict[str, float]:
    """Seconds since the last beat of every tracked peer across every
    live detector, keyed by the flight-safe peer label. Suspected
    (already-declared) peers are excluded — they are the health plane's
    problem; this surface is for trouble still brewing."""
    out: Dict[str, float] = {}
    for detector in list(DETECTORS):
        try:
            if detector._stop.is_set():
                continue  # a stopped pool's peers are not "silent"
            for peer, age in detector.ages().items():
                label = _peer_label(peer)
                out[label] = max(age, out.get(label, 0.0))
        except Exception:  # noqa: BLE001 - monitoring must not fail
            continue
    return out


class Heartbeater:
    """Call ``emit()`` every ``interval`` seconds on a daemon thread.

    ``emit`` does the actual send and may raise: ``TimeoutError`` skips
    one beat (channel congested — the frames already in flight serve as
    the beat); any ``OSError`` stops the thread (channel gone for good —
    the process is exiting or the master died, and the watchdog layers
    own that). ``gate`` is consulted before each beat; returning False
    skips it (chaos uses this to simulate a hung host without touching
    the emitter).
    """

    def __init__(self, emit: Callable[[], None], interval: float,
                 gate: Optional[Callable[[], bool]] = None,
                 name: str = "fiber-heartbeat") -> None:
        if interval <= 0:
            raise ValueError("heartbeat interval must be > 0")
        self._emit = emit
        self._interval = float(interval)
        self._gate = gate
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self.beats = 0  # emitted count (observable by tests)

    def start(self) -> "Heartbeater":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            if self._gate is not None and not self._gate():
                continue
            try:
                self._emit()
                self.beats += 1
                _m_heartbeats.inc()
            except TimeoutError:
                continue  # congested; data frames in flight beat for us
            except OSError:
                return  # channel closed under us: nothing left to beat on
            except Exception:
                logger.exception("heartbeater: emit failed; stopping")
                return


class FailureDetector:
    """Deadline failure detector over heartbeat observations.

    ``beat(peer)`` registers/refreshes a peer; a monitor thread declares
    any peer silent for ``suspect_timeout`` seconds dead and calls
    ``on_suspect(peer)`` (outside the detector lock — handlers may call
    back into :meth:`forget`). With ``permanent=True`` (pool worker
    idents, which are never reused) a declared peer stays dead and its
    late beats are ignored; with ``permanent=False`` (host agents, which
    restart) a later beat revives the peer and ``on_suspect`` may fire
    again on the next silence.
    """

    def __init__(self, suspect_timeout: float,
                 on_suspect: Callable[[object], None],
                 permanent: bool = True,
                 name: str = "fiber-failure-detector",
                 on_revive: Optional[Callable[[object], None]] = None
                 ) -> None:
        if suspect_timeout <= 0:
            raise ValueError("suspect_timeout must be > 0")
        self._timeout = float(suspect_timeout)
        self._on_suspect = on_suspect
        self._on_revive = on_revive
        self._permanent = permanent
        self._last_seen: Dict[object, float] = {}
        self._dead: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        self.suspected_total = 0  # lifetime declarations (observable)
        DETECTORS.add(self)

    def start(self) -> "FailureDetector":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def beat(self, peer) -> None:
        now = time.monotonic()
        revived = False
        with self._lock:
            if peer in self._dead:
                if self._permanent:
                    return  # declared dead stays dead; ident won't reuse
                self._dead.discard(peer)
                revived = True
            self._last_seen[peer] = now
        if revived:
            _m_revived.inc()
            FLIGHT.record("health", "revive", peer=_peer_label(peer))
            logger.info("health: peer %r revived after being declared "
                        "dead", peer)
            if self._on_revive is not None:
                # Outside the lock (handlers may call back in). The
                # backend uses this to clear the peer's stale circuit
                # breaker: a host that answers again must not stay
                # parked behind an open period earned while it was down.
                try:
                    self._on_revive(peer)
                except Exception:
                    logger.exception("health: on_revive handler failed "
                                     "for %r", peer)

    def forget(self, peer) -> None:
        """Deregister a peer whose death was observed through another
        path (process reap, clean retirement) so it is never suspected
        post-mortem."""
        with self._lock:
            self._last_seen.pop(peer, None)
            if self._permanent:
                self._dead.add(peer)

    def is_suspect(self, peer) -> bool:
        with self._lock:
            return peer in self._dead

    def ages(self) -> Dict[object, float]:
        """Seconds of silence per still-tracked peer (monitor plane;
        peers already declared dead are not listed)."""
        now = time.monotonic()
        with self._lock:
            return {p: now - seen for p, seen in self._last_seen.items()}

    def peers(self) -> Iterable:
        with self._lock:
            return list(self._last_seen)

    def _loop(self) -> None:
        tick = min(max(self._timeout / 4.0, 0.05), 1.0)
        while not self._stop.wait(tick):
            deadline = time.monotonic() - self._timeout
            with self._lock:
                expired = [p for p, seen in self._last_seen.items()
                           if seen < deadline]
                for peer in expired:
                    del self._last_seen[peer]
                    self._dead.add(peer)
                    self.suspected_total += 1
                    _m_suspects.inc()
                    FLIGHT.record(
                        "health", "suspect", peer=_peer_label(peer),
                        reason=f"silent > {self._timeout:g}s")
            for peer in expired:
                try:
                    self._on_suspect(peer)
                except Exception:
                    logger.exception("health: on_suspect handler failed "
                                     "for %r", peer)


class CircuitBreaker:
    """Per-key spawn-target breaker with exponential backoff + jitter.

    closed → (``fail_threshold`` consecutive failures) → open for
    ``base_backoff * 2^(opens-1)`` seconds (capped at ``max_backoff``,
    stretched by up to ``jitter`` fraction so a fleet of masters never
    retries a recovering host in lockstep) → half-open: the next
    ``allow()`` admits one trial; its failure reopens with doubled
    backoff, its success closes and resets everything.
    """

    def __init__(self, fail_threshold: int = 3,
                 base_backoff: float = 0.25,
                 max_backoff: float = 2.0,
                 jitter: float = 0.25,
                 rng: Optional[random.Random] = None) -> None:
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self._threshold = int(fail_threshold)
        self._base = float(base_backoff)
        self._max = float(max_backoff)
        self._jitter = float(jitter)
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        # key -> [consecutive_fails, opens, open_until (monotonic) | None]
        self._state: Dict[object, list] = {}
        self.opened_total = 0  # lifetime opens across keys (observable)

    def _entry(self, key) -> list:
        entry = self._state.get(key)
        if entry is None:
            entry = self._state[key] = [0, 0, None]
        return entry

    def allow(self, key) -> bool:
        """True unless the key's breaker is open (an expired open period
        admits trial attempts — half-open)."""
        with self._lock:
            entry = self._state.get(key)
            if entry is None or entry[2] is None:
                return True
            return time.monotonic() >= entry[2]

    def record_failure(self, key) -> bool:
        """Count one failure; returns True when this failure opened (or
        re-opened) the breaker."""
        with self._lock:
            entry = self._entry(key)
            entry[0] += 1
            half_open = entry[2] is not None \
                and time.monotonic() >= entry[2]
            if entry[0] < self._threshold and not half_open:
                return False
            entry[1] += 1
            self.opened_total += 1
            _m_breaker_opens.inc()
            backoff = min(self._base * (2 ** (entry[1] - 1)), self._max)
            backoff *= 1.0 + self._jitter * self._rng.random()
            entry[2] = time.monotonic() + backoff
            FLIGHT.record("health", "breaker_open",
                          key=_peer_label(key), backoff_s=round(backoff, 4),
                          opens=entry[1])
            entry[0] = 0  # streak restarts toward the next open
            now = time.monotonic()
            _g_breaker_open.set(sum(
                1 for e in self._state.values()
                if e[2] is not None and now < e[2]))
            return True

    def record_success(self, key) -> None:
        with self._lock:
            entry = self._state.pop(key, None)
            if entry is not None and entry[2] is not None:
                # Only open->closed transitions are flight-worthy; the
                # routine success of a never-failed key is not.
                FLIGHT.record("health", "breaker_close",
                              key=_peer_label(key))
            now = time.monotonic()
            _g_breaker_open.set(sum(
                1 for e in self._state.values()
                if e[2] is not None and now < e[2]))

    def state(self, key) -> str:
        with self._lock:
            entry = self._state.get(key)
            if entry is None or entry[2] is None:
                return "closed"
            return "half-open" if time.monotonic() >= entry[2] else "open"

    def open_keys(self) -> Iterable:
        now = time.monotonic()
        with self._lock:
            return [k for k, e in self._state.items()
                    if e[2] is not None and now < e[2]]

"""Worker-side bootstrap: ``python -m fiber_tpu.worker``.

Reference parity: fiber/spawn.py (spawn_prepare + the master-death
watchdog) and the ``python -c`` bootstrap templates in
fiber/popen_fiber_spawn.py:43-77. Sequence:

1. dial the master's admin server and send our launch ident (active mode),
   or listen on the fixed admin port and accept the master's dial-in
   (passive mode, ``ipc_active=False``);
2. receive the preparation frame: adopt the parent's config, sys.path,
   logging, and re-import the user's __main__ so pickled targets resolve;
3. receive the Process frame and run ``_bootstrap()``;
4. a watchdog thread blocks on the admin socket: if it closes (master died
   or reaped us), SIGTERM ourselves, then hard-exit after a grace period.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import sys
import threading
import time

# Must be set before fiber_tpu is imported so the package skips
# master-style logger init (fiber_tpu/__init__.py).
os.environ.setdefault("FIBER_WORKER", "1")

_worker_done = threading.Event()


def _apply_preparation(prep: dict) -> None:
    import multiprocessing
    import multiprocessing.spawn as mp_spawn

    from fiber_tpu import config
    from fiber_tpu.utils import logging as flogging

    # Staged workspace snapshot (multi-host code distribution): resolved
    # by the host agent from the {FIBER_STAGING} placeholder. It outranks
    # the master's sys_path entries — those name master-local directories
    # that may not exist on this host.
    staged = os.environ.get("FIBER_STAGED_CODE", "")

    cwd = prep.get("cwd")
    if cwd and os.path.isdir(cwd):
        os.chdir(cwd)
    elif staged and os.path.isdir(staged):
        # Master's cwd doesn't exist here; the snapshot is its stand-in.
        os.chdir(staged)

    for path in reversed(prep.get("sys_path", [])):
        if path not in sys.path:
            sys.path.insert(0, path)
    if staged and os.path.isdir(staged):
        # The snapshot mirrors the master's cwd tree, but user modules may
        # live on sys.path entries BELOW cwd (e.g. the script's own
        # directory, auto-inserted by the interpreter). Map each such
        # entry to its staged twin and give the twins top precedence.
        master_cwd = prep.get("cwd") or ""
        twins = [staged]
        for path in prep.get("sys_path", []):
            if not master_cwd or not path:
                continue
            rel = os.path.relpath(path, master_cwd)
            if rel == "." or rel.startswith(".."):
                continue
            candidate = os.path.normpath(os.path.join(staged, rel))
            if os.path.isdir(candidate):
                twins.append(candidate)
        for candidate in reversed(twins):
            if candidate in sys.path:
                sys.path.remove(candidate)
            sys.path.insert(0, candidate)

    config.init_from(prep["fiber_config"])

    if str(getattr(config.get(), "transport_io", "selector")) == "shm":
        # Same-host rings only engage when both peers share a placement
        # key; a remote worker under the shm engine pays the negotiate
        # timeout per master-bound connection and then runs TCP. Say so
        # once at bootstrap — the operator reading zeroed transport_shm_*
        # counters should not have to rediscover this.
        from fiber_tpu.sched import local_host_key

        master_key = prep.get("master_host_key")
        if master_key is not None and master_key != local_host_key():
            import logging as _logging

            _logging.getLogger("fiber_tpu").info(
                "transport_io=shm but this worker (host key %s) is not "
                "on the master's host (%s); master-bound connections "
                "negotiate down to TCP", local_host_key(), master_key)

    # Telemetry enablement / sampling / span-buffer capacity follow the
    # master's config, adopted above — so one knob governs the whole
    # process tree, and spans this worker records (pool.py task loop)
    # join the trace ids the master stamps into task envelopes. The
    # same refresh arms the continuous monitor sampler and, when
    # profiler_hz > 0, this worker's wall-clock stack sampler (its
    # folded stacks ship back on the result stream — pool.py).
    from fiber_tpu import telemetry

    telemetry.refresh()

    name = prep.get("name", "FiberWorker")
    mp_proc = multiprocessing.current_process()
    mp_proc.name = name  # so %(processName)s in log lines matches
    authkey = prep.get("authkey")
    if authkey:
        mp_proc.authkey = authkey

    flogging.init_logger(config.get(), process_name=name)

    sys_argv = prep.get("sys_argv")
    if sys_argv:
        sys.argv = list(sys_argv)

    # Re-import the user's entry module so functions pickled by reference
    # against __main__ resolve (the stdlib spawn fixups are the canonical
    # implementation of this dance).
    main_path = prep.get("init_main_from_path")
    if (main_path and not os.path.exists(main_path)
            and staged and cwd):
        # The master's script path doesn't exist on this host; its copy in
        # the staged snapshot (rooted at the master's cwd) does.
        rel = os.path.relpath(main_path, cwd)
        candidate = os.path.join(staged, rel)
        if not rel.startswith("..") and os.path.exists(candidate):
            prep["init_main_from_path"] = candidate
    try:
        if "init_main_from_name" in prep:
            mp_spawn._fixup_main_from_name(prep["init_main_from_name"])
        elif "init_main_from_path" in prep:
            mp_spawn._fixup_main_from_path(prep["init_main_from_path"])
    except Exception:
        # A broken/unimportable main is survivable when targets don't
        # actually live there; unpickling will raise if they do.
        pass


def _start_watchdog(conn: socket.socket) -> None:
    def watch() -> None:
        try:
            while True:
                data = conn.recv(1)
                if not data:
                    break
        except OSError:
            pass
        if _worker_done.is_set():
            return
        # Master is gone: mirror the reference watchdog
        # (fiber/spawn.py:33-51) — SIGTERM for a chance at cleanup, then
        # hard exit.
        try:
            os.kill(os.getpid(), signal.SIGTERM)
        except OSError:
            pass
        time.sleep(5.0)
        if not _worker_done.is_set():
            os._exit(1)

    threading.Thread(target=watch, name="fiber-watchdog", daemon=True).start()


def _connect_active(master: str, ident: int) -> socket.socket:
    host, port_s = master.rsplit(":", 1)
    conn = socket.create_connection((host, int(port_s)), timeout=30.0)
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    from fiber_tpu.admin import send_ident

    send_ident(conn, ident)
    conn.settimeout(None)
    return conn


def _listen_passive(port: int, ident: int) -> socket.socket:
    from fiber_tpu.admin import recv_ident, send_ident

    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("", port))
    listener.listen(1)
    while True:
        conn, _ = listener.accept()
        try:
            got = recv_ident(conn)
        except OSError:
            conn.close()
            continue
        if got != ident:
            # Another launch's master found us on the shared fixed port;
            # close so it retries until it reaches its own worker.
            conn.close()
            continue
        listener.close()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        send_ident(conn, ident)  # ack: confirms the master reached *us*
        return conn


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fiber_tpu.worker")
    # The ident is a bearer capability and rides the job ENVIRONMENT
    # (FIBER_LAUNCH_IDENT): on argv it would be world-readable via
    # /proc/<pid>/cmdline on shared worker hosts, letting any local
    # observer race us for the master's pickled process state. The
    # flag remains for tooling but the env is canonical.
    parser.add_argument("--ident", type=int, default=0)
    parser.add_argument("--master", default="")
    parser.add_argument("--listen", type=int, default=0)
    args = parser.parse_args(argv)
    ident = args.ident or int(os.environ.get("FIBER_LAUNCH_IDENT", "0"))
    if not ident:
        parser.error("need FIBER_LAUNCH_IDENT in the environment "
                     "(or --ident)")

    if args.master:
        try:
            conn = _connect_active(args.master, ident)
        except OSError:
            # Master vanished between job creation and our dial-in (e.g.
            # pool shutdown race) — nothing to report to anyone.
            return 1
    elif args.listen:
        conn = _listen_passive(args.listen, ident)
    else:
        parser.error("need --master (active) or --listen (passive)")

    from fiber_tpu import serialization
    from fiber_tpu.framing import recv_frame
    from fiber_tpu import process as fprocess

    prep = serialization.loads(recv_frame(conn))
    _apply_preparation(prep)

    process_obj = serialization.loads(recv_frame(conn))
    fprocess._set_current_process(process_obj)

    _start_watchdog(conn)
    try:
        exitcode = process_obj._bootstrap()
    finally:
        _worker_done.set()
        # Return device-tier HBM promptly: params this worker cached on
        # the chips (store/device_tier.py) should not stay resident until
        # interpreter teardown — the next worker on this host wants the
        # headroom. Peek, never instantiate.
        try:
            from fiber_tpu import store as storemod

            tier = storemod._dtier
            if tier is not None:
                tier.clear()
        except Exception:  # noqa: BLE001 - best-effort cleanup on exit
            pass
    try:
        conn.close()
    except OSError:
        pass
    return exitcode


if __name__ == "__main__":
    sys.exit(main())

"""Backend registry + auto-selection.

Reference parity: fiber/backend.py:24-76 (memoizing factory; auto-selection
sniffs the environment). fiber_tpu ships two backends:

* ``local`` — jobs are subprocess children of this machine;
* ``tpu``   — jobs are processes on TPU-VM pod-slice hosts (with a
  single-host simulation mode for CI).

Selection order: explicit ``name`` argument > ``FIBER_BACKEND`` env >
config ``backend`` key > sniffing (TPU metadata/env) > ``local``.
"""

from __future__ import annotations

import importlib
import os
import threading
import time
from typing import Dict, Optional

from fiber_tpu import config
from fiber_tpu.core import Backend

available_backends = ("local", "tpu")

_BACKEND_MODULES: Dict[str, str] = {
    "local": "fiber_tpu.backends.local",
    "tpu": "fiber_tpu.backends.tpu",
}

_backends: Dict[str, Backend] = {}
# Sniffed selections that probed unavailable -> monotonic deadline after
# which the probe is retried (agents may simply not be up YET on a real
# pod; a single transient failure must not pin a long-lived driver to
# the local backend forever). Until the deadline, later get_backend()
# calls skip the probe cost. Explicit selection (FIBER_BACKEND / config
# / name argument) bypasses this; reset_backends() clears it.
_failed_sniffs: Dict[str, float] = {}
_SNIFF_RETRY_S = 60.0
_lock = threading.Lock()
_build_locks: Dict[str, threading.Lock] = {}


def _on_tpu_pod() -> bool:
    """True when running on a TPU-VM host of a pod slice."""
    if os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "TPU_WORKER_ID"
    ):
        return True
    return bool(config.get().tpu_name or config.get().tpu_hosts)


def auto_select_backend() -> str:
    name, _ = _select_backend()
    return name


def _select_backend():
    """Returns (name, explicit). Explicit selections (env var / config key)
    must not be silently substituted; only sniffed ones may fall back."""
    env = os.environ.get("FIBER_BACKEND")
    if env:
        return env, True
    cfg_backend = config.get().backend
    if cfg_backend:
        return cfg_backend, True
    if _on_tpu_pod():
        return "tpu", False
    return "local", False


def get_backend(name: Optional[str] = None) -> Backend:
    """Memoized backend factory (reference: fiber/backend.py:56-76).

    A backend requested explicitly (``name`` argument, ``FIBER_BACKEND``
    env, or the config ``backend`` key) raises if it can't be loaded; only
    a *sniffed* selection falls back to ``local`` with a warning, so
    running on exotic hosts never hard-fails process creation.
    """
    sniffed = False
    if name is None:
        name, explicit = _select_backend()
        sniffed = not explicit
        if sniffed:
            deadline = _failed_sniffs.get(name)
            if deadline is not None:
                if time.monotonic() < deadline:
                    return get_backend("local")
                _failed_sniffs.pop(name, None)  # retry the probe
    try:
        with _lock:
            backend = _backends.get(name)
            if backend is not None:
                return backend
            # Per-name build lock so construction and (for sniffed
            # selections) the reachability probe — up to 2s of connect
            # timeout per host — never run under the registry lock:
            # concurrent get_backend("local") calls must not stall
            # behind a slow tpu probe.
            build_lock = _build_locks.setdefault(name, threading.Lock())
        with build_lock:
            with _lock:
                backend = _backends.get(name)
                if backend is not None:
                    return backend
            modname = _BACKEND_MODULES.get(name)
            if modname is None:
                raise ValueError(
                    f"unknown backend {name!r}; "
                    f"available: {available_backends}"
                )
            module = importlib.import_module(modname)
            backend = module.make_backend()
            if sniffed:
                # A sniffed selection must actually work before it is
                # memoized: TPU-shaped environments exist where no
                # host agent runs (e.g. a tunnel plugin injecting
                # TPU_WORKER_HOSTNAMES into every interpreter), and
                # accepting the backend there turns every Process
                # start into a connection-refused retry loop. An
                # explicit selection skips the probe — the operator
                # said tpu, so failing loudly at create_job is right.
                probe = getattr(backend, "probe_available", None)
                if probe is not None:
                    probe()
            with _lock:
                _backends[name] = backend
            return backend
    except Exception:
        if not sniffed or name == "local":
            raise
        from fiber_tpu.utils.logging import get_logger

        get_logger().warning(
            "auto-selected backend %r unavailable; falling back to 'local'",
            name, exc_info=True,
        )
        _failed_sniffs[name] = time.monotonic() + _SNIFF_RETRY_S
        return get_backend("local")


def reset_backends() -> None:
    """Drop memoized backends (tests)."""
    with _lock:
        _backends.clear()
    _failed_sniffs.clear()

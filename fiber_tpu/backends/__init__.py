"""Backend registry + auto-selection.

Reference parity: fiber/backend.py:24-76 (memoizing factory; auto-selection
sniffs the environment). fiber_tpu ships two backends:

* ``local`` — jobs are subprocess children of this machine;
* ``tpu``   — jobs are processes on TPU-VM pod-slice hosts (with a
  single-host simulation mode for CI).

Selection order: explicit ``name`` argument > ``FIBER_BACKEND`` env >
config ``backend`` key > sniffing (TPU metadata/env) > ``local``.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Dict, Optional

from fiber_tpu import config
from fiber_tpu.core import Backend

available_backends = ("local", "tpu")

_BACKEND_MODULES: Dict[str, str] = {
    "local": "fiber_tpu.backends.local",
    "tpu": "fiber_tpu.backends.tpu",
}

_backends: Dict[str, Backend] = {}
_lock = threading.Lock()


def _on_tpu_pod() -> bool:
    """True when running on a TPU-VM host of a pod slice."""
    if os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "TPU_WORKER_ID"
    ):
        return True
    return bool(config.get().tpu_name or config.get().tpu_hosts)


def auto_select_backend() -> str:
    name, _ = _select_backend()
    return name


def _select_backend():
    """Returns (name, explicit). Explicit selections (env var / config key)
    must not be silently substituted; only sniffed ones may fall back."""
    env = os.environ.get("FIBER_BACKEND")
    if env:
        return env, True
    cfg_backend = config.get().backend
    if cfg_backend:
        return cfg_backend, True
    if _on_tpu_pod():
        return "tpu", False
    return "local", False


def get_backend(name: Optional[str] = None) -> Backend:
    """Memoized backend factory (reference: fiber/backend.py:56-76).

    A backend requested explicitly (``name`` argument, ``FIBER_BACKEND``
    env, or the config ``backend`` key) raises if it can't be loaded; only
    a *sniffed* selection falls back to ``local`` with a warning, so
    running on exotic hosts never hard-fails process creation.
    """
    sniffed = False
    if name is None:
        name, explicit = _select_backend()
        sniffed = not explicit
    try:
        with _lock:
            backend = _backends.get(name)
            if backend is None:
                modname = _BACKEND_MODULES.get(name)
                if modname is None:
                    raise ValueError(
                        f"unknown backend {name!r}; "
                        f"available: {available_backends}"
                    )
                module = importlib.import_module(modname)
                backend = module.make_backend()
                _backends[name] = backend
            return backend
    except Exception:
        if not sniffed or name == "local":
            raise
        from fiber_tpu.utils.logging import get_logger

        get_logger().warning(
            "auto-selected backend %r unavailable; falling back to 'local'",
            name, exc_info=True,
        )
        return get_backend("local")


def reset_backends() -> None:
    """Drop memoized backends (tests)."""
    with _lock:
        _backends.clear()

"""TPU backend: jobs are processes on the TPU pod slice's VM hosts.

Reference parity: this fills the slot of fiber/kubernetes_backend.py +
docker_backend.py — one driver per cluster substrate — except the substrate
is a TPU pod slice. Placement model (SURVEY.md §2 parallelism table): one
framework process per TPU-VM host drives that host's local devices; jobs
round-robin across hosts unless ``JobSpec.host_hint`` pins one.

Host discovery, in priority order:

1. ``tpu_hosts`` config / ``FIBER_TPU_HOSTS`` env: ``"ip[:port],..."`` —
   explicit list (also how CI points at a simulated localhost cluster);
2. ``sim:N``: spawn N local host agents (single-machine simulation of an
   N-host slice, the Docker-backend role in the reference's test matrix);
3. ``TPU_WORKER_HOSTNAMES`` env (set on real TPU-VMs by the platform).

Each host runs a fiber_tpu host agent (fiber_tpu/host_agent.py); this
backend is a thin RPC client over authenticated TCP.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import weakref
from multiprocessing.connection import Client
from typing import Dict, List, Optional, Tuple

from fiber_tpu import config
from fiber_tpu.core import Backend, Job, JobSpec, ProcessStatus
from fiber_tpu.host_agent import DEFAULT_AGENT_PORT, cluster_authkey
from fiber_tpu.utils.logging import get_logger
from fiber_tpu.utils.net import find_listen_address

logger = get_logger()


class AgentClient:
    """One authenticated connection per host agent, lock-serialized."""

    def __init__(self, host: str, port: int) -> None:
        self.host = host
        self.port = port
        self._conn = None
        self._lock = threading.Lock()

    def call(self, op: str, *args):
        with self._lock:
            try:
                if self._conn is None:
                    self._conn = Client((self.host, self.port),
                                        authkey=cluster_authkey())
                self._conn.send((op, *args))
                ok, payload = self._conn.recv()
            except (OSError, EOFError):
                # A failed round-trip poisons the stream (the next recv
                # could read this call's late reply); drop the connection
                # so the next call redials cleanly.
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
                raise
        if not ok:
            raise RuntimeError(
                f"agent {self.host}:{self.port} error: {payload}"
            )
        return payload

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None


def _parse_hosts(spec: str,
                 default_port: int = 0) -> List[Tuple[str, int]]:
    """Parse ``ip[,ip:port,...]``; portless entries take
    ``default_port`` (the CLI passes the operator's --port so started
    and probed ports can never disagree) or DEFAULT_AGENT_PORT."""
    hosts = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            host, port_s = part.rsplit(":", 1)
            if not host or not port_s.isdigit():
                raise ValueError(
                    f"malformed host entry {part!r} (want ip or ip:port)"
                )
            hosts.append((host, int(port_s)))
        else:
            hosts.append((part, default_port or DEFAULT_AGENT_PORT))
    return hosts


class TpuBackend(Backend):
    name = "tpu"
    # Class-level defaults: shutdown_sim_cluster is atexit-registered
    # before the health plane is constructed, so a partial __init__
    # (sim agent failed to boot) must still shut down cleanly.
    _prober = None
    _detector = None

    def __init__(self) -> None:
        cfg = config.get()
        self._sim_agents: List[subprocess.Popen] = []
        hosts_spec = cfg.tpu_hosts or os.environ.get("FIBER_TPU_HOSTS", "")
        if hosts_spec.startswith("sim:"):
            n = int(hosts_spec.split(":", 1)[1])
            self._hosts = self._start_sim_cluster(n)
        elif hosts_spec:
            self._hosts = _parse_hosts(hosts_spec)
        else:
            names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
            if not names:
                raise RuntimeError(
                    "tpu backend: no hosts (set tpu_hosts config, "
                    "FIBER_TPU_HOSTS, or run on a pod slice with "
                    "TPU_WORKER_HOSTNAMES)"
                )
            self._hosts = _parse_hosts(names)
        if not self._hosts:
            raise RuntimeError("tpu backend: empty host list")
        self._agents: Dict[Tuple[str, int], AgentClient] = {}
        self._rr = 0
        self._lock = threading.Lock()
        self._jobs: List[Job] = []
        # Per-host health plane (fiber_tpu/health.py): the agent RPC
        # channel doubles as its heartbeat — a prober thread pings every
        # host each heartbeat_interval and any successful RPC beats the
        # detector. A host silent past suspect_timeout is suspected
        # (skipped by placement) but NOT permanent: agents restart, and
        # a later successful ping revives the host. The breaker
        # additionally blacklists hosts whose spawns keep FAILING even
        # though the agent answers (bad image, full disk) — backoff +
        # jitter, reset on the first spawn that succeeds.
        cfg = config.get()
        from fiber_tpu.health import (
            CircuitBreaker, FailureDetector, Heartbeater,
        )

        self._host_breaker = CircuitBreaker(
            fail_threshold=int(cfg.spawn_breaker_threshold),
            base_backoff=float(cfg.spawn_breaker_backoff),
            max_backoff=float(cfg.spawn_breaker_backoff_max),
        )
        self._detector = None
        self._prober = None
        if float(cfg.heartbeat_interval or 0) > 0 \
                and float(cfg.suspect_timeout or 0) > 0:
            self._detector = FailureDetector(
                float(cfg.suspect_timeout), self._on_host_suspect,
                permanent=False, name="fiber-agent-detector",
                on_revive=self._on_host_revive,
            ).start()
            self._prober = Heartbeater(
                self._probe_hosts, float(cfg.heartbeat_interval),
                name="fiber-agent-prober",
            ).start()
        # Policy-plane replication driver (telemetry/policy.py
        # replicate_and_boost): lets a heartbeat_age / throughput_drop
        # anomaly pre-emptively copy precious digests BEFORE the
        # failure detector declares anyone suspect. Weakref so a
        # registered driver never pins a dead backend alive.
        from fiber_tpu.store.replicate import REPLICATOR

        wself = weakref.ref(self)

        def _drive(reason: str) -> int:
            b = wself()
            return (b._replicate_precious(reason=reason)
                    if b is not None else 0)

        REPLICATOR.register_driver(_drive)
        logger.info("tpu backend: %d host(s): %s", len(self._hosts),
                    self._hosts)

    # ------------------------------------------------------------------
    def _start_sim_cluster(self, n: int) -> List[Tuple[str, int]]:
        """N local agents simulating an N-host pod slice (loopback-only)."""
        import atexit

        # Registered before any spawn so a partial startup failure still
        # reaps the agents that did come up.
        atexit.register(self.shutdown_sim_cluster)
        from fiber_tpu.utils.misc import package_pythonpath

        # Agents must import fiber_tpu no matter where the user's script
        # runs from (a bare `-m fiber_tpu.host_agent` only works when cwd
        # happens to contain the package).
        env = dict(os.environ, PYTHONPATH=package_pythonpath())
        # Each sim agent models a whole pod HOST, so it advertises a
        # host-sized core capacity regardless of this machine's physical
        # count (the agents share cores, like the reference's Docker
        # containers): otherwise packed jobs (cpu_per_job>1) would be
        # unspawnable on small CI machines and the pool would retry
        # forever. Override with FIBER_SIM_HOST_CORES.
        sim_cores = int(os.environ.get("FIBER_SIM_HOST_CORES", 0)) \
            or max(8, os.cpu_count() or 1)
        hosts = []
        for _ in range(n):
            proc = subprocess.Popen(
                [sys.executable, "-m", "fiber_tpu.host_agent",
                 "--port", "0", "--announce", "--bind", "127.0.0.1",
                 "--cores", str(sim_cores)],
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                env=env,
            )
            self._sim_agents.append(proc)
            line = proc.stdout.readline().strip()
            if not line.startswith("AGENT_PORT"):
                self.shutdown_sim_cluster()
                raise RuntimeError(
                    f"sim agent failed to start (got {line!r})"
                )
            port = int(line.split()[1])
            hosts.append(("127.0.0.1", port))
        return hosts

    def _probe_hosts(self) -> None:
        """One ping round (runs on the prober thread each interval). A
        host that answers ANY rpc is alive; a failed ping is left to the
        detector's deadline — one lost packet must not mark a host."""
        for host in list(self._hosts):
            try:
                self._agent(host).call("ping")
            except Exception:
                continue  # silence accrues; the detector owns the call
            detector = self._detector
            if detector is not None:
                detector.beat(host)

    def _on_host_suspect(self, host) -> None:
        logger.warning(
            "health: host agent %s:%s silent past suspect_timeout; "
            "suspending placement on it (revives on next answer)",
            host[0], host[1])
        # Host-loss tolerance (docs/robustness.md): precious digests —
        # ledger-journaled result payloads and active broadcasts — gain
        # a replica on a healthy host NOW, while "suspect" may still
        # become "dead". Off the detector thread: a slow agent push must
        # never delay further declarations.
        try:
            if bool(config.get().store_replicate):
                threading.Thread(
                    target=self._replicate_precious, args=(host,),
                    name="fiber-store-replicate", daemon=True,
                ).start()
        except Exception:  # noqa: BLE001 - durability bonus only
            logger.warning("store: replication kickoff failed",
                           exc_info=True)

    def _on_host_revive(self, host) -> None:
        """A declared-suspect host answered again: clear its spawn
        breaker so placement resumes immediately — an open period earned
        while the host was down must not park a recovered host."""
        self._host_breaker.record_success(host)
        logger.info("health: host %s:%s revived; spawn breaker cleared",
                    host[0], host[1])

    def _replicate_precious(self, suspect=None,
                            reason: str = "suspect") -> int:
        """Copy precious digests to healthy hosts. Two triggers share
        this routine: a declared-suspect host (``suspect`` excluded
        from targets) and the policy plane's pre-emptive drive on a
        heartbeat_age / throughput_drop anomaly (no suspect yet —
        every healthy host is a target)."""
        from fiber_tpu import store as storemod
        from fiber_tpu.store.replicate import REPLICATOR

        targets = [h for h in self._hosts
                   if h != suspect and self._host_healthy(h)]
        local = storemod.local_store()
        key = (f"{suspect[0]}:{suspect[1]}" if suspect is not None
               else str(reason))
        return REPLICATOR.replicate_for_suspect(
            key, targets,
            get_bytes=local.get_bytes,
            host_has=lambda h, d: self._agent(h).call("store_has", d),
            host_put=lambda h, d, data: self._agent(h).call(
                "store_put", d, data),
        )

    def host_health(self) -> Dict[str, str]:
        """Operator-facing snapshot: host -> 'ok'|'suspect'|'open'."""
        out = {}
        for host in self._hosts:
            key = f"{host[0]}:{host[1]}"
            if self._detector is not None \
                    and self._detector.is_suspect(host):
                out[key] = "suspect"
            elif not self._host_breaker.allow(host):
                out[key] = "open"
            else:
                out[key] = "ok"
        return out

    def shutdown_sim_cluster(self) -> None:
        if self._prober is not None:
            self._prober.stop()
        if self._detector is not None:
            self._detector.stop()
        for proc in self._sim_agents:
            if proc.poll() is None:
                proc.terminate()
        for proc in self._sim_agents:
            try:
                proc.wait(5)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._sim_agents = []

    def probe_available(self) -> None:
        """Raise unless at least one host agent is reachable. Called by
        the registry for *sniffed* (non-explicit) selections only: a
        TPU-shaped environment without running agents (e.g. a PJRT
        tunnel plugin injecting TPU_WORKER_HOSTNAMES) must fall back to
        the local backend instead of turning every job launch into a
        connection-refused retry loop. Sim clusters spawn their own
        agents in __init__, so they always pass."""
        import socket as pysocket
        from concurrent.futures import ThreadPoolExecutor

        def try_one(host_port):
            host, port = host_port
            try:
                with pysocket.create_connection((host, port), timeout=2.0):
                    return None
            except OSError as exc:
                return f"{host}:{port}: {exc}"

        # Concurrent probes: the failure path costs ~one connect timeout
        # total, not 2s x hosts (first success wins either way).
        with ThreadPoolExecutor(max_workers=min(16, len(self._hosts))) \
                as pool:
            errors = [e for e in pool.map(try_one, self._hosts)
                      if e is not None]
        if len(errors) < len(self._hosts):
            return  # at least one agent answered
        raise RuntimeError(
            "no fiber-tpu host agent reachable "
            f"({'; '.join(errors[:4])}) — start agents with "
            "`fiber-tpu up` / `fiber-tpu agent`, or set "
            "FIBER_BACKEND=local"
        )

    def _agent(self, host: Tuple[str, int]) -> AgentClient:
        with self._lock:
            client = self._agents.get(host)
            if client is None:
                client = AgentClient(*host)
                self._agents[host] = client
            return client

    def _host_healthy(self, host: Tuple[str, int]) -> bool:
        if self._detector is not None and self._detector.is_suspect(host):
            return False
        return self._host_breaker.allow(host)

    def _pick_host(self, spec: JobSpec) -> Tuple[str, int]:
        if spec.host_hint:
            for host in self._hosts:
                if host[0] == spec.host_hint or \
                        f"{host[0]}:{host[1]}" == spec.host_hint:
                    return host  # a pin overrides health (ring ranks
                    # etc. are placement-significant; fail loudly there)
            raise ValueError(f"host_hint {spec.host_hint!r} not in cluster")
        # Round-robin over HEALTHY hosts: suspected agents and
        # open-breaker targets are skipped. With every host unhealthy,
        # fall through to plain round-robin — a wrong placement beats a
        # placement deadlock, and the attempt itself is the breaker's
        # half-open trial.
        with self._lock:
            n = len(self._hosts)
            for step in range(1, n + 1):
                cand = self._hosts[(self._rr + step) % n]
                if self._host_healthy(cand):
                    self._rr = (self._rr + step) % n
                    return cand
            host = self._hosts[self._rr % n]
            self._rr += 1
        return host

    # ------------------------------------------------------------------
    def create_job(self, job_spec: JobSpec) -> Job:
        host = self._pick_host(job_spec)
        agent = self._agent(host)
        env = dict(job_spec.env or {})
        # Placement identity for the scheduler plane
        # (docs/scheduling.md): only this backend knows which host the
        # job landed on, so it stamps the key — the same "ip:port" the
        # host tables (host_health/store_stats/locate_object) use —
        # into the job env; pool workers echo it in "ready" frames.
        env.setdefault("FIBER_HOST_KEY", f"{host[0]}:{host[1]}")
        # Resource hints become agent-enforced limits (affinity + rlimit),
        # the reference's k8s/docker limit role. Device jobs keep all host
        # cores — pinning a jax host process to cpu_per_job cores would
        # starve its runtime threads.
        limits = {}
        if job_spec.cpu and not (job_spec.tpu or job_spec.gpu):
            limits["cpu"] = int(job_spec.cpu)
        if job_spec.mem:
            limits["mem"] = int(job_spec.mem)
        try:
            pid, log_path = agent.call(
                "spawn", job_spec.command, job_spec.cwd, env,
                job_spec.name, limits,
            )
        except Exception:
            if self._host_breaker.record_failure(host):
                logger.warning(
                    "health: spawn breaker OPEN for host %s:%s after "
                    "repeated failures; placement backs off it",
                    host[0], host[1])
            raise
        self._host_breaker.record_success(host)
        if self._detector is not None:
            self._detector.beat(host)  # an answering agent is alive
        job = Job({"host": host, "pid": pid, "log": log_path},
                  jid=f"{host[0]}:{host[1]}/{pid}")
        job.host = host[0]
        with self._lock:
            self._jobs.append(job)
        return job

    def _agent_for_job(self, job: Job) -> Tuple[AgentClient, int]:
        data = job.data
        return self._agent(data["host"]), data["pid"]

    def get_job_status(self, job: Job) -> ProcessStatus:
        agent, pid = self._agent_for_job(job)
        rc = agent.call("poll", pid)
        return ProcessStatus.STARTED if rc is None else ProcessStatus.STOPPED

    def get_job_logs(self, job: Job) -> str:
        agent, pid = self._agent_for_job(job)
        return agent.call("logs", pid)

    def wait_for_job(self, job: Job, timeout: Optional[float]) -> Optional[int]:
        agent, pid = self._agent_for_job(job)
        # Short bounded agent-side waits so one join never pins the shared
        # agent channel (other RPCs to this host interleave between slices).
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            slice_ = 0.5
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return agent.call("poll", pid)
                slice_ = min(slice_, remaining)
            rc = agent.call("wait", pid, slice_)
            if rc is not None:
                return rc

    def terminate_job(self, job: Job) -> None:
        agent, pid = self._agent_for_job(job)
        agent.call("signal", pid, int(signal.SIGTERM))

    def kill_job(self, job: Job) -> None:
        agent, pid = self._agent_for_job(job)
        agent.call("signal", pid, int(signal.SIGKILL))

    def _resolved_hosts_spec(self) -> str:
        return ",".join(f"{h}:{p}" for h, p in self._hosts)

    def child_env(self) -> Dict[str, str]:
        # Children must dial THIS cluster's agents — never re-expand a
        # "sim:N" spec into a private cluster of their own.
        return {
            "FIBER_TPU_HOSTS": self._resolved_hosts_spec(),
            "FIBER_BACKEND": "tpu",
        }

    def child_config(self) -> Dict[str, str]:
        return {"tpu_hosts": self._resolved_hosts_spec(), "backend": "tpu"}

    def default_pool_size(self) -> int:
        # Pool treats `processes` as the TOTAL sub-worker count and packs
        # cpu_per_job of them per spawned job — so the natural default is
        # one job per host × its packing factor (fills every host).
        from fiber_tpu import config

        cpu_per_job = max(1, int(config.get().cpu_per_job))
        return len(self._hosts) * cpu_per_job

    def get_listen_addr(self) -> Tuple[str, int, str]:
        if all(h[0] in ("127.0.0.1", "localhost") for h in self._hosts):
            return ("127.0.0.1", 0, "lo")
        ip = find_listen_address() or "127.0.0.1"
        return (ip, 0, "eth0")

    def list_jobs(self) -> List[Job]:
        with self._lock:
            jobs = list(self._jobs)
        live = []
        finished = set()
        for job in jobs:
            try:
                if self.get_job_status(job) == ProcessStatus.STARTED:
                    live.append(job)
                else:
                    finished.add(id(job))
            except Exception:
                pass  # transient RPC failure: keep tracking the job
        # Prune only jobs *observed finished* — jobs created concurrently
        # with the polling above (or whose poll failed) stay tracked.
        with self._lock:
            self._jobs = [j for j in self._jobs if id(j) not in finished]
        return live

    # -- file staging (fiber cp parity) --------------------------------
    def put_file(self, path: str, data: bytes, hosts=None,
                 mode: int = 0o644) -> None:
        for host in (hosts or self._hosts):
            self._agent(host).call("put_file", path, data, mode)

    def stage_code(self, digest: str, files) -> bool:
        """Push the workspace snapshot to every agent, content-addressed:
        a host that already has ``code/<digest>/.fiber-complete`` is
        skipped, so repeat spawns and repeat runs cost one RPC per host."""
        rel_root = f"code/{digest}"
        marker = f"{rel_root}/.fiber-complete"
        for host in self._hosts:
            agent = self._agent(host)
            try:
                agent.call("get_file", marker)
                continue  # this host already has the snapshot
            except Exception:
                pass
            for rel, data, mode in files:
                agent.call("put_file", f"{rel_root}/{rel}", data, mode)
            # Written last: a crashed staging run is retried, not trusted.
            agent.call("put_file", marker, b"ok", 0o644)
        return True

    def get_file(self, path: str, host=None) -> bytes:
        host = host or self._hosts[0]
        return self._agent(host).call("get_file", path)

    # -- object store (docs/objectstore.md) ----------------------------
    def put_object(self, digest: str, data: bytes, hosts=None) -> int:
        """Prestage one serialized store object into every host's cache
        tier (skipping hosts that already have it): workers there
        resolve the ref from local disk instead of dialing the owner —
        the explicit broadcast path for very hot objects. Returns the
        number of hosts that received bytes."""
        pushed = 0
        for host in (hosts or self._hosts):
            agent = self._agent(host)
            try:
                if agent.call("store_has", digest):
                    continue
            except Exception:
                pass  # can't tell; push anyway
            agent.call("store_put", digest, bytes(data))
            pushed += 1
        return pushed

    def host_suspect(self, host_key: str) -> bool:
        """Scheduler-plane health input: True when the keyed host is
        currently suspect (silent past suspect_timeout) or its spawn
        breaker is open — the pool's handout gate parks its workers'
        requests while healthier peers exist (docs/scheduling.md)."""
        host, _, port_s = host_key.rpartition(":")
        if not host or not port_s.isdigit():
            return False
        key = (host, int(port_s))
        if self._detector is not None and self._detector.is_suspect(key):
            return True
        return not self._host_breaker.allow(key)

    def locate_object(self, digest: str) -> List[str]:
        """Hosts whose object cache already holds ``digest`` (agent
        ``store_has``), keyed like :meth:`host_health` — the scheduler's
        placement probe for prestaged broadcasts. Best-effort: an
        unreachable agent just drops out of the answer."""
        out: List[str] = []
        for host in self._hosts:
            try:
                if self._agent(host).call("store_has", digest):
                    out.append(f"{host[0]}:{host[1]}")
            except Exception:  # noqa: BLE001 - locality is optional
                continue
        return out

    def fetch_object(self, digest: str) -> Optional[bytes]:
        """Pull one store object from whichever host cache still holds
        it (agent ``store_has`` + ``store_get``), digest-verified — the
        recovery path of ``fiber-tpu resume``: a journaled result whose
        master-disk copy is gone is fetched from the per-host stores
        instead of being recomputed. None when no host has it."""
        import hashlib as _hashlib

        for host in self._hosts:
            try:
                if not self._agent(host).call("store_has", digest):
                    continue
                data = bytes(self._agent(host).call("store_get", digest))
                if _hashlib.sha256(data).hexdigest() == digest:
                    return data
            except Exception:  # noqa: BLE001 - try the next host
                continue
        return None

    def store_stats(self) -> Dict[str, dict]:
        """Per-host object-cache counters, the store-plane sibling of
        :meth:`host_health` (same operator surface, same host keys)."""
        out: Dict[str, dict] = {}
        for host in self._hosts:
            key = f"{host[0]}:{host[1]}"
            try:
                out[key] = self._agent(host).call("store_stats")
            except Exception as exc:  # noqa: BLE001 - operator snapshot
                out[key] = {"error": repr(exc)}
        return out

    # -- telemetry (docs/observability.md) -----------------------------
    def collect_postmortem(self, host_key: str) -> Optional[dict]:
        """One host's black box (the agent's ``postmortem`` op): flight
        events, stack dump, and any crash bundles workers there flushed.
        ``host_key`` is the scheduler-plane ``ip:port`` key workers
        self-report; None when it doesn't name a known agent."""
        host, _, port_s = host_key.rpartition(":")
        if not host or not port_s.isdigit():
            return None
        return self._agent((host, int(port_s))).call("postmortem")

    def cluster_metrics(self) -> Dict[str, dict]:
        """Per-host telemetry snapshots keyed like :meth:`host_health` /
        :meth:`store_stats` (one operator surface), via each agent's
        ``telemetry_snapshot`` op. An unreachable host contributes an
        ``error`` entry instead of failing the sweep."""
        return self._sweep("telemetry_snapshot")

    def cluster_timeseries(self, history: int = 120) -> Dict[str, dict]:
        """Per-host continuous-monitor snapshots (time-series rings,
        derived rates, anomaly-watchdog state) via each agent's
        ``monitor_snapshot`` op — the data plane of ``fiber-tpu top``,
        keyed like :meth:`cluster_metrics`."""
        return self._sweep("monitor_snapshot", int(history))

    def collect_profiles(self, seconds: float = 1.0,
                         hz: float = 97.0) -> Dict[str, dict]:
        """Per-host on-demand sampling profiles (agent ``profile_dump``
        op): each agent samples its own process for ``seconds`` at
        ``hz`` and returns flamegraph folded stacks. Same host keys as
        the other sweeps; an unreachable host contributes ``error``."""
        return self._sweep("profile_dump", float(seconds), float(hz))

    def cluster_devices(self) -> Dict[str, dict]:
        """Per-host device-telemetry snapshots (agent
        ``device_snapshot`` op): transfer bytes+seconds, compile
        count+seconds, HBM / live-array stats (honest None on CPU
        hosts), recompile state and last MFU — the data plane of
        ``fiber-tpu devices``, keyed like :meth:`cluster_metrics`
        (docs/observability.md "Device telemetry")."""
        return self._sweep("device_snapshot")

    def cluster_costs(self) -> Dict[str, dict]:
        """Per-host accounting snapshots (agent ``cost_snapshot`` op):
        each host process's billing-key -> cost-vector table — the data
        plane of ``fiber-tpu top --costs``, keyed like
        :meth:`cluster_metrics` (docs/observability.md "Resource
        accounting")."""
        return self._sweep("cost_snapshot")

    def _sweep(self, op: str, *args) -> Dict[str, dict]:
        """One telemetry RPC against every host, error-isolating — the
        shared shape of cluster_metrics / cluster_timeseries /
        collect_profiles."""
        out: Dict[str, dict] = {}
        for host in self._hosts:
            key = f"{host[0]}:{host[1]}"
            try:
                out[key] = self._agent(host).call(op, *args)
            except Exception as exc:  # noqa: BLE001 - operator snapshot
                out[key] = {"error": repr(exc)}
        return out


def make_backend() -> TpuBackend:
    return TpuBackend()

"""Local backend: one job == one subprocess child of this machine.

Reference parity: fiber/local_backend.py (create_job via subprocess.Popen,
status from poll(), listen address 127.0.0.1). This is both the development
backend and the building block the TPU backend composes per-host.
"""

from __future__ import annotations

import subprocess
import threading
import weakref
from typing import List, Optional, Tuple

from fiber_tpu.core import Backend, Job, JobSpec, ProcessStatus
from fiber_tpu.testing import chaos
from fiber_tpu.utils.logging import get_logger

logger = get_logger()


class LocalBackend(Backend):
    name = "local"

    def __init__(self) -> None:
        # Weak set so finished/GC'd Job handles don't pin Popen objects.
        self._jobs: "weakref.WeakSet[Job]" = weakref.WeakSet()
        self._lock = threading.Lock()

    def create_job(self, job_spec: JobSpec) -> Job:
        import os

        plan = chaos._plan
        if plan is not None:
            # Induced spawn-failure burst (budgeted): models the backend
            # refusing job creation — exactly what the pool's breaker +
            # escalation layers must absorb.
            plan.fail_point("local_spawn")
        env = None
        if job_spec.env:
            env = dict(os.environ)
            env.update(job_spec.env)
        proc = subprocess.Popen(
            job_spec.command,
            cwd=job_spec.cwd,
            env=env,
            start_new_session=False,
        )
        job = Job(proc, proc.pid)
        job.host = "127.0.0.1"
        with self._lock:
            self._jobs.add(job)
        logger.debug("local backend created job pid=%s", proc.pid)
        return job

    def get_job_status(self, job: Job) -> ProcessStatus:
        proc: subprocess.Popen = job.data
        if proc.poll() is None:
            return ProcessStatus.STARTED
        return ProcessStatus.STOPPED

    def get_job_logs(self, job: Job) -> str:
        return ""

    def wait_for_job(self, job: Job, timeout: Optional[float]) -> Optional[int]:
        proc: subprocess.Popen = job.data
        try:
            return proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def terminate_job(self, job: Job) -> None:
        proc: subprocess.Popen = job.data
        if proc.poll() is None:
            proc.terminate()

    def kill_job(self, job: Job) -> None:
        proc: subprocess.Popen = job.data
        if proc.poll() is None:
            proc.kill()

    def get_listen_addr(self) -> Tuple[str, int, str]:
        return ("127.0.0.1", 0, "lo")

    def cluster_metrics(self) -> dict:
        """Telemetry snapshot keyed like the tpu backend's per-host map
        — the local backend's one 'host' is this process (same shape,
        so tooling renders either backend identically)."""
        from fiber_tpu import telemetry

        return {"local": telemetry.snapshot()}

    def cluster_timeseries(self, history: int = 120) -> dict:
        """Continuous-monitor snapshot, same one-host shape as
        :meth:`cluster_metrics` (docs/observability.md)."""
        from fiber_tpu.telemetry.monitor import monitor_payload
        from fiber_tpu.telemetry.timeseries import TIMESERIES

        if TIMESERIES.enabled:
            TIMESERIES.sample_once()
        return {"local": monitor_payload(history=int(history))}

    def cluster_devices(self) -> dict:
        """Device-telemetry snapshot, same one-host shape as
        :meth:`cluster_metrics` (docs/observability.md "Device
        telemetry")."""
        from fiber_tpu.telemetry.device import DEVICE

        DEVICE.update_gauges()
        return {"local": DEVICE.snapshot()}

    def cluster_costs(self) -> dict:
        """Accounting snapshot, same one-host shape as
        :meth:`cluster_metrics` (docs/observability.md "Resource
        accounting")."""
        from fiber_tpu.telemetry.accounting import COSTS

        return {"local": COSTS.snapshot()}

    def collect_profiles(self, seconds: float = 1.0,
                         hz: float = 97.0) -> dict:
        """On-demand sampling profile of this process, same one-host
        shape as the tpu backend's agent sweep."""
        import os

        from fiber_tpu.telemetry import tracing
        from fiber_tpu.telemetry.profiler import PROFILER

        return {"local": {
            "host": tracing.host_id(),
            "pid": os.getpid(),
            "hz": float(hz),
            "folded": PROFILER.sample_for(seconds, hz),
            "standing": PROFILER.snapshot(),
        }}

    def list_jobs(self) -> List[Job]:
        with self._lock:
            return [
                j
                for j in list(self._jobs)
                if j.data.poll() is None
            ]


def make_backend() -> LocalBackend:
    return LocalBackend()

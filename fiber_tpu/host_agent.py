"""Host agent: the per-host daemon the TPU backend drives.

On a real pod slice one agent runs on every TPU-VM host
(``python -m fiber_tpu.host_agent --port 7060``, e.g. from the ``fiber-tpu
up`` CLI or a startup script); the master's ``tpu`` backend dials each
agent and asks it to spawn/poll/wait/signal framework processes and to
stage files. This replaces the reference's cluster drivers (Docker daemon /
K8s API — fiber/docker_backend.py, fiber/kubernetes_backend.py) with a
self-contained, zero-dependency control plane over authenticated TCP
(multiprocessing.connection with HMAC auth, like the managers plane).

The same agent binary doubles as the **simulated cluster** for CI: N agents
on localhost behave exactly like N pod hosts (reference test strategy §4 —
multi-node simulated on one machine).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from multiprocessing.connection import Listener
from typing import Any, Dict, Optional, Tuple

from fiber_tpu.utils.serve import serve_authenticated

DEFAULT_AGENT_PORT = 7060


def cluster_authkey() -> bytes:
    """Shared-secret for agent auth: FIBER_CLUSTER_KEY env or a
    well-known development default (one source: fiber_tpu.auth)."""
    from fiber_tpu.auth import cluster_key

    return cluster_key()


class _AgentJob:
    def __init__(self, proc: subprocess.Popen, log_path: str,
                 cpu: int = 0) -> None:
        self.proc = proc
        self.log_path = log_path
        self.cpu = cpu  # cores reserved (0 = unlimited)


#: Completed-job records kept before the oldest are pruned (their logs too).
MAX_FINISHED_JOBS = 1024


def default_staging_root() -> str:
    """Where file-staging ops may read/write unless ``--unrestricted-files``:
    FIBER_AGENT_STAGING or ~/.fiber_tpu/staging."""
    return os.environ.get(
        "FIBER_AGENT_STAGING",
        os.path.join(os.path.expanduser("~"), ".fiber_tpu", "staging"),
    )


class HostAgent:
    """Serves spawn/poll/wait/logs/signal/put_file requests."""

    def __init__(self, port: int, authkey: Optional[bytes] = None,
                 bind: str = "127.0.0.1",
                 staging_root: Optional[str] = None,
                 restrict_files: bool = True,
                 strict_resources: bool = False,
                 exit_on_shutdown: bool = False,
                 cores: Optional[int] = None) -> None:
        if (bind not in ("127.0.0.1", "localhost")
                and authkey is None
                and "FIBER_CLUSTER_KEY" not in os.environ):
            # The agent is spawn-anything-as-me; with the well-known default
            # key that is unauthenticated RCE for anyone with network reach.
            # Refuse outright rather than warn (advisor, round 1).
            raise RuntimeError(
                "fiber-tpu agent: refusing to bind non-loopback interface "
                f"{bind!r} with the default cluster key. Set "
                "FIBER_CLUSTER_KEY (e.g. `openssl rand -hex 32`) on every "
                "host, or bind 127.0.0.1."
            )
        self._staging_root = os.path.realpath(
            staging_root or default_staging_root()
        )
        self._restrict_files = restrict_files
        # strict: reject spawns whose cpu reservation would oversubscribe
        # this host (off by default — sim clusters run many agents on one
        # machine and must share cores).
        self._strict_resources = strict_resources
        # Advertised core capacity. Defaults to the physical count; a sim
        # cluster overrides it upward because its N agents model N *hosts*
        # sharing one machine (the reference's Docker-backend posture —
        # containers share host cores, fiber/docker_backend.py mounts no
        # cpuset): reservation math is validated against the advertised
        # capacity, physical cores are shared.
        self._cores = int(cores) if cores else (os.cpu_count() or 1)
        self._core_rr = 0  # rotating start for affinity placement
        self._pending_cpu = 0  # reservations between check and job insert
        # Standalone daemons hard-exit on the shutdown op; embedded agents
        # (tests, tooling) must only stop serving — os._exit(0) from a
        # library call would kill the host interpreter silently.
        self._exit_on_shutdown = exit_on_shutdown
        # No authkey on the Listener: accept() must return after the
        # bare TCP accept so one hostile/stalled client can't block the
        # accept loop inside the HMAC challenge. The SAME mutual
        # challenge (deliver_challenge + answer_challenge, exactly what
        # Listener.accept(authkey=...) would run) happens per
        # connection in its own thread, under a kernel-level recv
        # timeout — see _serve.
        self._authkey = authkey or cluster_authkey()
        self._listener = Listener((bind, port))
        self.port = self._listener.address[1]
        # Jobs are keyed by a monotonically increasing id, never the OS
        # pid — pid reuse must not alias a finished job's record.
        self._jobs: Dict[int, _AgentJob] = {}
        self._next_jid = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def serve_forever(self) -> None:
        # Hostile or broken clients must never take the agent down or
        # starve it (pre-fix, one bare TCP connect-close exited the
        # daemon rc 0, and one connect-and-hold client stalled every
        # other RPC inside the accept-time challenge). The shared
        # hardened loop TCP-accepts only and authenticates each
        # connection on its own thread under hard deadlines and a
        # pre-auth connection cap (fiber_tpu/utils/serve.py).
        serve_authenticated(self._listener, self._authkey, self._stop,
                            self._serve, "fiber-agent-conn")

    def stop(self) -> None:
        """Stop serving (embedded agents / teardown): sets the flag
        BEFORE closing the listener so serve_forever's OSError path
        exits instead of retrying."""
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def _serve(self, conn) -> None:
        try:
            while True:
                request = conn.recv()
                try:
                    result = self._dispatch(*request)
                except SystemExit:
                    conn.send((True, None))
                    raise
                except BaseException as exc:  # noqa: BLE001
                    conn.send((False, repr(exc)))
                    continue
                conn.send((True, result))
        except (EOFError, OSError):
            pass
        except SystemExit:
            if self._exit_on_shutdown:
                os._exit(0)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # ------------------------------------------------------------------
    def _dispatch(self, op: str, *args: Any) -> Any:
        handler = getattr(self, "_op_" + op, None)
        if handler is None:
            raise ValueError(f"unknown agent op {op!r}")
        from fiber_tpu import telemetry

        telemetry.counter(
            "agent_ops", "Host-agent RPC ops served, by op").inc(op=op)
        return handler(*args)

    def _op_ping(self) -> str:
        return "pong"

    def _limits_exec(self, command, cpu: Optional[int],
                     mem: Optional[int]) -> list:
        """Wrap ``command`` in a bootstrap that applies CPU affinity and an
        address-space rlimit, then execs the real job (reference: JobSpecs
        become enforced k8s/docker resource limits,
        fiber/kubernetes_backend.py:80-101, fiber/docker_backend.py:63-102).
        An exec wrapper instead of preexec_fn: preexec_fn can deadlock in a
        threaded parent like this agent."""
        parts = ["import os,resource,sys"]
        if cpu:
            cores = sorted(os.sched_getaffinity(0))
            with self._lock:  # spawns run on per-connection threads
                start = self._core_rr % len(cores)
                self._core_rr += cpu
            chosen = tuple(
                cores[(start + i) % len(cores)]
                for i in range(min(cpu, len(cores)))
            )
            parts.append(f"os.sched_setaffinity(0, {chosen!r})")
        if mem:
            limit = int(mem) << 20  # MiB -> bytes
            parts.append(
                "resource.setrlimit(resource.RLIMIT_AS, "
                f"({limit}, {limit}))"
            )
        parts.append("os.execvp(sys.argv[1], sys.argv[1:])")
        return [sys.executable, "-c", ";".join(parts)] + list(command)

    def _op_spawn(self, command, cwd, env, name,
                  limits: Optional[dict] = None) -> Tuple[int, str]:
        from fiber_tpu.testing import chaos

        plan = chaos._plan
        if plan is not None:
            # Induced agent-side spawn refusal (budgeted): surfaces to
            # the master as an RPC error from this host — the per-host
            # breaker/blacklist case, distinct from a local_spawn
            # failure which hits every target equally.
            plan.fail_point("agent_spawn")
        limits = limits or {}
        cpu = limits.get("cpu")
        mem = limits.get("mem")
        ncpu = self._cores
        if cpu and cpu > ncpu:
            raise ValueError(
                f"cpu reservation {cpu} exceeds host cores {ncpu}"
            )
        reserved = 0
        if cpu and self._strict_resources:
            # Check AND reserve in one critical section — concurrent
            # spawn threads must not both pass the check before either
            # records its reservation (TOCTOU).
            with self._lock:
                in_use = self._pending_cpu + sum(
                    j.cpu for j in self._jobs.values()
                    if j.cpu and j.proc.poll() is None
                )
                if in_use + cpu > ncpu:
                    raise ValueError(
                        f"cpu over-subscription: {in_use} reserved + {cpu} "
                        f"requested > {ncpu} cores"
                    )
                self._pending_cpu += cpu
                reserved = cpu
        if cpu or mem:
            command = self._limits_exec(command, cpu, mem)
        log_fd, log_path = tempfile.mkstemp(
            prefix=f"fiber-agent-{name or 'job'}-", suffix=".log"
        )
        full_env = dict(os.environ)
        # Masters can't know each host's staging root when they build the
        # job env, so they send a placeholder this agent resolves (used by
        # code staging: PYTHONPATH={FIBER_STAGING}/code/<digest>:...).
        full_env.update({
            k: v.replace("{FIBER_STAGING}", self._staging_root)
            if isinstance(v, str) else v
            for k, v in (env or {}).items()
        })
        if isinstance(cwd, str):
            cwd = cwd.replace("{FIBER_STAGING}", self._staging_root)
        try:
            proc = subprocess.Popen(
                list(command),
                cwd=cwd if cwd and os.path.isdir(cwd) else None,
                env=full_env,
                stdout=log_fd,
                stderr=subprocess.STDOUT,
            )
        except BaseException:
            # Bad command must not leak the fd/logfile on a long-lived
            # agent (a master retry loop would exhaust descriptors).
            os.close(log_fd)
            try:
                os.unlink(log_path)
            except OSError:
                pass
            with self._lock:
                self._pending_cpu -= reserved
            raise
        os.close(log_fd)
        with self._lock:
            self._pending_cpu -= reserved
            self._next_jid += 1
            jid = self._next_jid
            self._jobs[jid] = _AgentJob(proc, log_path, cpu=int(cpu or 0))
        self._prune_finished()
        return jid, log_path

    def _prune_finished(self) -> None:
        """Bound the job table on long-lived agents: drop the oldest
        finished records (and their log files) past MAX_FINISHED_JOBS."""
        with self._lock:
            finished = [
                (jid, j) for jid, j in self._jobs.items()
                if j.proc.poll() is not None
            ]
            excess = len(finished) - MAX_FINISHED_JOBS
            victims = sorted(finished)[:excess] if excess > 0 else []
            for jid, _ in victims:
                del self._jobs[jid]
        for _, job in victims:
            try:
                os.unlink(job.log_path)
            except OSError:
                pass

    def _job(self, jid: int) -> _AgentJob:
        with self._lock:
            job = self._jobs.get(jid)
        if job is None:
            raise KeyError(f"no such job {jid}")
        return job

    def _op_poll(self, jid: int) -> Optional[int]:
        return self._job(jid).proc.poll()

    def _op_wait(self, jid: int, timeout: Optional[float]) -> Optional[int]:
        try:
            return self._job(jid).proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None

    def _op_signal(self, jid: int, signum: int) -> bool:
        job = self._job(jid)
        if job.proc.poll() is None:
            job.proc.send_signal(signum)
            return True
        return False

    def _op_logs(self, jid: int, max_bytes: int = 65536) -> str:
        job = self._job(jid)
        try:
            with open(job.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                size = fh.tell()
                fh.seek(max(0, size - max_bytes))
                return fh.read().decode(errors="replace")
        except OSError:
            return ""

    def _op_list_jobs(self) -> list:
        with self._lock:
            return [
                jid for jid, j in self._jobs.items()
                if j.proc.poll() is None
            ]

    def _file_path(self, path: str) -> str:
        """Resolve a file-op path. Relative paths land under the staging
        root; absolute paths must stay inside the staging root or the
        system tempdir unless the agent runs ``--unrestricted-files``
        (advisor: confine the remote read/write surface)."""
        if not os.path.isabs(path):
            path = os.path.join(self._staging_root, path)
        real = os.path.realpath(path)
        if self._restrict_files:
            allowed = (self._staging_root,
                       os.path.realpath(tempfile.gettempdir()))
            if not any(real == root or real.startswith(root + os.sep)
                       for root in allowed):
                raise PermissionError(
                    f"agent file ops are confined to {allowed} "
                    f"(got {path!r}); start the agent with "
                    "--unrestricted-files to lift this"
                )
        return real

    def _op_put_file(self, path: str, data: bytes, mode: int = 0o644) -> int:
        """File staging — the ``fiber cp`` equivalent (reference:
        fiber/cli.py:112-170 copies through a PVC pod)."""
        path = self._file_path(path)
        directory = os.path.dirname(path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(path, "wb") as fh:
            fh.write(data)
        os.chmod(path, mode)
        return len(data)

    def _op_get_file(self, path: str) -> bytes:
        with open(self._file_path(path), "rb") as fh:
            return fh.read()

    # -- object store (fiber_tpu/store, docs/objectstore.md) -----------
    # The agent serves the HOST CACHE tier of the per-host object store:
    # masters prestage broadcast objects through these ops so workers on
    # this host resolve refs from local disk without ever dialing the
    # owner, and operators inspect/clean the cache remotely. The
    # directory is the same `<staging>/objects` the in-process
    # LocalStore spills into.
    @staticmethod
    def _check_digest(digest: str) -> str:
        from fiber_tpu.utils.staging import is_object_digest

        if not is_object_digest(digest):
            raise ValueError(f"malformed object digest {digest!r}")
        return digest

    def _object_path(self, digest: str) -> str:
        return os.path.join(self._staging_root, "objects",
                            f"{self._check_digest(digest)}.obj")

    def _op_store_put(self, digest: str, data: bytes) -> int:
        import hashlib

        if hashlib.sha256(data).hexdigest() != self._check_digest(digest):
            raise ValueError("object payload does not match its digest")
        path = self._object_path(digest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)  # atomic: readers see complete objects
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return len(data)

    def _op_store_get(self, digest: str) -> bytes:
        with open(self._object_path(digest), "rb") as fh:
            return fh.read()

    def _op_store_has(self, digest: str) -> bool:
        return os.path.exists(self._object_path(digest))

    def _op_store_delete(self, digest: str) -> bool:
        try:
            os.unlink(self._object_path(digest))
            return True
        except OSError:
            return False

    def _op_store_stats(self) -> dict:
        root = os.path.join(self._staging_root, "objects")
        count = 0
        total = 0
        try:
            for name in os.listdir(root):
                if not name.endswith(".obj"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(root, name))
                    count += 1
                except OSError:
                    continue
        except OSError:
            pass
        return {"objects": count, "bytes": total}

    # -- telemetry (docs/observability.md) ------------------------------
    def _op_postmortem(self, last: int = 256) -> dict:
        """Black-box pull for this host: the agent process's own flight
        events + all-thread stack dump, plus the newest crash bundles
        workers on this host flushed under ``<staging>/postmortem/``
        (the health plane calls this when it declares a worker here
        dead; ``fiber-tpu postmortem --hosts`` is the operator form)."""
        from fiber_tpu.telemetry import postmortem, tracing
        from fiber_tpu.telemetry.flightrec import FLIGHT

        bundles = []
        pm_dir = postmortem.bundle_dir(self._staging_root)
        for path in postmortem.list_bundles(pm_dir)[-8:]:
            try:
                bundles.append(postmortem.read_bundle(path))
            except (OSError, ValueError):
                continue
        return {
            "host": tracing.host_id(),
            "pid": os.getpid(),
            "flight": FLIGHT.snapshot(last=int(last)),
            "stacks": postmortem.stack_dump(),
            "bundle_dir": pm_dir,
            "bundles": bundles,
        }

    def _op_telemetry_snapshot(self) -> dict:
        """This agent process's metrics/timers/span-buffer state — the
        per-host payload ``TpuBackend.cluster_metrics`` and the
        ``fiber-tpu metrics`` CLI aggregate."""
        from fiber_tpu import telemetry

        return telemetry.snapshot()

    def _op_device_snapshot(self) -> dict:
        """Device telemetry surface for this host: transfer accounting,
        compile count/seconds + recompile state, HBM and live-array
        stats (honest None when this process has no device runtime —
        the probe never *initializes* a jax backend), and the last live
        MFU — the per-host payload of ``TpuBackend.cluster_devices``
        and the ``fiber-tpu devices`` CLI (docs/observability.md
        "Device telemetry")."""
        from fiber_tpu.telemetry.device import DEVICE

        DEVICE.update_gauges()  # extra-fresh HBM/live-array probe
        return DEVICE.snapshot()

    def _op_cost_snapshot(self) -> dict:
        """Accounting-plane surface for this host: the process cost
        ledger's per-billing-key vectors (docs/observability.md
        "Resource accounting") — the per-host payload of
        ``TpuBackend.cluster_costs`` and ``fiber-tpu top --costs``."""
        from fiber_tpu.telemetry.accounting import COSTS

        return COSTS.snapshot()

    def _op_monitor_snapshot(self, history: int = 120) -> dict:
        """Continuous-monitor surface for this host: time-series rings,
        derived rates, heartbeat ages and the anomaly watchdog state —
        the per-host payload of ``TpuBackend.cluster_timeseries`` and
        the ``fiber-tpu top`` row (docs/observability.md). An
        extra-fresh sample is taken when the sampler is armed so `top`
        never renders a tick-old rate."""
        from fiber_tpu.telemetry.monitor import monitor_payload
        from fiber_tpu.telemetry.timeseries import TIMESERIES

        if TIMESERIES.enabled:
            TIMESERIES.sample_once()
        return monitor_payload(history=int(history))

    def _op_profile_dump(self, seconds: float = 1.0,
                         hz: float = 97.0) -> dict:
        """On-demand sampling profile of THIS process (bounded burst;
        docs/observability.md "Sampling profiler"). When the standing
        profiler is armed (``profiler_hz`` > 0) its aggregate rides
        along so ``fiber-tpu profile --hosts`` sees history too."""
        from fiber_tpu.telemetry import tracing
        from fiber_tpu.telemetry.profiler import PROFILER

        folded = PROFILER.sample_for(seconds, hz)
        return {
            "host": tracing.host_id(),
            "pid": os.getpid(),
            "hz": float(hz),
            "seconds": min(max(0.0, float(seconds)), 30.0),
            "folded": folded,
            "standing": PROFILER.snapshot(),
        }

    def _op_host_info(self) -> dict:
        from fiber_tpu.transport import shm as shm_mod

        return {
            "pid": os.getpid(),
            "cpu_count": self._cores,
            "physical_cpu_count": os.cpu_count(),
            "cwd": os.getcwd(),
            "python": sys.executable,
            "staging_root": self._staging_root,
            # Same-host transport capability: whether /dev/shm backs the
            # ring files (docs/transport.md) — tmpdir rings still work
            # but may touch disk, which placement may care about.
            "shm_dir": shm_mod.ring_dir(),
            "shm_ram_backed": shm_mod.ring_dir().startswith("/dev/shm"),
        }

    def _op_shutdown(self) -> None:
        self._stop.set()
        # reap children first
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            if job.proc.poll() is None:
                job.proc.terminate()
        try:
            self._listener.close()
        except OSError:
            pass
        raise SystemExit(0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fiber_tpu.host_agent")
    parser.add_argument("--port", type=int, default=DEFAULT_AGENT_PORT)
    parser.add_argument("--bind", default="127.0.0.1",
                        help="interface to bind; non-loopback requires "
                             "FIBER_CLUSTER_KEY to be set")
    parser.add_argument("--announce", action="store_true",
                        help="print the bound port to stdout once serving")
    parser.add_argument("--staging-root", default=None,
                        help="root for put_file/get_file "
                             "(default: ~/.fiber_tpu/staging)")
    parser.add_argument("--unrestricted-files", action="store_true",
                        help="allow put_file/get_file anywhere on disk")
    parser.add_argument("--strict-resources", action="store_true",
                        help="reject spawns whose cpu reservations would "
                             "oversubscribe this host")
    parser.add_argument("--cores", type=int, default=0,
                        help="advertised core capacity (default: physical "
                             "cpu count; sim clusters raise it — N agents "
                             "on one machine model N hosts sharing cores)")
    args = parser.parse_args(argv)
    agent = HostAgent(args.port, bind=args.bind,
                      staging_root=args.staging_root,
                      restrict_files=not args.unrestricted_files,
                      strict_resources=args.strict_resources,
                      exit_on_shutdown=True,
                      cores=args.cores)
    if args.announce:
        print(f"AGENT_PORT {agent.port}", flush=True)
    # Prometheus sidecar (docs/observability.md): an authenticated
    # exposition endpoint next to the agent when metrics_port is set.
    from fiber_tpu import config as fconfig

    metrics_port = int(fconfig.get().metrics_port or 0)
    if metrics_port > 0:
        from fiber_tpu import telemetry

        try:
            server = telemetry.serve_metrics(metrics_port, bind=args.bind)
            print(f"METRICS_PORT {server.port}", flush=True)
        except Exception:
            from fiber_tpu.utils.logging import get_logger

            get_logger().exception("agent: metrics endpoint failed to "
                                   "start; serving without it")
    # Die with the parent where supported (sim clusters).
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))
    agent.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())

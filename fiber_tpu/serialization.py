"""Object serialization for the control and data planes.

Policy (reference parity: fiber/popen_fiber_spawn.py:348-354, pool.py:60-63):
use the stdlib ``multiprocessing.reduction.ForkingPickler`` for normal
programs, and fall back to **cloudpickle** when the object graph needs
pickling-by-value (interactive shells, closures, lambdas).

TPU-native extension: a reducer for ``jax.Array`` so device arrays can ride
the host plane — they are pulled to host memory as numpy on serialize and
re-materialized with ``jax.device_put`` on deserialize. Cross-host device
state otherwise never touches pickle: bulk tensors move on the ICI plane via
collectives, not the host plane.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

from multiprocessing.reduction import ForkingPickler

from fiber_tpu.utils.misc import is_in_interactive_console

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None


def _jax_array_reduce(arr):
    import jax
    import numpy as np

    host = np.asarray(arr)
    return (_jax_array_rebuild, (host,))


def _jax_array_rebuild(host):
    import jax

    return jax.device_put(host)


_jax_reducer_registered = False


def register_jax_reducers() -> None:
    """Register the jax.Array reducer on both picklers (idempotent, lazy —
    only ever called once jax is already imported by user code)."""
    global _jax_reducer_registered
    if _jax_reducer_registered:
        return
    import sys

    if "jax" not in sys.modules:
        return
    # Pickle dispatch is exact-type, so the concrete ArrayImpl class must
    # be registered (not the jax.Array ABC). Import it without creating an
    # array: materializing even a scalar would initialize the TPU runtime
    # from whatever process happens to pickle first.
    try:
        from jax._src.array import ArrayImpl
    except ImportError:  # pragma: no cover - jax internals moved
        return
    ForkingPickler.register(ArrayImpl, _jax_array_reduce)
    _jax_reducer_registered = True


def dumps(obj: Any) -> bytes:
    """Serialize with the stdlib reducer; cloudpickle on failure or in
    interactive sessions."""
    register_jax_reducers()
    if cloudpickle is not None and is_in_interactive_console():
        return cloudpickle.dumps(obj)
    try:
        buf = io.BytesIO()
        ForkingPickler(buf, pickle.HIGHEST_PROTOCOL).dump(obj)
        return buf.getvalue()
    except (pickle.PicklingError, AttributeError, TypeError):
        if cloudpickle is None:
            raise
        return cloudpickle.dumps(obj)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def dump_to(obj: Any, fileobj) -> None:
    fileobj.write(dumps(obj))

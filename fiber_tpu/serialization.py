"""Object serialization for the control and data planes.

Policy (reference parity: fiber/popen_fiber_spawn.py:348-354, pool.py:60-63):
use the stdlib ``multiprocessing.reduction.ForkingPickler`` for normal
programs, and fall back to **cloudpickle** when the object graph needs
pickling-by-value (interactive shells, closures, lambdas).

Pickle protocol 5: large contiguous buffers (numpy arrays, bytes) are
captured **out-of-band** via ``buffer_callback`` and framed alongside the
pickle stream instead of being copied through it. In-band protocol-5
pickling costs two full copies of every big array (pickler write +
``BytesIO.getvalue``); the out-of-band envelope costs one gather copy on
``dumps`` and one (writability-preserving) copy on ``loads``. The object
store (fiber_tpu/store) reuses the same envelope as its on-disk and wire
format, so a stored payload is exactly ``loads``-able.

Envelope layout (only produced when at least one buffer went out-of-band;
plain pickles pass through untouched, so old payloads always load)::

    0xFB 0x05 | u32 nbuf | u64 len(pickle) | nbuf * u64 len | pickle | bufs

TPU-native extension: a reducer for ``jax.Array`` so device arrays can ride
the host plane — they are pulled to host memory as numpy on serialize and
re-materialized with ``jax.device_put`` on deserialize (device placement
happens on the *consuming* process, which is what the store's
resolve-on-worker contract needs). Cross-host device state otherwise never
touches pickle: bulk tensors move on the ICI plane via collectives, not
the host plane.
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Tuple

from multiprocessing.reduction import ForkingPickler

from fiber_tpu.utils.misc import is_in_interactive_console

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    cloudpickle = None

#: Envelope magic. Safe discriminator: every pickle this module can emit
#: (protocol >= 2, stdlib or cloudpickle) starts with 0x80.
_OOB_MAGIC = b"\xfb\x05"
_OOB_HEAD = struct.Struct(">IQ")
_OOB_LEN = struct.Struct(">Q")

#: Buffers smaller than this stay in-band: the envelope bookkeeping and
#: the extra frame slices cost more than one memcpy of a small array.
OOB_MIN_BYTES = 64 * 1024


def _jax_array_reduce(arr):
    import jax
    import numpy as np

    host = np.asarray(arr)
    return (_jax_array_rebuild, (host,))


def _jax_array_rebuild(host):
    import jax

    from fiber_tpu.telemetry.device import DEVICE

    # The device boundary of every pickled jax.Array (store resolution,
    # result deserialize): accounted per-site so `fiber-tpu explain`
    # can blame transfer seconds (docs/observability.md).
    with DEVICE.transfer("deserialize", getattr(host, "nbytes", 0)):
        return jax.device_put(host)


_jax_reducer_registered = False


def register_jax_reducers() -> None:
    """Register the jax.Array reducer on both picklers (idempotent, lazy —
    only ever called once jax is already imported by user code)."""
    global _jax_reducer_registered
    if _jax_reducer_registered:
        return
    import sys

    if "jax" not in sys.modules:
        return
    # Pickle dispatch is exact-type, so the concrete ArrayImpl class must
    # be registered (not the jax.Array ABC). Import it without creating an
    # array: materializing even a scalar would initialize the TPU runtime
    # from whatever process happens to pickle first.
    try:
        from jax._src.array import ArrayImpl
    except ImportError:  # pragma: no cover - jax internals moved
        return
    ForkingPickler.register(ArrayImpl, _jax_array_reduce)
    _jax_reducer_registered = True


class _OOBPickler(pickle.Pickler):
    """ForkingPickler's reducer table + protocol-5 ``buffer_callback``
    (ForkingPickler.__init__ takes ``*args`` and can't forward the
    keyword-only callback, so the table copy happens here instead)."""

    def __init__(self, file, buffer_callback) -> None:
        super().__init__(file, 5, buffer_callback=buffer_callback)
        self.dispatch_table = ForkingPickler._copyreg_dispatch_table.copy()
        self.dispatch_table.update(ForkingPickler._extra_reducers)


def dumps_oob(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to ``(pickle_bytes, out_of_band_buffers)``. The buffers
    are zero-copy views into the caller's objects — valid only while
    those objects live and are not mutated. Raises the usual pickling
    errors; callers that want the cloudpickle fallback use :func:`dumps`.
    """
    register_jax_reducers()
    buffers: List[memoryview] = []

    def keep_oob(pb: pickle.PickleBuffer):
        # Pickler semantics: a FALSY return means out-of-band, truthy
        # means serialize in-band.
        try:
            view = pb.raw()
        except BufferError:
            return True  # non-contiguous: let pickle in-band it
        if view.nbytes < OOB_MIN_BYTES:
            return True
        buffers.append(view)
        return False

    buf = io.BytesIO()
    _OOBPickler(buf, keep_oob).dump(obj)
    return buf.getvalue(), buffers


def pack_envelope(data, buffers) -> bytes:
    """Gather ``(pickle, buffers)`` into the single self-describing byte
    string :func:`loads` accepts (one copy of each buffer)."""
    parts = [
        _OOB_MAGIC,
        _OOB_HEAD.pack(len(buffers), len(data)),
    ]
    parts.extend(_OOB_LEN.pack(b.nbytes if isinstance(b, memoryview)
                               else len(b)) for b in buffers)
    parts.append(data)
    parts.extend(buffers)
    return b"".join(bytes(p) if isinstance(p, memoryview) else p
                    for p in parts)


def is_envelope(data) -> bool:
    return len(data) >= 2 and bytes(data[:2]) == _OOB_MAGIC


def unpack_envelope(data) -> Tuple[memoryview, List[memoryview]]:
    """Split an envelope into ``(pickle_view, buffer_views)`` without
    copying (views into ``data``)."""
    mv = memoryview(data)
    nbuf, ndata = _OOB_HEAD.unpack_from(mv, 2)
    off = 2 + _OOB_HEAD.size
    lens = []
    for _ in range(nbuf):
        (n,) = _OOB_LEN.unpack_from(mv, off)
        lens.append(n)
        off += _OOB_LEN.size
    head = mv[off:off + ndata]
    off += ndata
    bufs = []
    for n in lens:
        bufs.append(mv[off:off + n])
        off += n
    return head, bufs


def dumps(obj: Any) -> bytes:
    """Serialize with the stdlib reducer (protocol 5, out-of-band buffer
    envelope for large arrays); cloudpickle on failure or in interactive
    sessions."""
    register_jax_reducers()
    if cloudpickle is not None and is_in_interactive_console():
        return cloudpickle.dumps(obj)
    try:
        data, buffers = dumps_oob(obj)
    except (pickle.PicklingError, AttributeError, TypeError):
        if cloudpickle is None:
            raise
        return cloudpickle.dumps(obj)
    if not buffers:
        return data
    return pack_envelope(data, buffers)


def loads(data: Any) -> Any:
    """Inverse of :func:`dumps`; accepts bytes, bytearray or memoryview
    (the framing layer hands over bytearrays). Out-of-band buffers are
    re-materialized as private *writable* copies — handing callers views
    into a shared frame would make every deserialized array aliased and
    read-only, a silent behavior change from in-band pickling."""
    if is_envelope(data):
        head, views = unpack_envelope(data)
        return pickle.loads(head, buffers=[bytearray(v) for v in views])
    return pickle.loads(data)


def dump_to(obj: Any, fileobj) -> None:
    fileobj.write(dumps(obj))

"""Selector-based transport I/O core (docs/transport.md).

One poller thread per process owns every non-blocking channel socket the
``transport_io="selector"`` path creates:

* **ingress** — readiness-driven incremental frame decode from a
  per-channel :class:`~fiber_tpu.framing.FrameBuffer` replaces the
  thread-per-connection blocking readers: a master driving a pod-slice's
  worth of workers runs O(1) socket threads instead of one GIL-contending
  thread per peer, and a burst of tiny frames queued in the kernel drains
  in one syscall and one inbox notify;
* **egress** — a per-channel write queue drained with
  ``socket.sendmsg`` scatter-gather: a large frame leaves as one
  vectored syscall (header + type tag + payload, zero copies), and small
  control frames (credit grants, heartbeats, span batches, storemiss
  notices) queued between poller wakeups coalesce into a single flush of
  up to ``transport_coalesce_max`` bytes.

The loop is an implementation detail behind ``Endpoint`` — recv/send,
credit semantics, ``last_rx``, the exact byte/frame counters, and the
chaos ingress hook behave identically to the ``"threads"`` fallback
(tested: tests/test_transport.py parity suite, tests/test_chaos.py drop
plans under both modes). The design is the standard event-loop +
vectored-I/O shape of Ray's raylet and gRPC's polling engine.

Threading rules:

* every selector mutation (register/modify/unregister/close) happens on
  the loop thread; other threads submit ops through ``_pending`` and
  :meth:`wake` — epoll tolerates concurrent ctl calls but the selectors
  bookkeeping does not;
* sender threads only touch a channel's tx queue under its tx condition,
  so enqueue is a few appends + at most one wake write;
* the loop never sleeps in user hooks: a chaos-injected ingress stall
  parks ONE channel until its deadline (select timeout), it does not
  stall the process's whole data plane the way sleeping the poller
  would.
"""

from __future__ import annotations

import os
import selectors
import socket
import threading
import time
from contextlib import contextmanager
from typing import List, Optional

from fiber_tpu import telemetry
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

# Registry twins of the transport wire counters — same instruments
# transport/tcp.py registers (the registry folds same-name lookups), so
# the loop can bump them once per decode batch instead of per frame.
_m_bytes_rx = telemetry.counter(
    "transport_bytes_rx", "Wire bytes received (framing headers included)")
_m_frames_rx = telemetry.counter("transport_frames_rx", "Frames received")

# Poller health surface (docs/transport.md / docs/observability.md).
_m_channels = telemetry.gauge(
    "transport_evloop_channels",
    "Channel sockets currently owned by this process's selector loop")
_m_wakeups = telemetry.counter(
    "transport_evloop_wakeups", "Selector loop select() returns")
_m_flush_frames = telemetry.histogram(
    "transport_evloop_flush_frames",
    "Whole frames completed per coalesced sendmsg flush",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256))
_m_flush_bytes = telemetry.histogram(
    "transport_evloop_flush_bytes",
    "Bytes accepted by the kernel per sendmsg flush",
    buckets=(64, 1024, 16384, 65536, 262144, 1 << 20, 8 << 20))
_m_turn_seconds = telemetry.histogram(
    "transport_evloop_turn_seconds",
    "Active processing per selector-loop turn (select sleep excluded)",
    buckets=(1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 5e-3,
             2.5e-2, 0.1, 1.0))
# Same instrument transport/tcp.py registers at enqueue time (the
# registry folds same-name lookups): the loop decrements as it drains.
_g_txq_bytes = telemetry.gauge(
    "transport_evloop_tx_queue_bytes",
    "Bytes queued for the selector loop's coalescing flush, all "
    "channels")

#: iovec entries per sendmsg call; Linux UIO_MAXIOV is 1024 — stay under.
_IOV_MAX = 512

#: Per-channel write-queue high-water mark: an enqueuing sender blocks
#: past this many pending bytes until the loop drains below it (bounds
#: memory the way a blocking sendall's kernel-buffer wait did). A single
#: frame is always accepted whole, so one oversized payload can't
#: deadlock its own enqueue.
TX_HIGH_WATER = 32 << 20


def set_tx_high_water(n: int) -> int:
    """Retune the TX high-water mark live (the policy plane's
    tx_queue_high remediation halves it; the clear-edge revert restores
    it). Floored at 1 MiB so a runaway tightening loop can never choke
    enqueue to a standstill. Returns the previous value — senders read
    the module global per enqueue, so the change takes effect on the
    next frame."""
    global TX_HIGH_WATER
    prev = TX_HIGH_WATER
    TX_HIGH_WATER = max(1 << 20, int(n))
    return prev


class EventLoop:
    """The per-process poller. Use :func:`get_loop`, not the class."""

    def __init__(self) -> None:
        self._selector = selectors.DefaultSelector()
        self._lock = threading.Lock()
        self._pending: List[tuple] = []   # (op, chan) submitted cross-thread
        self._stalled: set = set()        # channels parked by chaos stalls
        self._rx_batches: dict = {}       # endpoint -> frames this turn
        self._hold_tx = False             # test hook: park all flushes
        self._in_select = False           # loop is (about to be) sleeping
        self._closed = False
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._wake_armed = False
        self._selector.register(self._wake_r, selectors.EVENT_READ, None)
        self._thread = threading.Thread(
            target=self._run, name="fiber-evloop", daemon=True)
        self._thread.start()

    # -- cross-thread interface ------------------------------------------
    def wake(self) -> None:
        with self._lock:
            if self._wake_armed:
                return
            if not self._in_select:
                # The loop is mid-turn: it re-checks the op queue under
                # this lock before its next sleep, so the byte (a
                # syscall per sender) is pure waste right now.
                return
            self._wake_armed = True
        try:
            self._wake_w.send(b"\0")
        except OSError:
            pass

    def _submit(self, op: str, chan) -> None:
        with self._lock:
            self._pending.append((op, chan))
        self.wake()

    def register_channel(self, chan) -> None:
        """Adopt ``chan``'s socket (already non-blocking). Called from
        the thread that accepted/dialed the connection."""
        chan.sock.setblocking(False)
        self._submit("add", chan)

    def request_flush(self, chan) -> None:
        """A sender queued data on ``chan``; schedule a drain."""
        self._submit("tx", chan)

    def close_channel(self, chan) -> None:
        """Flush ``chan``'s queued egress best-effort, then unregister
        and close its socket on the loop thread. Callable from any
        thread, including the loop itself (the drop path)."""
        on_loop = threading.current_thread() is self._thread
        with chan._tx_cond:
            already = chan._tx_closing
            chan._tx_closing = True
            chan._tx_cond.notify_all()
            if not already and not on_loop:
                # Caller-side synchronous drain: the worker-exit path
                # (result sent, endpoint closed, process gone) must not
                # race the daemon poller for its last frames. Wait out
                # any in-flight loop flush first (its pieces are with
                # the loop thread), then push the queued remainder
                # ourselves — the tx condition serializes the two.
                deadline = time.monotonic() + 2.0
                while chan._tx_inflight and time.monotonic() < deadline:
                    chan._tx_cond.wait(0.05)
                if chan._txq:
                    try:
                        chan.sock.settimeout(2.0)
                        for piece, _end in chan._txq:
                            chan.sock.sendall(piece)
                    except OSError:
                        pass
                    finally:
                        chan._txq.clear()
                        _g_txq_bytes.dec(chan._tx_bytes)
                        chan._tx_bytes = 0
                        try:
                            chan.sock.setblocking(False)
                        except OSError:
                            pass
        if already:
            return
        if on_loop:
            self._finalize(chan)
        else:
            self._submit("close", chan)

    @contextmanager
    def hold_tx(self):
        """Test hook: park every egress flush while the context is held,
        so a burst of sends lands in the write queues and the release
        flush demonstrates (and lets tests assert) coalescing."""
        self._hold_tx = True
        try:
            yield
        finally:
            self._hold_tx = False
            self._submit("txall", None)

    @property
    def thread(self) -> threading.Thread:
        return self._thread

    def channel_count(self) -> int:
        return len(self._selector.get_map()) - 1  # minus the wake pipe

    # -- loop body --------------------------------------------------------
    def _run(self) -> None:
        while not self._closed:
            try:
                self._turn()
            except Exception:  # pragma: no cover - belt and braces
                logger.exception("transport event loop turn failed")
                time.sleep(0.01)

    def _turn(self) -> None:
        timeout = None
        if self._stalled:
            now = time.monotonic()
            timeout = max(0.0, min(
                c._stall_until for c in self._stalled) - now)
        with self._lock:
            if self._pending:
                timeout = 0  # ops queued while we were mid-turn
            else:
                self._in_select = True
        events = self._selector.select(timeout)
        t_active = time.perf_counter()
        _m_wakeups.inc()
        wake_ready = any(key.data is None for key, _mask in events)
        if wake_ready:
            # Drain the wake pipe BEFORE clearing the armed flag: the
            # flag promises "a wake byte is in flight for you" —
            # draining after the clear could swallow a byte a mid-turn
            # submitter wrote for its freshly-armed wake, leaving
            # armed=True with an empty pipe, after which every later
            # submit skips the write and the loop sleeps through pending
            # ops forever (the lost-wakeup race this ordering kills). A
            # byte written after this drain just makes the next select
            # return immediately.
            try:
                while self._wake_r.recv(4096):
                    pass
            except (BlockingIOError, InterruptedError):
                pass
        with self._lock:
            self._in_select = False
            self._wake_armed = False
            ops, self._pending = self._pending, []
        for op, chan in ops:
            if op == "add":
                self._add(chan)
            elif op == "tx":
                if chan._registered and not chan._tx_closing:
                    self._flush(chan)
            elif op == "txall":
                for key in list(self._selector.get_map().values()):
                    c = key.data
                    if c is not None and c._txq and not c._tx_closing:
                        self._flush(c)
            elif op == "close":
                self._finalize(chan)
        for key, mask in events:
            chan = key.data
            if chan is None:
                continue  # wake pipe — drained above
            if not chan._registered:
                continue  # closed by an earlier op this turn
            if mask & selectors.EVENT_READ:
                self._readable(chan)
            if (mask & selectors.EVENT_WRITE) and chan._registered:
                self._flush(chan)
        if self._stalled:
            self._service_stalls()
        if self._rx_batches:
            # One inbox extend + notify per ENDPOINT per turn: a 64-way
            # fan-in delivers the whole turn's decode in one condition
            # round instead of 64.
            batches, self._rx_batches = self._rx_batches, {}
            for owner, items in batches.items():
                if items:
                    # Guarded: a turn that only advanced a mid-frame
                    # decode leaves an empty batch, and an empty
                    # put_many would still notify — spuriously waking
                    # the consumer once per turn of a large transfer.
                    owner._inbox.put_many(items)
        # Poller health (docs/observability.md): how long each turn
        # held the loop — a fat tail here means one channel's work is
        # delaying every other channel's ingress.
        _m_turn_seconds.observe(time.perf_counter() - t_active)

    # -- registration -----------------------------------------------------
    def _add(self, chan) -> None:
        try:
            self._selector.register(
                chan.sock, selectors.EVENT_READ, chan)
        except (ValueError, KeyError, OSError):
            # Socket died between accept and registration.
            chan.owner._drop_channel(chan)
            return
        chan._registered = True
        chan._ev_mask = selectors.EVENT_READ
        _m_channels.set(self.channel_count())
        if chan._txq:
            self._flush(chan)

    def _set_mask(self, chan, mask: int) -> None:
        if chan._ev_mask == mask or not chan._registered:
            return
        try:
            self._selector.modify(chan.sock, mask, chan)
            chan._ev_mask = mask
        except (ValueError, KeyError, OSError):
            self._drop(chan)

    def _finalize(self, chan) -> None:
        if chan._registered:
            chan._registered = False
            try:
                self._selector.unregister(chan.sock)
            except (ValueError, KeyError, OSError):
                pass
            _m_channels.set(self.channel_count())
        self._stalled.discard(chan)
        chan._tx_head.clear()
        with chan._tx_cond:
            chan._txq.clear()
            _g_txq_bytes.dec(chan._tx_bytes)
            chan._tx_bytes = 0
            chan._tx_inflight = False
            chan._tx_cond.notify_all()
        try:
            chan.sock.close()
        except OSError:
            pass

    def _drop(self, chan) -> None:
        """Connection-level failure: hand the channel back to its
        endpoint (counter folding, sentinel wake) — which re-enters
        close_channel → _finalize on this thread."""
        chan.owner._drop_channel(chan)

    # -- ingress ----------------------------------------------------------
    #: Bytes one channel may drain per readiness event before yielding
    #: the loop to its siblings — drain-until-EAGAIN (one select per
    #: kernel-buffered burst instead of one per recv) bounded so a
    #: firehose peer cannot starve the other channels for a whole
    #: tensor.
    RX_TURN_BUDGET = 4 << 20

    def _readable(self, chan) -> None:
        got = 0
        eof = False
        while got < self.RX_TURN_BUDGET:
            try:
                n = chan._fb.fill(chan.sock)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop(chan)
                return
            if n == 0:
                eof = True
                break
            got += n
            if n < 65536:
                # Short read: the kernel buffer is (almost certainly)
                # empty — skip the EAGAIN probe syscall. Safe because
                # select is level-triggered: any bytes that did land
                # re-notify on the next turn.
                break
        if got:
            # Frames completed before an EOF still deliver — the peer
            # flushed them before closing.
            self._pump_rx(chan)
        if eof:
            self._drop(chan)

    def _pump_rx(self, chan) -> None:
        """Decode and deliver every complete frame buffered on ``chan``.
        Delivery batches bound-ingress frames into the turn's
        per-endpoint batch (one inbox extend + condition notify per
        endpoint per TURN, flushed at the end of :meth:`_turn`), and the
        process-wide registry twins of the wire counters are bumped once
        per batch (the per-channel/endpoint counters stay exact
        per-frame inside handle_frame)."""
        batch = self._rx_batches.get(chan.owner)
        if batch is None:
            batch = self._rx_batches.setdefault(chan.owner, [])
        rx_bytes = 0
        rx_frames = 0
        try:
            while chan._stall_until is None:
                try:
                    frame = chan._fb.pop()
                except OSError:
                    self._drop(chan)
                    return
                if frame is None:
                    break
                rx_bytes += len(frame) + 8
                rx_frames += 1
                stall = chan.handle_frame(frame, True, batch, False)
                if stall is not None:
                    stall_s, drop = stall
                    chan._stall_until = time.monotonic() + stall_s
                    chan._stall_pending = (frame, drop)
                    self._stalled.add(chan)
                    break
        finally:
            if rx_frames:
                _m_bytes_rx.inc(rx_bytes)
                _m_frames_rx.inc(rx_frames)

    def _service_stalls(self) -> None:
        now = time.monotonic()
        for chan in [c for c in self._stalled
                     if c._stall_until is not None
                     and c._stall_until <= now]:
            self._stalled.discard(chan)
            chan._stall_until = None
            frame, drop = chan._stall_pending
            chan._stall_pending = None
            if not chan._registered:
                continue
            if drop:
                # Loss model: hand the consumed window slot back (same
                # compensation as the threads path).
                try:
                    chan.send_credit(1)
                except OSError:
                    pass
            else:
                chan.deliver_data(frame)
            self._pump_rx(chan)

    # -- egress -----------------------------------------------------------
    def _flush(self, chan) -> None:
        """Drain ``chan``'s write queue with coalesced vectored sends:
        one ``sendmsg`` gathers queued pieces up to the configured
        coalescing cap (whole frames of any size always ship — a large
        payload is one iovec entry, never split or copied). The queued
        pieces move to a loop-owned head under the tx condition, then
        every syscall runs OUTSIDE it — a producer keeps enqueueing
        while the kernel copies."""
        if self._hold_tx:
            return
        from fiber_tpu import config

        cap = int(getattr(config.get(), "transport_coalesce_max",
                          256 * 1024)) or (256 * 1024)
        head = chan._tx_head
        with chan._tx_cond:
            chan._tx_dirty = False
            if chan._tx_closing:
                chan._tx_inflight = False
                chan._tx_cond.notify_all()
                return
            if chan._txq:
                head.extend(chan._txq)
                chan._txq.clear()
            chan._tx_inflight = bool(head)
        error = False
        sent_total = 0
        while head:
            iov = []
            take = 0
            for piece, _end in head:
                iov.append(piece)
                take += len(piece)
                if take >= cap or len(iov) >= _IOV_MAX:
                    break
            try:
                sent = chan.sock.sendmsg(iov)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                error = True
                break
            if sent <= 0:
                break
            sent_total += sent
            chan.flushes_tx += 1
            frames_done = 0
            while sent and head:
                piece, end = head[0]
                n = len(piece)
                if sent >= n:
                    sent -= n
                    head.popleft()
                    if end:
                        frames_done += 1
                else:
                    head[0] = (memoryview(piece)[sent:], end)
                    sent = 0
            _m_flush_frames.observe(frames_done)
        if sent_total:
            _m_flush_bytes.observe(sent_total)
        with chan._tx_cond:
            chan._tx_bytes -= sent_total
            if sent_total:
                _g_txq_bytes.dec(sent_total)
            chan._tx_inflight = bool(head)
            pending = bool(head) or bool(chan._txq)
            chan._tx_cond.notify_all()
        if error:
            self._drop(chan)
            return
        self._set_mask(
            chan,
            selectors.EVENT_READ | selectors.EVENT_WRITE
            if pending else selectors.EVENT_READ,
        )

    # -- lifecycle --------------------------------------------------------
    def close(self) -> None:  # pragma: no cover - process teardown only
        self._closed = True
        self.wake()


_loop: Optional[EventLoop] = None
_loop_pid: Optional[int] = None
_loop_guard = threading.Lock()


def get_loop() -> EventLoop:
    """The process-wide poller, created on first use. Guarded by pid so a
    forked child never inherits a loop whose thread died in the fork."""
    global _loop, _loop_pid
    pid = os.getpid()
    with _loop_guard:
        if _loop is None or _loop_pid != pid:
            _loop = EventLoop()
            _loop_pid = pid
        return _loop

"""Host-plane transport: framed-TCP endpoints + device forwarders.

Semantics preserved from the reference's nanomsg data plane
(fiber/socket.py) without the library zoo:

* modes ``r`` (pull), ``w`` (push, strict round-robin over connected
  peers), ``rw`` (pair-ish duplex), ``req``/``rep`` (resilient task
  handout);
* a ``Device`` is a forwarder bound to stable addresses so both producers
  and consumers dial *it* (reference: fiber/socket.py:297-320 nn_device);
* random bind ports in 40000-65535.

The pump loop runs in Python threads by default and in the C++ epoll pump
(fiber_tpu/_native) when built — same observable behavior.
"""

from fiber_tpu.transport.tcp import Device, Endpoint, TransportClosed  # noqa: F401

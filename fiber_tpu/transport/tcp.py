"""TCP implementation of the host-plane transport.

An ``Endpoint`` is a message socket with one of five modes:

====  =========================================================
r     receive-only; fair-merges frames from all connected peers
w     send-only; strict round-robin across connected peers
rw    duplex; round-robin send + fair-merge receive
req   client of a rep endpoint: send a request, recv the answer
rep   server: recv returns a request; the next send answers it
====  =========================================================

A bound endpoint accepts any number of dialing peers. Fairness contracts
(tested, mirroring the reference's nanomsg behavior): ``w``-send
round-robins message-by-message across peers regardless of consumer speed;
``r``-recv merges arrival order across peers.
"""

from __future__ import annotations

import collections
import itertools
import select
import socket as pysocket
import struct
import threading
import time
from typing import List, Optional, Tuple

from fiber_tpu import auth, config, telemetry
from fiber_tpu.testing import chaos
from fiber_tpu.framing import (
    FRAME_OVERHEAD,
    SMALL_FRAME_MAX,
    ConnectionClosed,
    FrameBuffer,
    FrameReader,
    pack_header,
    send_frame,
)
from fiber_tpu.utils.logging import get_logger
from fiber_tpu.utils.net import random_port_bind

logger = get_logger()

# Cluster-wide wire volume (docs/observability.md). Per-endpoint EXACT
# counters live on Endpoint.bytes_tx/bytes_rx/frames_tx/frames_rx —
# these registry twins aggregate across every endpoint in the process.
_m_bytes_tx = telemetry.counter(
    "transport_bytes_tx", "Wire bytes sent (framing headers included)")
_m_bytes_rx = telemetry.counter(
    "transport_bytes_rx", "Wire bytes received (framing headers included)")
_m_frames_tx = telemetry.counter("transport_frames_tx", "Frames sent")
_m_frames_rx = telemetry.counter("transport_frames_rx", "Frames received")
_m_connect_retries = telemetry.counter(
    "transport_connect_retries",
    "connect() attempts that failed and were retried")
# Selector-engine egress queue surface (docs/transport.md): aggregate
# queued bytes across every channel's write queue, the high-water mark
# ever observed, and how often a sender blocked at the TX_HIGH_WATER
# gate. Aggregates (not per-channel labels): a pod-scale master has an
# unbounded channel-id stream that would instantly fold into the
# registry's overflow series; exact per-channel depth remains readable
# on the channel objects.
_g_txq_bytes = telemetry.gauge(
    "transport_evloop_tx_queue_bytes",
    "Bytes queued for the selector loop's coalescing flush, all "
    "channels")
_g_txq_peak = telemetry.gauge(
    "transport_evloop_tx_queue_peak_bytes",
    "High-water mark of any single channel's egress queue")
_m_txq_highwater_waits = telemetry.counter(
    "transport_evloop_tx_highwater_waits",
    "Sends that blocked on the per-channel TX_HIGH_WATER gate")
_txq_peak_seen = 0  # unlocked monotone max; races only under-report

#: Wire overhead per frame: 8-byte length header + 1-byte type prefix.
#: Aliased from framing.FRAME_OVERHEAD — the single billing authority
#: shared with the accounting plane's ``wire_size`` — so every engine
#: (threads/selector/shm) and every biller count the same 9 bytes.
_FRAME_OVERHEAD = FRAME_OVERHEAD

MODES = ("r", "w", "rw", "req", "rep")

_SENTINEL = object()
_WAKE = object()  # recv_req nudge (Endpoint.wake), never delivered as data

# Transport frame types (first payload byte). Only the w→r push pattern
# uses credits; rw/req/rep frames are always DATA.
_T_DATA = b"\x00"
_T_CREDIT = b"\x01"
_T_CREDIT_BYTE = _T_CREDIT[0]  # int compare — no per-frame slice alloc
# 0x02 marks shm-negotiation control frames (fiber_tpu/transport/shm.py).
# They live strictly in the pre-data handshake; one reaching handle_frame
# means a timed-out handshake race, and the ingress drops it silently so
# the race can never corrupt the data stream.
_T_SHM = b"\x02"
_T_SHM_BYTE = _T_SHM[0]
#: The shm doorbell: one complete 9-byte wire frame whose payload is a
#: single 0x02 byte. A writer sends it on the companion TCP socket to
#: wake a reader parked in select(); the shm read loop drops it before
#: handle_frame so it never touches the wire counters (exact tx/rx
#: parity for data frames is a billing invariant).
_SHM_DOORBELL = pack_header(1) + _T_SHM
_CREDIT = struct.Struct(">I")

#: Standing credit window granted per peer by bound r-endpoints (fan-in
#: ingress like pool result streams): large enough to never throttle, small
#: enough to bound memory.
DEFAULT_CREDIT_WINDOW = 4096


class TransportClosed(OSError):
    pass


class _Inbox:
    """FIFO of (channel, frame) with blocking get and a true (non-consuming)
    peek, so poll() can never reorder frames."""

    def __init__(self) -> None:
        self._items: "collections.deque" = collections.deque()
        self._cond = threading.Condition()

    def put(self, item) -> None:
        with self._cond:
            self._items.append(item)
            self._cond.notify_all()

    def put_many(self, items) -> None:
        """Append a batch under one lock round and one notify — the
        selector loop delivers every frame decoded from one readiness
        event this way instead of paying a condition dance per frame."""
        with self._cond:
            self._items.extend(items)
            self._cond.notify_all()

    def get(self, timeout: Optional[float] = None):
        with self._cond:
            if not self._items:  # fast path: skip the predicate closure
                if not self._cond.wait_for(
                        lambda: len(self._items) > 0, timeout):
                    return _SENTINEL_EMPTY
            return self._items.popleft()

    def peek(self, timeout: Optional[float] = None):
        """Return the head item without removing it (or _SENTINEL_EMPTY)."""
        with self._cond:
            if not self._cond.wait_for(lambda: len(self._items) > 0, timeout):
                return _SENTINEL_EMPTY
            return self._items[0]

    def empty(self) -> bool:
        return not self._items

    def qsize(self) -> int:
        return len(self._items)

    def drop_leading(self, sentinel) -> None:
        """Remove consecutive head items identical to ``sentinel`` (used
        by poll() to consume wake nudges, which are not data)."""
        with self._cond:
            while self._items and self._items[0] is sentinel:
                self._items.popleft()


_SENTINEL_EMPTY = object()


class _Channel:
    """One TCP connection. Its I/O engine is the owning endpoint's
    ``transport_io`` mode: ``"threads"`` runs the classic blocking
    reader thread per connection; ``"selector"`` hands the socket to
    the process-wide poller (fiber_tpu/transport/evloop.py) — no
    per-connection thread, writes through a coalescing queue. Per-frame
    semantics (credits, chaos ingress hook, inbox delivery, counters)
    live in :meth:`handle_frame`, shared by both engines so they cannot
    diverge."""

    _ids = itertools.count()

    def __init__(self, sock: pysocket.socket, owner: "Endpoint",
                 shm=None) -> None:
        self.sock = sock
        self.owner = owner
        self.cid = next(self._ids)
        self.alive = True
        # shm engine: a negotiated ShmPair replaces the socket as the
        # data path (the socket stays open for EOF-based peer-death
        # detection and to heal handshake races). None = plain TCP —
        # including the fallback channels of an endpoint whose _io is
        # "shm" (those run the threads engine).
        self.shm = shm
        self.credit = 0  # how many frames the peer is ready to accept
        self.replenish_owed = 0  # batched standing-window replenish
        self.last_rx: Optional[float] = None  # monotonic, any frame kind
        # Exact wire-volume counters at the framing boundary (monotonic;
        # single-writer each: rx by the I/O engine, tx under _send_lock /
        # _tx_cond — so reads need no extra locking). flushes_tx counts
        # egress syscalls: == frames_tx on the threads path, <= frames_tx
        # under the selector loop's small-frame coalescing.
        self.bytes_rx = 0
        self.bytes_tx = 0
        self.frames_rx = 0
        self.frames_tx = 0
        self.flushes_tx = 0
        self._send_lock = threading.Lock()
        sock.setsockopt(pysocket.IPPROTO_TCP, pysocket.TCP_NODELAY, 1)
        self._reader: Optional[threading.Thread] = None
        self._io_selector = shm is None and owner._io == "selector"
        self._loop = None
        if self._io_selector:
            from fiber_tpu.transport.evloop import get_loop

            self._loop = get_loop()
            self._fb = FrameBuffer()
            self._txq: "collections.deque" = collections.deque()
            self._tx_head: "collections.deque" = collections.deque()
            self._tx_bytes = 0
            self._tx_cond = threading.Condition()
            self._tx_dirty = False
            self._tx_closing = False
            self._tx_inflight = False
            self._registered = False
            self._ev_mask = 0
            self._stall_until: Optional[float] = None
            self._stall_pending = None

    def start_io(self) -> None:
        """Attach the connection to its I/O engine (reader thread,
        shm poll loop, or the selector loop)."""
        if self._io_selector:
            self._loop.register_channel(self)
            return
        self._reader = threading.Thread(
            target=self._shm_read_loop if self.shm is not None
            else self._read_loop,
            name=f"fiber-chan-{self.cid}",
            daemon=True,
        )
        self._reader.start()

    # -- shared ingress ---------------------------------------------------
    def handle_frame(self, frame, defer_stall: bool = False,
                     batch=None, registry: bool = True):
        """One received frame, decoded: counters, credit accounting, the
        chaos ingress hook, and inbox delivery — identical under both
        I/O engines. Returns None normally. When ``defer_stall`` and a
        chaos plan injects an ingress stall, returns ``(stall_s, drop)``
        WITHOUT sleeping so the selector loop can park just this channel
        (sleeping the poller would stall every channel in the process);
        the caller delivers/drops the frame at the deadline."""
        # Observable silence: the failure detector reads last_rx instead
        # of opening extra sockets; credit frames count too (any byte
        # proves the peer's stack is alive).
        self.last_rx = self.owner.last_rx = time.monotonic()
        wire = len(frame) + 8  # + length header
        self.bytes_rx += wire
        self.frames_rx += 1
        if registry:  # False: the selector loop bumps the registry
            _m_bytes_rx.inc(wire)  # twins once per decode batch
            _m_frames_rx.inc()
        if frame and frame[0] == _T_CREDIT_BYTE:
            (n,) = _CREDIT.unpack(bytes(frame[1:5]))
            with self.owner._chan_lock:
                self.credit += n
                self.owner._chan_lock.notify_all()
            return None
        if frame and frame[0] == _T_SHM_BYTE:
            # Stray shm-handshake frame (a timed-out negotiation race):
            # control traffic, never data — counted as wire, dropped.
            return None
        # Chaos injection point (no-op unless a plan is active): bound-r
        # ingress only — REQ/REP and connected endpoints have lockstep
        # protocols a dropped/stalled frame would wedge rather than
        # degrade, which is not the fault being modeled.
        plan = chaos._plan
        if (plan is not None and self.owner._is_bound
                and self.owner.mode == "r"):
            stall_s, drop = plan.recv_frame_actions(self)
            if stall_s > 0.0:
                from fiber_tpu.telemetry.flightrec import FLIGHT

                if defer_stall:
                    # The selector loop PARKS this one channel instead
                    # of sleeping the poller (evloop._readable).
                    FLIGHT.record("transport", "park",
                                  stall_s=stall_s, cid=self.cid)
                    return (stall_s, drop)
                FLIGHT.record("transport", "stall",
                              stall_s=stall_s, cid=self.cid)
                time.sleep(stall_s)
            if drop:
                # Dropped: model LOSS, not throttling — hand the
                # consumed window slot back so the sender's standing
                # credit doesn't shrink per drop.
                try:
                    self.send_credit(1)
                except OSError:
                    pass
                return None
        self.deliver_data(frame, batch)
        return None

    def deliver_data(self, frame, batch=None) -> None:
        """Strip the 1-byte type tag and hand the payload to the owner's
        inbox. Large frames are stripped with a memoryview (the old
        ``frame[1:]`` slice re-copied every host-plane tensor); small
        ones stay plain bytearray slices."""
        if len(frame) > SMALL_FRAME_MAX:
            payload = memoryview(frame)[1:]
        else:
            payload = frame[1:]
        owner = self.owner
        # Arrival consumes the credit that pulled it: count each
        # undelivered frame ONCE (inbox qsize), so the prefetch window
        # arithmetic in _maybe_grant doesn't double-count frames as both
        # queued and outstanding. Enqueue and decrement under the lock
        # _maybe_grant holds: decrementing before enqueueing (the old
        # order) let a concurrent grant see neither the queued frame nor
        # the outstanding credit and over-grant past the parked-frame
        # bound (advisor, round 2). _Inbox locks are leaf-level and
        # readers never block holding _recv_lock, so this nesting cannot
        # deadlock.
        if owner._demand_driven:
            with owner._recv_lock:
                owner._inbox.put((self, payload))
                if owner._credit_outstanding > 0:
                    owner._credit_outstanding -= 1
        elif batch is not None:
            batch.append((self, payload))
        else:
            owner._inbox.put((self, payload))

    def _read_loop(self) -> None:
        reader = FrameReader(self.sock)
        try:
            while True:
                self.handle_frame(reader.recv())
        except (ConnectionClosed, OSError):
            pass
        finally:
            self.owner._drop_channel(self)

    def _shm_read_loop(self) -> None:
        """shm-engine ingress: drain the rx ring through FrameBuffer
        (the ring quacks like a non-blocking socket) and run every frame
        through the shared handle_frame ingress — credits, chaos hook,
        counters, inbox delivery all behave exactly as under the other
        engines. The companion TCP socket serves three jobs: EOF is
        peer death (the ring itself has no hangup signal); stray TCP
        *frames* decode through the same ingress — which heals the one
        pathological handshake race (we ACKed shm but the dialer timed
        out onto TCP); and it carries the writer's doorbell. When both
        sources are idle the loop raises the ring's waiting flag,
        re-checks the ring (closes the flag-raised-too-late race), and
        parks in select() on the socket — zero CPU while idle. Pure
        doorbell frames (payload == 0x02, nothing else) are dropped
        BEFORE handle_frame so they never perturb the exact wire
        counters. The select timeout bounds the one missed-wakeup
        window a cross-process flag handoff leaves open (store/load
        reordering between the position advance and the flag check)."""
        from fiber_tpu.transport.shm import (
            _m_shm_bytes_rx, _m_shm_frames_rx)

        ring = self.shm.rx
        ring_fb = FrameBuffer()
        sock_fb = FrameBuffer()
        try:
            self.sock.setblocking(False)
        except OSError:
            pass
        try:
            while self.alive:
                progressed = False
                try:
                    if ring_fb.fill(ring):
                        progressed = True
                except BlockingIOError:
                    pass
                while True:
                    frame = ring_fb.pop()
                    if frame is None:
                        break
                    progressed = True
                    _m_shm_bytes_rx.inc(len(frame) + 8)
                    _m_shm_frames_rx.inc()
                    self.handle_frame(frame)
                try:
                    if sock_fb.fill(self.sock) == 0:
                        return  # EOF: peer is gone
                    progressed = True
                except (BlockingIOError, InterruptedError):
                    pass
                while True:
                    frame = sock_fb.pop()
                    if frame is None:
                        break
                    progressed = True
                    if frame == _T_SHM:
                        continue  # doorbell: we are, demonstrably, awake
                    self.handle_frame(frame)
                if progressed:
                    continue
                ring.set_waiting()
                try:
                    if ring.buffered() == 0:
                        select.select([self.sock], [], [], 0.05)
                finally:
                    ring.clear_waiting()
        except OSError:
            pass
        finally:
            self.owner._drop_channel(self)

    # -- egress -----------------------------------------------------------
    def _tx_enqueue(self, pieces, wire_bytes: int) -> None:
        """Queue frame pieces for the selector loop's coalescing flush.
        ``pieces`` is a list of ``(buffer, frame_end)`` tuples; the
        counters commit here — the frame is on its way to the wire (the
        same guarantee a blocking sendall's return gave: kernel-buffered,
        not yet acknowledged). Blocks past the queue's high-water mark
        (bounded memory), except on the loop thread itself, which must
        never wait on its own drain.

        Large frames take an inline fast path when nothing is queued or
        in flight: the caller's own thread pushes the iovec until the
        kernel buffer pushes back (EAGAIN), so a worker streaming
        tensors overlaps its copy-to-kernel with the loop's ingress work
        exactly like a dedicated sender thread would — only the EAGAIN
        remainder is left for the poller."""
        from fiber_tpu.transport.evloop import TX_HIGH_WATER

        global _txq_peak_seen
        loop = self._loop
        with self._tx_cond:
            if not self.alive or self._tx_closing:
                raise TransportClosed("channel closed")
            if (self._tx_bytes > TX_HIGH_WATER
                    and threading.current_thread() is not loop.thread):
                _m_txq_highwater_waits.inc()
                from fiber_tpu.telemetry.flightrec import FLIGHT

                FLIGHT.record("transport", "highwater",
                              queued=self._tx_bytes,
                              reason="egress queue past TX_HIGH_WATER; "
                                     "sender blocked")
                while (self._tx_bytes > TX_HIGH_WATER and self.alive
                       and not self._tx_closing):
                    self._tx_cond.wait(0.5)
                if not self.alive or self._tx_closing:
                    raise TransportClosed("channel closed")
            queued_bytes = wire_bytes
            if (wire_bytes > SMALL_FRAME_MAX and self._registered
                    and not self._txq and not self._tx_inflight):
                pieces = self._inline_send(pieces)
                if pieces is None:  # fully on the wire
                    self.bytes_tx += wire_bytes
                    self.frames_tx += 1
                    return
                # Only the EAGAIN remainder is queued: accounting the
                # full frame here would inflate _tx_bytes by the
                # inline-sent portion on every partial send (the flush
                # only ever decrements what it actually wrote), walking
                # the queue depth toward a permanent high-water block.
                queued_bytes = sum(len(p) for p, _end in pieces)
            self._txq.extend(pieces)
            self._tx_bytes += queued_bytes
            _g_txq_bytes.inc(queued_bytes)
            if self._tx_bytes > _txq_peak_seen:
                _txq_peak_seen = self._tx_bytes
                _g_txq_peak.set(self._tx_bytes)
            self.bytes_tx += wire_bytes
            self.frames_tx += 1
            dirty = self._tx_dirty
            self._tx_dirty = True
        if not dirty:
            loop.request_flush(self)

    def _inline_send(self, pieces):
        """Under the tx condition (order is safe: queue empty, loop not
        flushing): vectored non-blocking sends until done or EAGAIN.
        Returns None when everything shipped, else the remaining pieces
        (partial head trimmed to a memoryview). OSError propagates like
        a failed blocking send."""
        iov = [p for p, _end in pieces]
        while iov:
            try:
                sent = self.sock.sendmsg(iov)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                raise
            if sent <= 0:
                break
            self.flushes_tx += 1
            while sent and iov:
                n = len(iov[0])
                if sent >= n:
                    sent -= n
                    iov.pop(0)
                    pieces.pop(0)
                else:
                    iov[0] = memoryview(iov[0])[sent:]
                    pieces[0] = (iov[0], pieces[0][1])
                    sent = 0
        return pieces if iov else None

    def _shm_doorbell(self) -> None:
        """Wake a peer parked in select(): one 9-byte 0x02 frame on the
        companion socket. Called under _send_lock (concurrent bells must
        not interleave — a torn frame would desync the socket stream the
        heal path decodes). EAGAIN before the first byte means unread
        bells already fill the socket buffer — the peer has wakeups
        pending, so dropping this one is safe. EAGAIN mid-frame is
        different: the frame MUST complete or the stream desyncs, and
        the peer drains the socket every loop pass, so a brief retry
        always lands."""
        data = memoryview(_SHM_DOORBELL)
        sent_any = False
        while data.nbytes:
            try:
                n = self.sock.send(data)
            except (BlockingIOError, InterruptedError):
                if not sent_any:
                    return
                time.sleep(0.0002)
                continue
            except OSError:
                return
            if n <= 0:
                return
            sent_any = True
            data = data[n:]

    def send(self, payload: bytes) -> None:
        wire = len(payload) + _FRAME_OVERHEAD
        if self.shm is not None:
            from fiber_tpu.transport.shm import (
                _m_shm_bytes_tx, _m_shm_frames_tx)

            ring = self.shm.tx
            with self._send_lock:
                if len(payload) > SMALL_FRAME_MAX:
                    # Large path: header+tag first, then the payload
                    # memoryview straight into the ring — ONE copy, the
                    # zero-copy promise of the engine.
                    bell = ring.write(pack_header(len(payload) + 1)
                                      + _T_DATA)
                    ring.write(payload)
                else:
                    if not isinstance(payload, (bytes, bytearray)):
                        payload = bytes(payload)
                    bell = ring.write(pack_header(len(payload) + 1)
                                      + _T_DATA + payload)
                self.bytes_tx += wire
                self.frames_tx += 1
                self.flushes_tx += 1
                if bell or ring.reader_waiting:
                    self._shm_doorbell()
            _m_bytes_tx.inc(wire)
            _m_frames_tx.inc()
            _m_shm_bytes_tx.inc(wire)
            _m_shm_frames_tx.inc()
            return
        if self._io_selector:
            header = pack_header(len(payload) + 1)
            if len(payload) > SMALL_FRAME_MAX:
                # Scatter-gather shape: tiny header+tag piece, then the
                # payload as one uncopied iovec entry.
                pieces = [(header + _T_DATA, False),
                          (memoryview(payload), True)]
            else:
                if not isinstance(payload, (bytes, bytearray)):
                    payload = bytes(payload)
                pieces = [(header + _T_DATA + payload, True)]
            self._tx_enqueue(pieces, wire)
        else:
            with self._send_lock:
                send_frame(self.sock, payload, prefix=_T_DATA)
                self.bytes_tx += wire
                self.frames_tx += 1
                self.flushes_tx += 1
        _m_bytes_tx.inc(wire)
        _m_frames_tx.inc()

    def send_credit(self, n: int) -> None:
        wire = _CREDIT.size + _FRAME_OVERHEAD
        if self.shm is not None:
            from fiber_tpu.transport.shm import (
                _m_shm_bytes_tx, _m_shm_frames_tx)

            body = _T_CREDIT + _CREDIT.pack(n)
            ring = self.shm.tx
            with self._send_lock:
                bell = ring.write(pack_header(len(body)) + body)
                self.bytes_tx += wire
                self.frames_tx += 1
                self.flushes_tx += 1
                # Credits must doorbell too: a starved sender is blocked
                # on THIS frame reaching the peer's parked read loop.
                if bell or ring.reader_waiting:
                    self._shm_doorbell()
            _m_shm_bytes_tx.inc(wire)
            _m_shm_frames_tx.inc()
            return
        if self._io_selector:
            body = _T_CREDIT + _CREDIT.pack(n)
            self._tx_enqueue(
                [(pack_header(len(body)) + body, True)], wire)
            return
        with self._send_lock:
            send_frame(self.sock, _T_CREDIT + _CREDIT.pack(n))
            self.bytes_tx += wire
            self.frames_tx += 1
            self.flushes_tx += 1

    def close(self) -> None:
        self.alive = False
        if self.shm is not None:
            # Closing the rings wakes a writer blocked on a full ring
            # (RingClosed is an OSError, so it rides the normal drop
            # paths); closing the socket EOFs the peer's read loop.
            self.shm.close()
        if self._io_selector and self._loop is not None:
            self._loop.close_channel(self)
            return
        try:
            self.sock.close()
        except OSError:
            pass


class Endpoint:
    def __init__(self, mode: str, prefetch: int = 1,
                 io: Optional[str] = None) -> None:
        if mode not in MODES:
            raise ValueError(f"invalid endpoint mode {mode!r}")
        self.mode = mode
        # I/O engine for this endpoint's channels: "selector" (one
        # process-wide poller, O(1) threads in peer count, coalesced
        # vectored sends) or "threads" (blocking reader thread per
        # connection). Resolved once at construction from the
        # transport_io config knob; ``io=`` overrides for tests/benches
        # that compare the engines side by side. docs/transport.md.
        self._io = io or str(getattr(config.get(), "transport_io",
                                     "selector"))
        if self._io not in ("selector", "threads", "shm"):
            raise ValueError(f"invalid transport_io {self._io!r}")
        # r-mode credit window: 1 = pure demand-driven (a dead consumer
        # never has frames parked beyond what a blocked reader asked
        # for); >1 pipelines a bounded window for throughput.
        self.prefetch = max(1, int(prefetch))
        self._inbox = _Inbox()
        self._channels: List[_Channel] = []
        self._chan_lock = threading.Condition()
        self._rr = 0
        self._listener: Optional[pysocket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        # Unauthenticated dialers mid-handshake: the shared evict-oldest
        # pool (fiber_tpu/utils/serve.py PreauthPool documents the
        # protocol — drop-newest would let idle holders lock real peers
        # out for a whole handshake-timeout window).
        from fiber_tpu.utils.serve import PreauthPool

        self._preauth = PreauthPool(64)
        self._closed = False
        self._reply_to: Optional[_Channel] = None
        self.addr: Optional[str] = None
        self._is_bound = False
        # Demand-driven credit state for *connected* r-endpoints (queue
        # consumers): credit is granted only when a reader actually blocks
        # in recv(), so undelivered frames stay in the upstream device
        # instead of a dead consumer's socket buffer.
        self._credit_outstanding = 0
        self._waiting_readers = 0
        self._recv_lock = threading.Lock()
        self._wake_queued = False  # coalesces Endpoint.wake nudges
        #: Monotonic timestamp of the newest frame received on ANY of
        #: this endpoint's channels (None until the first). The failure
        #: detector observes silence through this instead of extra
        #: sockets; per-connection granularity lives on _Channel.last_rx.
        self.last_rx: Optional[float] = None
        # Wire totals of channels that have already been dropped, so the
        # endpoint aggregates (bytes_tx etc.) stay monotonic across
        # reconnects.
        self._dead_wire = [0, 0, 0, 0, 0]  # b_rx, b_tx, f_rx, f_tx, fl_tx

    # -- wiring -----------------------------------------------------------
    def bind(self, ip: str, port: int = 0) -> str:
        """Listen on ``ip`` and return the advertised address
        ``tcp://ip:port``. The listener binds that specific interface — a
        wildcard bind would expose the pickle-carrying data plane on every
        NIC even for loopback-only backends. Non-loopback binds demand a
        real cluster key (the default is public knowledge)."""
        if (ip not in ("127.0.0.1", "localhost")
                and auth.auth_enabled()
                and auth.cluster_key() == auth.DEFAULT_KEY.encode()):
            raise TransportClosed(
                "refusing to bind the data plane on non-loopback "
                f"{ip!r} with the default cluster key; set "
                "FIBER_CLUSTER_KEY on every host (fiber-tpu up generates "
                "one), or FIBER_DATA_AUTH=0 on an isolated network"
            )
        listener = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        listener.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
        if port:
            listener.bind((ip, port))
        else:
            _, port = random_port_bind(listener, host=ip)
        listener.listen(512)
        self._listener = listener
        self._is_bound = True
        self.addr = f"tcp://{ip}:{port}"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fiber-ep-accept", daemon=True
        )
        self._accept_thread.start()
        return self.addr

    def connect(self, addr: str, retries: int = 3,
                retry_base: float = 0.1) -> "Endpoint":
        """Dial ``addr`` with bounded exponential-backoff retry on
        connection errors (``retries`` extra attempts, delays
        ``retry_base * 2^k`` capped at 2 s). Retry covers exactly the
        window a restarting listener or a momentarily full accept
        backlog creates; an *authentication* failure is terminal — the
        key won't get righter by redialing. ``retries=0`` restores the
        old single-shot behavior (watchdog-style callers that must fail
        fast when the master is gone)."""
        host, port = parse_addr(addr)
        attempt = 0
        while True:
            try:
                sock = pysocket.create_connection((host, port),
                                                  timeout=30.0)
                break
            except OSError:
                if attempt >= retries:
                    raise
                _m_connect_retries.inc()
                from fiber_tpu.telemetry.flightrec import FLIGHT

                FLIGHT.record("transport", "retry", addr=addr,
                              attempt=attempt + 1)
                time.sleep(min(retry_base * (2 ** attempt), 2.0))
                attempt += 1
        sock.settimeout(None)
        if auth.auth_enabled():
            try:
                auth.client_handshake(sock)
            except (OSError, auth.AuthenticationError):
                sock.close()
                raise
        self.addr = addr
        if self._io == "shm":
            # Negotiate rings strictly before any data frame; a binder
            # that doesn't speak shm answers with its normal first wire
            # frame, which comes back as `leftover` and is re-injected
            # through the shared ingress so nothing is lost.
            from fiber_tpu.transport import shm as shm_mod

            pair, leftover = shm_mod.negotiate_dialer(sock)
            self._add_channel(sock, shm=pair, initial_frame=leftover)
            return self
        self._add_channel(sock)
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            if auth.auth_enabled():
                # Handshake off-thread: a slow or hostile dialer must not
                # stall accepts for legitimate peers. At the cap the
                # OLDEST unauthenticated holder is evicted (shutdown
                # wakes its blocked recv with EOF; its thread cleans up)
                # so a standing flood cannot lock legitimate peers out.
                evict = self._preauth.admit(sock)
                if evict is not None:
                    try:
                        evict.shutdown(pysocket.SHUT_RDWR)
                    except OSError:
                        pass
                threading.Thread(
                    target=self._authenticate_and_add, args=(sock,),
                    name="fiber-ep-auth", daemon=True,
                ).start()
            elif self._io == "shm":
                # Negotiation blocks on the dialer's first frame —
                # off-thread so accepts keep flowing.
                threading.Thread(
                    target=self._negotiate_and_add, args=(sock,),
                    name="fiber-ep-shm-neg", daemon=True,
                ).start()
            else:
                self._add_channel(sock)

    def _negotiate_and_add(self, sock: pysocket.socket) -> None:
        from fiber_tpu.transport import shm as shm_mod

        try:
            pair, leftover = shm_mod.negotiate_binder(sock)
        except OSError:
            try:
                sock.close()
            except OSError:
                pass
            return
        self._add_channel(sock, shm=pair, initial_frame=leftover)

    def _authenticate_and_add(self, sock: pysocket.socket) -> None:
        try:
            auth.server_handshake(sock)
        except (OSError, auth.AuthenticationError) as err:
            if not self._preauth.complete(sock):
                # Evicted holders fail by design — logging each would
                # amplify a flood into the log.
                logger.warning(
                    "rejecting unauthenticated data-plane peer: %s", err)
            try:
                sock.close()
            except OSError:
                pass
            return
        # Success — promote ONLY if the evictor didn't pop us while the
        # handshake was finishing (its shutdown may land any moment; a
        # channel built on that socket would die confusingly mid-use).
        if self._preauth.complete(sock):
            try:
                sock.close()
            except OSError:
                pass
            return
        if self._io == "shm":
            self._negotiate_and_add(sock)
            return
        self._add_channel(sock)

    def _add_channel(self, sock: pysocket.socket, shm=None,
                     initial_frame=None) -> None:
        chan = _Channel(sock, self, shm=shm)
        with self._chan_lock:
            self._channels.append(chan)
            self._chan_lock.notify_all()
        if initial_frame is not None:
            # A wire frame consumed during shm negotiation (the peer
            # spoke plain TCP first): run it through the shared ingress
            # before the I/O engine starts, preserving frame order.
            chan.handle_frame(initial_frame)
        # Every channel gets an I/O engine: data/credit frames for
        # receiving modes, EOF detection for send-only ones.
        chan.start_io()
        if self.mode == "r" and self._is_bound:
            # Fan-in ingress (e.g. pool result streams): standing credit
            # window per peer, replenished as frames are consumed.
            try:
                chan.send_credit(int(getattr(
                    config.get(), "transport_credit_window",
                    DEFAULT_CREDIT_WINDOW)) or DEFAULT_CREDIT_WINDOW)
            except OSError:
                pass

    def _drop_channel(self, chan: _Channel) -> None:
        chan.alive = False
        with self._chan_lock:
            if chan in self._channels:
                self._channels.remove(chan)
                dead = self._dead_wire
                dead[0] += chan.bytes_rx
                dead[1] += chan.bytes_tx
                dead[2] += chan.frames_rx
                dead[3] += chan.frames_tx
                dead[4] += chan.flushes_tx
            now_empty = not self._channels
        chan.close()
        # A connected endpoint has no listener: losing its only channel is
        # final, so wake blocked receivers with closure instead of letting
        # them hang (multiprocessing raises EOFError here).
        if now_empty and not self._is_bound and not self._closed:
            self._inbox.put(_SENTINEL)

    # -- data path --------------------------------------------------------
    def send(self, payload: bytes, timeout: Optional[float] = None) -> None:
        plan = chaos._plan
        if plan is not None:
            plan.on_send_frame()  # latency injection (no-op by default)
        if self.mode == "r":
            raise TransportClosed("receive-only endpoint")
        if self.mode == "rep":
            chan = self._reply_to
            if chan is None:
                raise TransportClosed("rep endpoint has no request to answer")
            self._reply_to = None
            chan.send(payload)
            return
        use_credit = self.mode == "w"
        while True:
            with self._chan_lock:
                if self._closed:
                    raise TransportClosed("endpoint closed")
                chan = None
                live = self._channels
                if live:
                    # Strict message-level round-robin (the tested fairness
                    # contract for push queues), gated on peer credit in
                    # w-mode so frames only go to peers ready to take them.
                    n = len(live)
                    for step in range(1, n + 1):
                        cand = live[(self._rr + step) % n]
                        if not use_credit or cand.credit > 0:
                            self._rr = (self._rr + step) % n
                            chan = cand
                            if use_credit:
                                cand.credit -= 1
                            break
                if chan is None:
                    if not self._chan_lock.wait(timeout):
                        raise TimeoutError(
                            "no connected peer ready to accept"
                        ) from None
            if chan is not None:
                try:
                    chan.send(payload)
                    return
                except OSError:
                    self._drop_channel(chan)

    def _maybe_grant(self, pipeline: bool = True) -> None:
        """Credit for connected r-endpoints. With prefetch=1 (default):
        grant one credit per reader actually waiting, never more (a dead
        consumer therefore never has frames parked in its socket
        buffer). With prefetch>1: keep a bounded window of credits in
        flight once a reader has engaged — higher throughput, at most
        `prefetch` undelivered frames pulled toward a consumer that
        dies. ``pipeline=False`` (the poll path) grants demand-only:
        polling is not consuming, so an empty()-style caller must not
        hoard the window."""
        with self._recv_lock:
            want = self._waiting_readers
            if pipeline and self._waiting_readers:
                want = max(want, self.prefetch)
            grant = want - self._inbox.qsize() - self._credit_outstanding
            if grant <= 0:
                return
            self._credit_outstanding += grant
        with self._chan_lock:
            chan = self._channels[0] if self._channels else None
        if chan is not None:
            try:
                chan.send_credit(grant)
            except OSError:
                pass

    @property
    def _demand_driven(self) -> bool:
        return self.mode == "r" and not self._is_bound

    def recv(self, timeout: Optional[float] = None) -> bytes:
        if self.mode == "w":
            raise TransportClosed("send-only endpoint")
        if self.mode == "rep":
            # One inbox protocol: the rep arm IS recv_req with the
            # classic implicit-reply convention layered on.
            frame, chan = self.recv_req(timeout)
            self._reply_to = chan
            return frame
        demand = self._demand_driven
        if demand:
            with self._recv_lock:
                self._waiting_readers += 1
            self._maybe_grant()
        item = self._inbox.get(timeout=timeout)
        if item is _SENTINEL_EMPTY:
            if demand:
                with self._recv_lock:
                    self._waiting_readers -= 1
            raise TimeoutError("recv timed out")
        if item is _SENTINEL:
            self._inbox.put(_SENTINEL)  # wake other readers too
            if demand:
                with self._recv_lock:
                    self._waiting_readers -= 1
            raise TransportClosed("endpoint closed")
        chan, frame = item
        if demand:
            with self._recv_lock:
                self._waiting_readers -= 1
            self._maybe_grant()  # top up for any other blocked readers
        elif self.mode == "r":
            # Bound ingress: replenish the standing window, batched — one
            # credit frame per 32 data frames instead of per frame (the
            # window is 4096, so senders never starve on the float). The
            # counter is guarded: concurrent recv() callers must not lose
            # increments (each loss permanently shrinks the window).
            owed = 0
            with self._recv_lock:
                chan.replenish_owed += 1
                if chan.replenish_owed >= 32:
                    owed, chan.replenish_owed = chan.replenish_owed, 0
            if owed:
                try:
                    chan.send_credit(owed)
                except OSError:
                    pass
        return frame

    def recv_req(self, timeout: Optional[float] = None):
        """rep-mode receive that returns ``(payload, reply_handle)``
        instead of arming the implicit ``_reply_to`` slot — so a server
        can hold several requests open and answer them OUT OF ORDER
        (the pool's reservation-gated handout parks "ready" requests
        from busy workers while idle ones get first chunks). Answer
        with :meth:`reply`. A :meth:`wake` nudge surfaces as
        ``TimeoutError`` — the caller's timeout turn, just early."""
        if self.mode != "rep":
            raise TransportClosed("recv_req is for rep endpoints")
        item = self._inbox.get(timeout=timeout)
        if item is _SENTINEL_EMPTY or item is _WAKE:
            if item is _WAKE:
                self._wake_queued = False
            raise TimeoutError("recv timed out")
        if item is _SENTINEL:
            self._inbox.put(_SENTINEL)  # wake other readers too
            raise TransportClosed("endpoint closed")
        chan, frame = item
        return frame, chan

    def wake(self) -> None:
        """Nudge a reader blocked in :meth:`recv_req` to re-run its
        loop turn now (used by the pool: a result arriving or a task
        being queued can clear a parked request's gate — without the
        nudge the handout would notice only at its next timeout).
        Coalesced: at most one nudge sits in the inbox at a time (the
        clear-after-pop race can drop a nudge, which costs one recv
        timeout turn at worst — the fallback that existed anyway).

        rep-mode only: plain :meth:`recv` unpacks inbox items as
        ``(chan, frame)`` and would crash on the bare ``_WAKE``
        sentinel — only ``recv_req``/``poll`` know to skip it."""
        if self.mode != "rep":
            raise RuntimeError(
                f"wake() needs a rep-mode endpoint, not {self.mode!r}")
        if self._wake_queued:
            return
        self._wake_queued = True
        self._inbox.put(_WAKE)

    @staticmethod
    def reply(handle, payload: bytes) -> None:
        """Answer one request taken via :meth:`recv_req`. Raises
        ``OSError``/``TransportClosed`` if that requester is gone."""
        if not handle.alive:
            raise TransportClosed("requester disconnected")
        handle.send(payload)

    def poll(self, timeout: Optional[float] = 0.0) -> bool:
        """True if a data frame is ready (or arrives within timeout).
        Never consumes or reorders DATA frames (wake nudges are not
        data and are consumed here so they can't masquerade as one)."""
        self._inbox.drop_leading(_WAKE)
        self._wake_queued = False
        if not self._inbox.empty():
            return not self._is_closed_head()
        if not timeout:
            return False
        if self._demand_driven:
            with self._recv_lock:
                self._waiting_readers += 1
            self._maybe_grant(pipeline=False)
        try:
            item = self._inbox.peek(timeout=timeout)
            return item is not _SENTINEL_EMPTY and item is not _SENTINEL
        finally:
            if self._demand_driven:
                with self._recv_lock:
                    self._waiting_readers -= 1

    def _is_closed_head(self) -> bool:
        head = self._inbox.peek(0)
        return head is _SENTINEL

    # -- wire-volume counters (framing boundary, exact) -------------------
    def _wire_total(self, idx: int, attr: str) -> int:
        with self._chan_lock:
            return self._dead_wire[idx] + sum(
                getattr(c, attr) for c in self._channels)

    @property
    def bytes_rx(self) -> int:
        """Monotonic wire bytes received across every channel this
        endpoint ever had (length headers included)."""
        return self._wire_total(0, "bytes_rx")

    @property
    def bytes_tx(self) -> int:
        return self._wire_total(1, "bytes_tx")

    @property
    def frames_rx(self) -> int:
        return self._wire_total(2, "frames_rx")

    @property
    def frames_tx(self) -> int:
        return self._wire_total(3, "frames_tx")

    @property
    def flushes_tx(self) -> int:
        """Egress syscalls across every channel this endpoint ever had:
        equals ``frames_tx`` on the threads path; under the selector
        loop's coalescing, N small frames queued between wakeups leave
        in one flush, so this counts how often that actually paid off
        (tested: tests/test_transport.py coalescing suite)."""
        return self._wire_total(4, "flushes_tx")

    # -- lifecycle --------------------------------------------------------
    def peer_count(self) -> int:
        with self._chan_lock:
            return len(self._channels)

    def wait_for_peers(self, n: int, timeout: Optional[float] = None) -> bool:
        """Block until at least n peers are connected."""
        with self._chan_lock:
            return self._chan_lock.wait_for(
                lambda: len(self._channels) >= n, timeout
            )

    def fileno(self) -> int:
        """Fd of the sole channel (connected endpoints only)."""
        with self._chan_lock:
            if len(self._channels) != 1:
                raise ValueError(
                    "fileno() requires exactly one connected channel"
                )
            return self._channels[0].sock.fileno()

    def close(self) -> None:
        with self._chan_lock:
            if self._closed:
                return
            self._closed = True
            channels = list(self._channels)
            self._channels = []
            self._chan_lock.notify_all()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for chan in channels:
            chan.close()
        self._inbox.put(_SENTINEL)

    def __del__(self) -> None:  # pragma: no cover - GC path
        try:
            self.close()
        except Exception:
            pass


def parse_addr(addr: str) -> Tuple[str, int]:
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://"):]
    host, port_s = addr.rsplit(":", 1)
    return host, int(port_s)


#: Modes the native C client supports, with REQ mapped onto rw (same wire
#: framing: no credit protocol on req/rep exchanges).
_NATIVE_MODE_MAP = {"r": "r", "w": "w", "rw": "rw", "req": "rw"}


def connect_transport(mode: str, addr: str, native: bool = True,
                      prefetch: int = 1, retries: int = 3):
    """The one place that picks a connection-side transport: the native C
    client (framing + socket + credit protocol per ctypes call) when the
    library loads and the address is a numeric IPv4, else a Python
    Endpoint. Used by queue/pipe Connections and pool workers alike so
    the selection policy can never diverge.

    ``native=False`` forces the Python Endpoint — for callers that need
    honored send deadlines (the C client's send blocks on the credit
    wait with no timeout plumbing; fine for the data path, wrong for
    watchdog-style control sends that must never freeze). ``retries``
    bounds the Python path's connect backoff retry; pass 0 for callers
    that must fail fast when the peer is gone (the native client keeps
    its own single-shot connect)."""
    host, port = parse_addr(addr)
    native_mode = _NATIVE_MODE_MAP.get(mode) if native else None
    if str(getattr(config.get(), "transport_io", "selector")) == "shm":
        # The C client speaks plain TCP and can't join an shm
        # negotiation; under the shm engine the Python Endpoint IS the
        # fast path (rings beat loopback TCP), so native would be a
        # downgrade here.
        native_mode = None
    if native_mode is not None and host.count(".") == 3 and \
            host.replace(".", "").isdigit():
        try:
            from fiber_tpu._native import NativeClient, available

            if available():
                return NativeClient(host, port, native_mode,
                                    prefetch=prefetch)
        except Exception:
            pass
    return Endpoint(mode, prefetch=prefetch).connect(addr, retries=retries)


class Device:
    """A forwarder bound to two stable addresses (reference: the nanomsg
    ``nn_device`` under every queue, fiber/socket.py:297-320).

    ``Device("r", "w")``: producers dial ``in_addr`` with mode ``w``;
    consumers dial ``out_addr`` with mode ``r``; one pump thread forwards
    in→out with round-robin fan-out. ``Device("rw", "rw")`` is a duplex
    relay (Pipe): frames arriving on either side are forwarded to the
    other.
    """

    def __init__(self, in_mode: str, out_mode: str, ip: str) -> None:
        self._native = None
        duplex = in_mode == "rw" and out_mode == "rw"
        if (in_mode, out_mode) in (("r", "w"), ("rw", "rw")):
            # Same refusal as Endpoint.bind — the native pump must not be
            # a wildcard-bound bypass of the default-key check.
            if (ip not in ("127.0.0.1", "localhost")
                    and auth.auth_enabled()
                    and auth.cluster_key() == auth.DEFAULT_KEY.encode()):
                raise TransportClosed(
                    "refusing to bind the data plane on non-loopback "
                    f"{ip!r} with the default cluster key; set "
                    "FIBER_CLUSTER_KEY (fiber-tpu up generates one)"
                )
            # Under the shm engine the Python endpoints negotiate rings
            # per channel — the TCP-only native pump would silently put
            # every same-host frame back on loopback sockets.
            if str(getattr(config.get(), "transport_io",
                           "selector")) != "shm":
                try:
                    from fiber_tpu._native import NativePump, available

                    if available():
                        self._native = NativePump(duplex, bind_ip=ip)
                except Exception:
                    self._native = None
        if self._native is not None:
            self.in_ep = None
            self.out_ep = None
            self.in_addr = f"tcp://{ip}:{self._native.in_port}"
            self.out_addr = f"tcp://{ip}:{self._native.out_port}"
            self._pumps: List[threading.Thread] = []
            return
        self.in_ep = Endpoint(in_mode)
        self.out_ep = Endpoint(out_mode)
        self.in_addr = self.in_ep.bind(ip)
        self.out_addr = self.out_ep.bind(ip)
        self._pumps = []
        if duplex:
            self._start_pump(self.in_ep, self.out_ep)
            self._start_pump(self.out_ep, self.in_ep)
        else:
            self._start_pump(self.in_ep, self.out_ep)

    def _start_pump(self, src: Endpoint, dst: Endpoint) -> None:
        t = threading.Thread(
            target=self._pump, args=(src, dst),
            name="fiber-device-pump", daemon=True,
        )
        t.start()
        self._pumps.append(t)

    @staticmethod
    def _pump(src: Endpoint, dst: Endpoint) -> None:
        while True:
            try:
                frame = src.recv()
            except (TransportClosed, OSError):
                return
            while True:
                try:
                    dst.send(frame, timeout=1.0)
                    break
                except TimeoutError:
                    if src._closed or dst._closed:
                        return
                except (TransportClosed, OSError):
                    return

    def wait_out_peers(self, n: int, timeout: Optional[float] = None) -> bool:
        """Block until n consumers are connected (both pump impls)."""
        if self._native is not None:
            import time as _time

            deadline = None if timeout is None else _time.monotonic() + timeout
            while self._native.peers("out") < n:
                if deadline is not None and _time.monotonic() > deadline:
                    return False
                _time.sleep(0.01)
            return True
        return self.out_ep.wait_for_peers(n, timeout)

    @property
    def is_native(self) -> bool:
        return self._native is not None

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            return
        self.in_ep.close()
        self.out_ep.close()

"""Same-host shared-memory transport engine (``transport_io="shm"``).

One negotiated connection owns a pair of mmap'd SPSC ring buffers (one
per direction) backed by files in ``/dev/shm`` (tempdir fallback). The
rings carry the exact TCP wire format — 8-byte big-endian length header
plus the transport's 1-byte frame-type tag — so the shared ingress
(``_Channel.handle_frame``) and the frame decoder (``framing.
FrameBuffer``) run unchanged: a ring quacks like a non-blocking socket
(``recv``/``recv_into`` raising ``BlockingIOError`` when empty). Large
payloads are written into the ring as one copy and read out of it with
``recv_into`` directly into the frame buffer — one copy per side,
instead of the four a loopback TCP hop costs (pickle→send→recv→
unpickle staging buffers).

Negotiation (docs/transport.md) is strictly sequential on the freshly
authenticated TCP socket, BEFORE any data frame:

* the dialer creates both rings, stamps a random token in each header,
  and sends one hello frame (paths + tokens + capacity + its host key);
* the binder attaches the rings only when the host keys match and the
  tokens verify, then answers ACK (go shm) or NAK (stay TCP);
* any non-handshake first frame means the peer speaks plain TCP — it is
  handed back to the caller as ``leftover`` and injected through
  ``handle_frame`` so no wire frame is ever lost;
* a timeout on either side falls back to TCP. Handshake frames all
  start with the ``0x02`` type byte, which ``handle_frame`` drops
  silently, so a timed-out race can never corrupt the data stream.

The TCP socket stays open beside the rings: it detects peer death (EOF)
and heals the one pathological race (binder ACKed but the dialer timed
out) because the shm read loop decodes stray TCP frames through the
same ingress. It is also the *doorbell*: an idle reader raises a
waiting flag in its rx ring header and parks in ``select()`` on the
socket; a writer whose write found the ring empty (or the flag up)
sends one tiny ``0x02`` wake frame. No spinning while idle — the cost
of a wakeup is one 9-byte loopback send, paid only on empty→non-empty
transitions, and a short select timeout bounds the one cross-process
store/load reordering window a flag-based handoff cannot close.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import tempfile
import time
from typing import Optional, Tuple

from fiber_tpu import telemetry
from fiber_tpu.framing import recv_frame_timeout, send_frame
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

# Registry twins for the shm engine (docs/observability.md): the
# engine-agnostic transport_* counters still cover every frame; these
# isolate the shm share so operators can see negotiation win/loss and
# ring throughput directly.
_m_shm_bytes_tx = telemetry.counter(
    "transport_shm_bytes_tx",
    "Wire bytes written into shm rings (framing headers included)")
_m_shm_bytes_rx = telemetry.counter(
    "transport_shm_bytes_rx",
    "Wire bytes read out of shm rings (framing headers included)")
_m_shm_frames_tx = telemetry.counter(
    "transport_shm_frames_tx", "Frames written into shm rings")
_m_shm_frames_rx = telemetry.counter(
    "transport_shm_frames_rx", "Frames read out of shm rings")
_m_shm_channels = telemetry.counter(
    "transport_shm_channels", "Connections negotiated onto shm rings")
_m_shm_fallbacks = telemetry.counter(
    "transport_shm_fallbacks",
    "shm negotiations that fell back to plain TCP")
_m_shm_backpressure = telemetry.counter(
    "transport_shm_ring_full_waits",
    "Ring writes that blocked on a full ring (backpressure)")

#: Ring file layout: a 64-byte header, then the data area.
#: [0:8]   write_pos — free-running uint64, writer-owned
#: [8:16]  read_pos  — free-running uint64, reader-owned
#: [16:24] capacity  — data-area bytes
#: [24:40] token     — 16 random bytes; the attach-side proof that the
#:                     file is the one the hello named (a stale path
#:                     reused by another process fails verification)
#: [40]    waiting   — reader-owned doorbell flag: 1 while the reader
#:                     is parked in select() on the companion socket
HEADER_SIZE = 64
_POS = struct.Struct("<Q")
_CAP_OFF = 16
_TOKEN_OFF = 24
_TOKEN_LEN = 16
_WAIT_OFF = 40

#: First byte of every handshake frame — the transport's 0x02 frame
#: type, which _Channel.handle_frame drops silently so a timed-out
#: handshake race cannot masquerade as data.
MAGIC = b"\x02FIBSHM1"

#: How long each side waits for the peer's handshake turn. A same-host
#: shm peer answers in microseconds; only MIXED engine configs (one
#: side shm, the other not) ever run the clock out, paying this once
#: per connection before the TCP fallback.
NEGOTIATE_TIMEOUT_S = 2.0


def negotiate_timeout() -> float:
    try:
        return float(os.environ.get("FIBER_SHM_NEGOTIATE_S",
                                    NEGOTIATE_TIMEOUT_S))
    except ValueError:
        return NEGOTIATE_TIMEOUT_S


class RingClosed(OSError):
    """The ring was closed under a blocked reader/writer (peer death or
    endpoint shutdown)."""


def ring_dir() -> str:
    d = "/dev/shm"
    if os.path.isdir(d) and os.access(d, os.W_OK):
        return d
    return tempfile.gettempdir()


def ring_capacity() -> int:
    from fiber_tpu import config

    kb = int(getattr(config.get(), "transport_shm_ring_kb", 4096) or 4096)
    # Floor keeps a misconfigured tiny ring from grinding every frame
    # into single-byte writes; frames larger than the ring still move
    # (write() streams them through in chunks).
    return max(64, kb) * 1024


class ShmRing:
    """SPSC byte ring over one mmap'd file. Single writer process,
    single reader process; positions are free-running so ``write_pos -
    read_pos`` is the buffered byte count and wraparound needs no
    modular fixups. The reader side quacks like a non-blocking socket
    (``recv``/``recv_into`` raise ``BlockingIOError`` when empty) so
    ``framing.FrameBuffer`` decodes it unchanged."""

    __slots__ = ("_mm", "path", "capacity", "token", "_closed")

    def __init__(self, mm: mmap.mmap, path: str, capacity: int,
                 token: bytes) -> None:
        self._mm = mm
        self.path = path
        self.capacity = capacity
        self.token = token
        self._closed = False

    # -- lifecycle --------------------------------------------------------
    @classmethod
    def create(cls, capacity: int,
               directory: Optional[str] = None) -> "ShmRing":
        fd, path = tempfile.mkstemp(prefix="fiber-shm-",
                                    dir=directory or ring_dir())
        try:
            os.ftruncate(fd, HEADER_SIZE + capacity)
            mm = mmap.mmap(fd, HEADER_SIZE + capacity)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        os.close(fd)
        token = os.urandom(_TOKEN_LEN)
        _POS.pack_into(mm, _CAP_OFF, capacity)
        mm[_TOKEN_OFF:_TOKEN_OFF + _TOKEN_LEN] = token
        return cls(mm, path, capacity, token)

    @classmethod
    def attach(cls, path: str, token: bytes, capacity: int) -> "ShmRing":
        fd = os.open(path, os.O_RDWR)
        try:
            mm = mmap.mmap(fd, HEADER_SIZE + capacity)
        finally:
            os.close(fd)
        if (bytes(mm[_TOKEN_OFF:_TOKEN_OFF + _TOKEN_LEN]) != token
                or _POS.unpack_from(mm, _CAP_OFF)[0] != capacity):
            mm.close()
            raise OSError("shm ring token/capacity mismatch")
        return cls(mm, path, capacity, token)

    def unlink(self) -> None:
        """Remove the backing file (both sides' mmaps keep the memory
        alive); called once the peer has attached so a crash leaves no
        litter in /dev/shm."""
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def close(self) -> None:
        """Mark the ring closed: blocked writers raise RingClosed, the
        reader raises on its next call. The mmap itself is released by
        GC — closing it here could race a reader mid-copy."""
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    # -- positions --------------------------------------------------------
    @property
    def write_pos(self) -> int:
        return _POS.unpack_from(self._mm, 0)[0]

    @property
    def read_pos(self) -> int:
        return _POS.unpack_from(self._mm, 8)[0]

    def buffered(self) -> int:
        """Bytes written but not yet read."""
        return self.write_pos - self.read_pos

    # -- doorbell flag ----------------------------------------------------
    def set_waiting(self) -> None:
        """Reader side: advertise that we are about to park in select()
        on the companion socket. The writer doorbells any write that
        lands while the flag is up."""
        self._mm[_WAIT_OFF] = 1

    def clear_waiting(self) -> None:
        self._mm[_WAIT_OFF] = 0

    @property
    def reader_waiting(self) -> bool:
        """Writer side: is the peer parked (or about to park) waiting
        for a doorbell?"""
        return self._mm[_WAIT_OFF] != 0

    # -- writer side ------------------------------------------------------
    def write(self, data) -> bool:
        """Append all of ``data``, blocking (yield, then sleeps growing
        to 1 ms — a full ring means a deep backlog, not a latency-
        critical wait) while the ring is full. Frames larger than the
        ring stream through in capacity-bounded chunks, so a huge
        broadcast payload can never deadlock against its own
        backpressure. Returns True when the ring was empty at call
        entry — the reader may have parked, so the caller should ring
        the doorbell. Raises :class:`RingClosed` if the ring closes
        mid-write."""
        mv = memoryview(data)
        if mv.nbytes == 0:
            return False
        if mv.ndim != 1 or mv.itemsize != 1:
            mv = mv.cast("B")
        mm = self._mm
        cap = self.capacity
        was_empty = (_POS.unpack_from(mm, 0)[0]
                     == _POS.unpack_from(mm, 8)[0])
        spins = 0
        waited = False
        while mv.nbytes:
            if self._closed:
                raise RingClosed("shm ring closed")
            wp = _POS.unpack_from(mm, 0)[0]
            free = cap - (wp - _POS.unpack_from(mm, 8)[0])
            if free <= 0:
                if not waited:
                    waited = True
                    _m_shm_backpressure.inc()
                spins += 1
                time.sleep(0.0 if spins < 16
                           else min(0.001, 0.0001 * (spins - 16)))
                continue
            spins = 0
            n = min(mv.nbytes, free)
            off = wp % cap
            first = min(n, cap - off)
            mm[HEADER_SIZE + off:HEADER_SIZE + off + first] = mv[:first]
            if n > first:
                mm[HEADER_SIZE:HEADER_SIZE + n - first] = mv[first:n]
            # Data lands before the position advances — the reader can
            # never see bytes it isn't allowed to copy yet.
            _POS.pack_into(mm, 0, wp + n)
            mv = mv[n:]
        return was_empty

    # -- reader side (socket-quack for framing.FrameBuffer) ---------------
    def recv(self, n: int) -> bytes:
        """Up to ``n`` buffered bytes; BlockingIOError when empty (never
        ``b""`` — EOF is the companion TCP socket's job)."""
        if self._closed:
            raise RingClosed("shm ring closed")
        mm = self._mm
        rp = _POS.unpack_from(mm, 8)[0]
        avail = _POS.unpack_from(mm, 0)[0] - rp
        if avail <= 0:
            raise BlockingIOError
        n = min(n, avail)
        cap = self.capacity
        off = rp % cap
        first = min(n, cap - off)
        out = bytes(mm[HEADER_SIZE + off:HEADER_SIZE + off + first])
        if n > first:
            out += bytes(mm[HEADER_SIZE:HEADER_SIZE + n - first])
        _POS.pack_into(mm, 8, rp + n)
        return out

    def recv_into(self, view, n: Optional[int] = None) -> int:
        """Copy up to ``n`` (default: ``len(view)``) buffered bytes into
        ``view``; BlockingIOError when empty. The large-frame path:
        framing.FrameBuffer fills the frame's own buffer directly from
        the ring — the single reader-side copy."""
        if self._closed:
            raise RingClosed("shm ring closed")
        mm = self._mm
        rp = _POS.unpack_from(mm, 8)[0]
        avail = _POS.unpack_from(mm, 0)[0] - rp
        if avail <= 0:
            raise BlockingIOError
        view = memoryview(view)
        if view.ndim != 1 or view.itemsize != 1:
            view = view.cast("B")
        n = view.nbytes if n is None else min(n, view.nbytes)
        n = min(n, avail)
        cap = self.capacity
        off = rp % cap
        first = min(n, cap - off)
        view[:first] = mm[HEADER_SIZE + off:HEADER_SIZE + off + first]
        if n > first:
            view[first:n] = mm[HEADER_SIZE:HEADER_SIZE + n - first]
        _POS.pack_into(mm, 8, rp + n)
        return n


class ShmPair:
    """The two rings of one negotiated channel, from the owner's point
    of view: ``tx`` is written by this process, ``rx`` read by it."""

    __slots__ = ("tx", "rx")

    def __init__(self, tx: ShmRing, rx: ShmRing) -> None:
        self.tx = tx
        self.rx = rx

    def close(self) -> None:
        self.tx.close()
        self.rx.close()


def _cleanup_created(*rings: ShmRing) -> None:
    for r in rings:
        r.close()
        r.unlink()


def negotiate_dialer(
    sock,
) -> Tuple[Optional[ShmPair], Optional[bytes]]:
    """Dialer side of the shm handshake, run on the freshly
    authenticated socket before any data frame. Returns ``(pair,
    leftover)``: ``pair=None`` means stay on TCP; ``leftover`` is a
    non-handshake frame consumed from the stream during the attempt
    (the binder spoke plain TCP first) which the caller must inject
    through ``handle_frame`` so no wire frame is lost."""
    from fiber_tpu.sched import local_host_key

    cap = ring_capacity()
    try:
        tx = ShmRing.create(cap)
    except OSError:
        _m_shm_fallbacks.inc()
        return None, None
    try:
        rx = ShmRing.create(cap)
    except OSError:
        _cleanup_created(tx)
        _m_shm_fallbacks.inc()
        return None, None
    hello = MAGIC + json.dumps({
        "host": local_host_key(),
        "tx": tx.path, "tx_token": tx.token.hex(),
        "rx": rx.path, "rx_token": rx.token.hex(),
        "capacity": cap,
    }).encode()
    try:
        send_frame(sock, hello)
        reply = recv_frame_timeout(sock, negotiate_timeout())
    except OSError:
        _cleanup_created(tx, rx)
        _m_shm_fallbacks.inc()
        return None, None
    if reply is None:
        # Timeout: the binder either isn't shm or is pathologically
        # slow. Either way TCP is safe — a late ACK is never acted on,
        # and a binder that DID go shm still decodes our TCP frames
        # (its read loop drains both sources).
        _cleanup_created(tx, rx)
        _m_shm_fallbacks.inc()
        return None, None
    if not bytes(reply).startswith(MAGIC):
        # A shm binder sends nothing before its verdict, so a non-
        # handshake first frame proves the binder speaks plain TCP.
        _cleanup_created(tx, rx)
        _m_shm_fallbacks.inc()
        leftover = bytes(reply)
        # A stray 0x02 frame is control noise, not data — drop it.
        return None, (None if leftover[:1] == b"\x02" else leftover)
    try:
        verdict = json.loads(bytes(reply[len(MAGIC):]))
        ok = bool(verdict.get("ok"))
    except ValueError:
        ok = False
    if not ok:
        _cleanup_created(tx, rx)
        _m_shm_fallbacks.inc()
        return None, None
    # Both sides are attached: the files can go — the mmaps keep the
    # memory alive, and an unlinked ring survives any crash cleanly.
    tx.unlink()
    rx.unlink()
    _m_shm_channels.inc()
    return ShmPair(tx=tx, rx=rx), None


def negotiate_binder(
    sock,
) -> Tuple[Optional[ShmPair], Optional[bytes]]:
    """Binder side: wait for the dialer's first frame. A hello with a
    matching host key and verifying ring tokens → attach, ACK, go shm.
    Any other first frame → the dialer speaks plain TCP; return that
    frame as ``leftover``. Timeout (a dialer that never speaks first,
    e.g. a plain receive-only peer waiting for credit) → TCP."""
    from fiber_tpu.sched import local_host_key

    try:
        first = recv_frame_timeout(sock, negotiate_timeout())
    except OSError:
        return None, None
    if first is None:
        _m_shm_fallbacks.inc()
        return None, None
    first = bytes(first)
    if not first.startswith(MAGIC):
        _m_shm_fallbacks.inc()
        return None, (None if first[:1] == b"\x02" else first)
    pair = None
    try:
        info = json.loads(first[len(MAGIC):])
        if info.get("host") == local_host_key():
            cap = int(info["capacity"])
            # Reversed roles: the dialer's tx ring is our rx.
            rx = ShmRing.attach(str(info["tx"]),
                                bytes.fromhex(info["tx_token"]), cap)
            try:
                tx = ShmRing.attach(str(info["rx"]),
                                    bytes.fromhex(info["rx_token"]), cap)
            except OSError:
                rx.close()
                raise
            pair = ShmPair(tx=tx, rx=rx)
    except (OSError, KeyError, ValueError, TypeError):
        pair = None
    try:
        send_frame(sock, MAGIC + json.dumps(
            {"ok": pair is not None}).encode())
    except OSError:
        if pair is not None:
            pair.close()
        return None, None
    if pair is None:
        _m_shm_fallbacks.inc()
        return None, None
    _m_shm_channels.inc()
    return pair, None

"""Content-addressed per-host object store: ObjectRef + LocalStore.

The data-plane problem this solves (Moritz et al., 2018 — Ray's Plasma
store — applied to the fiber workload): ES/POET masters broadcast one
large immutable blob (policy parameters) to hundreds of tasks per
generation, and a ship-by-value task protocol serializes and transmits
it once *per task*. Here large payloads are ``put`` once, addressed by
content digest, and every task carries a tiny :class:`ObjectRef`;
workers resolve refs through a per-host cache so the payload crosses
the wire once per host per generation (fiber_tpu/store/plane.py owns
the wire; this module owns the host-local state).

Storage model — one object is one opaque byte string, exactly what
``serialization.loads`` accepts (the protocol-5 out-of-band envelope or
a plain pickle), so disk files, wire chunks and RAM entries are all the
same representation:

* **RAM tier**: LRU over unpinned entries, capacity-bounded.
* **Disk tier**: ``<root>/<digest>.obj`` under the staging root
  (utils/staging.py) — doubles as the *host cache* shared by every
  fiber process on the host (atomic rename publication) and as the
  spill target for RAM evictions.
* **Refs and pins**: ``refs`` is the lifecycle count (a map in flight
  holds one ref on each of its arg objects; releases on completion make
  the entry evictable). ``pins`` is a short-lived hard pin held across
  a wire transfer so eviction can never free buffers mid-send. Entries
  with refs or pins never leave the store entirely: capacity pressure
  spills them to disk instead of dropping them.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from fiber_tpu import serialization
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.testing import chaos
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Best-effort bound on the disk tier (spill + host cache), bytes.
#: Enforced opportunistically at spill time, oldest files first.
DEFAULT_MAX_DISK_BYTES = 4 << 30


def default_store_root() -> str:
    """``store_dir`` config, or ``<staging root>/objects`` (the same
    root host agents confine file ops to, so agent-plane store ops and
    worker-local caching see one directory)."""
    from fiber_tpu import config

    configured = str(config.get().store_dir or "")
    if configured:
        return os.path.realpath(configured)
    from fiber_tpu.host_agent import default_staging_root

    return os.path.join(os.path.realpath(default_staging_root()), "objects")


class ObjectRef:
    """By-reference handle to one stored payload: content ``digest``
    (hex sha256), serialized ``size`` in bytes, and the ``owner`` store
    address (``tcp://ip:port``) that is guaranteed to be able to serve
    it. Tiny and picklable — this is what rides task/result frames.

    ``device_hint`` marks a device-destined BROADCAST payload (the map
    function's @meta asks for an accelerator and the encoder saw the
    object shared across items): the resolving worker routes it
    through the store's DEVICE tier (docs/objectstore.md "Device
    tier"), so one host pays one H2D per digest no matter how many
    co-located workers resolve it. Per-item payloads never carry the
    hint — mesh-replicating each would burn n_dev x HBM per item. A
    hint, never a requirement — resolution without a tier is the
    ordinary host path."""

    __slots__ = ("digest", "size", "owner", "device_hint")

    def __init__(self, digest: str, size: int, owner: str = "",
                 device_hint: bool = False) -> None:
        self.digest = digest
        self.size = int(size)
        self.owner = owner
        self.device_hint = bool(device_hint)

    def __reduce__(self):
        return (ObjectRef,
                (self.digest, self.size, self.owner, self.device_hint))

    def __eq__(self, other) -> bool:
        return (isinstance(other, ObjectRef)
                and other.digest == self.digest
                and other.owner == self.owner)

    def __hash__(self) -> int:
        return hash((self.digest, self.owner))

    def __repr__(self) -> str:
        return (f"ObjectRef({self.digest[:12]}…, size={self.size}, "
                f"owner={self.owner!r})")


def digest_of(data) -> str:
    return hashlib.sha256(data).hexdigest()


class _Entry:
    __slots__ = ("data", "refs", "pins", "on_disk")

    def __init__(self, data: bytes, refs: int, on_disk: bool) -> None:
        self.data = data
        self.refs = refs
        self.pins = 0
        self.on_disk = on_disk


class LocalStore:
    """Host-RAM object store with LRU eviction and disk spill.

    Thread-safe. ``root=None`` disables the disk tier entirely (unit
    tests, memory-only caches); then entries with refs/pins are simply
    never evicted.
    """

    def __init__(self, capacity_bytes: int = 512 << 20,
                 root: Optional[str] = None,
                 max_disk_bytes: int = DEFAULT_MAX_DISK_BYTES) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self.root = os.path.realpath(root) if root else None
        self.max_disk_bytes = int(max_disk_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._ram_bytes = 0
        self._stats: Dict[str, int] = {
            "puts": 0, "put_dedup_hits": 0,
            "ram_hits": 0, "disk_hits": 0, "misses": 0,
            "evictions": 0, "spills": 0, "spill_bytes": 0,
            "disk_corrupt": 0,
            # Accounting plane (docs/observability.md "Resource
            # accounting"): bytes currently hard-pinned by in-flight
            # transfers, and the high-water mark — the store's
            # contribution to a cost report's memory story.
            "pinned_bytes": 0, "peak_pinned_bytes": 0,
        }

    # -- paths ----------------------------------------------------------
    def _path(self, digest: str) -> str:
        # digest is validated hex (never user-controlled path material).
        return os.path.join(self.root, f"{digest}.obj")

    # -- write side -----------------------------------------------------
    def put(self, obj: Any, refs: int = 0,
            owner: str = "") -> ObjectRef:
        """Serialize ``obj`` (protocol-5 out-of-band envelope: large
        numpy/jax buffers are gathered, not re-copied through the
        pickler) and store it. Content-addressed: an identical payload
        already present just gains ``refs``."""
        data, buffers = serialization.dumps_oob(obj)
        if buffers:
            blob = serialization.pack_envelope(data, buffers)
        else:
            blob = data
        return self.put_bytes(blob, refs=refs, owner=owner)

    def put_bytes(self, data, refs: int = 0, owner: str = "",
                  persist: bool = False,
                  digest: Optional[str] = None) -> ObjectRef:
        """Store one serialized payload. ``persist=True`` publishes it
        to the host cache file immediately (fetched objects — sibling
        processes on this host must be able to find them *now*, not at
        spill time); master-side puts default to lazy (spill-only)."""
        data = bytes(data)
        digest = digest or digest_of(data)
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.refs += refs
                self._entries.move_to_end(digest)
                self._stats["put_dedup_hits"] += 1
                return ObjectRef(digest, len(entry.data), owner)
            on_disk = self.root is not None and os.path.exists(
                self._path(digest))
            self._entries[digest] = _Entry(data, refs, on_disk)
            self._ram_bytes += len(data)
            self._stats["puts"] += 1
            if FLIGHT.enabled:
                FLIGHT.record("store", "put", digest=digest[:8],
                              bytes=len(data))
            self._evict_locked()
        if persist and self.root is not None \
                and self._write_disk(digest, data):
            with self._lock:
                e = self._entries.get(digest)
                if e is not None:
                    e.on_disk = True
        return ObjectRef(digest, len(data), owner)

    # -- read side ------------------------------------------------------
    def get_bytes(self, digest: str, pin: bool = False) -> Optional[bytes]:
        """RAM tier, then the disk tier; None on a true miss. With
        ``pin=True`` the entry is hard-pinned (caller must
        :meth:`unpin` after its transfer completes)."""
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
                if pin:
                    entry.pins += 1
                    self._note_pin_locked(len(entry.data))
                self._stats["ram_hits"] += 1
                return entry.data
        data = self._read_disk(digest)
        if data is None:
            with self._lock:
                self._stats["misses"] += 1
            return None
        with self._lock:
            self._stats["disk_hits"] += 1
            entry = self._entries.get(digest)
            if entry is None:
                entry = _Entry(data, 0, on_disk=True)
                self._entries[digest] = entry
                self._ram_bytes += len(data)
                self._evict_locked(protect=digest)
            if pin:
                entry.pins += 1
                self._note_pin_locked(len(entry.data))
            return entry.data

    def get(self, digest: str) -> Tuple[bool, Any]:
        """Deserialized fetch: ``(found, obj)``."""
        data = self.get_bytes(digest)
        if data is None:
            return False, None
        return True, serialization.loads(data)

    def contains(self, digest: str) -> bool:
        with self._lock:
            if digest in self._entries:
                return True
        return (self.root is not None
                and os.path.exists(self._path(digest)))

    # -- lifecycle ------------------------------------------------------
    def add_ref(self, digest: str, n: int = 1) -> None:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.refs += n

    def release(self, digest: str, n: int = 1) -> None:
        """Drop lifecycle refs; at zero the entry becomes an ordinary
        LRU citizen (evicted under capacity pressure, droppable once
        spilled)."""
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.refs = max(0, entry.refs - n)

    def _note_pin_locked(self, nbytes: int) -> None:
        self._stats["pinned_bytes"] += nbytes
        if self._stats["pinned_bytes"] > self._stats["peak_pinned_bytes"]:
            self._stats["peak_pinned_bytes"] = self._stats["pinned_bytes"]

    def unpin(self, digest: str) -> None:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None and entry.pins > 0:
                entry.pins -= 1
                self._stats["pinned_bytes"] = max(
                    0, self._stats["pinned_bytes"] - len(entry.data))

    def delete(self, digest: str) -> None:
        """Drop an entry from RAM and disk regardless of refs (operator
        tooling; in-flight transfers still hold their own `data`
        reference, Python's GC makes this safe)."""
        with self._lock:
            entry = self._entries.pop(digest, None)
            if entry is not None:
                self._ram_bytes -= len(entry.data)
        if self.root is not None:
            try:
                os.unlink(self._path(digest))
            except OSError:
                pass

    # -- eviction / spill ----------------------------------------------
    def _evict_locked(self, protect: Optional[str] = None) -> None:
        """Walk the LRU order until under capacity (caller holds lock).
        Pinned entries are untouchable; ref-held entries must survive
        somewhere, so without a disk tier they are skipped too."""
        if self._ram_bytes <= self.capacity_bytes:
            return
        for digest in list(self._entries):
            if self._ram_bytes <= self.capacity_bytes:
                return
            entry = self._entries[digest]
            if digest == protect or entry.pins > 0:
                continue
            if entry.refs > 0 and self.root is None:
                continue  # nowhere to keep it; must stay resident
            if self.root is not None and not entry.on_disk:
                if not self._write_disk(digest, entry.data):
                    if entry.refs > 0:
                        continue  # spill failed; dropping would lose it
                else:
                    entry.on_disk = True
                    self._stats["spills"] += 1
                    self._stats["spill_bytes"] += len(entry.data)
                    FLIGHT.record("store", "spill", digest=digest[:8],
                                  bytes=len(entry.data),
                                  reason="RAM tier over capacity")
            del self._entries[digest]
            self._ram_bytes -= len(entry.data)
            self._stats["evictions"] += 1

    def _write_disk(self, digest: str, data: bytes) -> bool:
        """Atomic publication: tmp file + rename, so concurrent readers
        (sibling processes on this host) only ever see complete
        objects. False when the write failed (full/readonly disk)."""
        path = self._path(digest)
        if os.path.exists(path):
            return True
        plan = chaos._plan
        if plan is not None:
            # Chaos corrupt_store_disk: the bytes that hit disk differ
            # from the digest — _read_disk's verification is the
            # degradation under test (docs/robustness.md).
            data = plan.corrupt_disk_write(data)
        try:
            os.makedirs(self.root, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            logger.warning("object store: disk write failed for %s "
                           "(continuing RAM-only)", digest[:12],
                           exc_info=True)
            return False
        self._trim_disk()
        return True

    def _read_disk(self, digest: str) -> Optional[bytes]:
        if self.root is None:
            return None
        try:
            with open(self._path(digest), "rb") as fh:
                data = fh.read()
        except OSError:
            return None
        # The file IS the content address: verify it on every disk read
        # (spill reload, cross-process host-cache hit) exactly like the
        # wire fetch path does — silent disk corruption must degrade to
        # a miss (and a refetch from the owner), never a wrong payload.
        # The corrupt file is quarantined so the refetch can republish.
        if digest_of(data) != digest:
            with self._lock:
                self._stats["disk_corrupt"] += 1
            FLIGHT.record("store", "disk_corrupt", digest=digest[:8],
                          bytes=len(data),
                          reason="cache/spill file failed digest "
                                 "verification; treating as miss")
            logger.warning(
                "object store: disk file for %s failed digest "
                "verification (%d bytes); removed — callers refetch",
                digest[:12], len(data))
            try:
                os.unlink(self._path(digest))
            except OSError:
                pass
            return None
        return data

    def _trim_disk(self, target: Optional[int] = None) -> None:
        """Keep the disk tier under ``target`` bytes (default
        max_disk_bytes), oldest-mtime first (best effort — concurrent
        processes may race; losing a cache file only costs a
        re-fetch)."""
        bound = self.max_disk_bytes if target is None else int(target)
        try:
            names = [n for n in os.listdir(self.root)
                     if n.endswith(".obj")]
            files = []
            total = 0
            for n in names:
                p = os.path.join(self.root, n)
                try:
                    st = os.stat(p)
                except OSError:
                    continue
                files.append((st.st_mtime, st.st_size, p))
                total += st.st_size
            files.sort()
            for _, size, p in files:
                if total <= bound:
                    break
                try:
                    os.unlink(p)
                    total -= size
                except OSError:
                    pass
        except OSError:
            pass

    def shed_disk(self, fraction: float = 0.7) -> int:
        """Evict down to ``fraction`` of the disk budget NOW, oldest
        first (the policy plane's store_disk_fill remediation — the
        watchdog fires at 90% of budget, so trimming only to 100% would
        never clear the anomaly). Returns bytes freed."""
        if self.root is None:
            return 0
        before = self.disk_usage()
        self._trim_disk(target=int(
            self.max_disk_bytes * max(0.0, min(1.0, float(fraction)))))
        return max(0, before - self.disk_usage())

    def disk_usage(self) -> int:
        """Bytes currently held by the disk tier (spill + host cache),
        0 when the tier is disabled — the monitor watchdog's
        ``store_disk_fill`` input, checked against max_disk_bytes."""
        if self.root is None:
            return 0
        total = 0
        try:
            for name in os.listdir(self.root):
                if not name.endswith(".obj"):
                    continue
                try:
                    total += os.path.getsize(os.path.join(self.root, name))
                except OSError:
                    continue
        except OSError:
            return 0
        return total

    # -- introspection --------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
            out["objects"] = len(self._entries)
            out["ram_bytes"] = self._ram_bytes
        return out

    def ram_digests(self) -> List[str]:
        with self._lock:
            return list(self._entries)

"""Store wire plane: chunked fetch/put RPC over the authenticated
transport.

The server is an ``Endpoint("rep")`` on the same framed-TCP,
HMAC-authenticated plane as every other fiber_tpu listener (transport
accept path runs fiber_tpu.auth per connection through the shared
PreauthPool from utils/serve.py), so the store inherits the data plane's
threat posture for free. Large objects stream as a header frame plus
``STORE_CHUNK``-sized frames over framing.py instead of one giant frame
— an 800 MB checkpoint never forces an 800 MB contiguous recv on either
side of the transfer.

Protocol (control frames are serialization.dumps tuples; chunk frames
are raw bytes — the server tells them apart because chunks only ever
follow a ``put`` header *from the same channel*, and a req client only
ever sees chunks after an ``ok`` get header):

===========================================  =============================
client -> server                             server -> client
===========================================  =============================
("get", digest)                              ("ok", size, nchunks) + chunks
                                             | ("miss",)
("put", digest, size, nchunks) + chunks      ("ok",) | ("err", msg)
("release", digest)                          ("ok",)
("stats",)                                   ("ok", stats_dict)
===========================================  =============================

The client side (StoreClient) layers the per-host fetch discipline on
top: RAM tier -> host cache file -> wire, with an O_EXCL lock file per
digest so N worker processes on one host fetching the same broadcast
object cost ONE wire transfer (the losers wait for the winner's atomic
cache publication). All failures converge on :class:`StoreFetchError`;
the pool turns that into its storemiss/inline fallback instead of
failing tasks.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from fiber_tpu import serialization, telemetry
from fiber_tpu.store.core import LocalStore, ObjectRef, digest_of
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.testing import chaos
from fiber_tpu.transport import Endpoint, TransportClosed
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

# Store-plane metrics (docs/observability.md): the same counters the
# ad-hoc ``store_stats`` dicts expose, mirrored into the shared registry
# so cluster_metrics / the Prometheus endpoint see them. The "side"
# label splits server (owner) from client (fetcher) traffic.
_m_store_ops = telemetry.counter(
    "store_ops", "Object-store operations by op kind and side")
_m_store_bytes = telemetry.counter(
    "store_bytes", "Object-store payload bytes moved, by direction")

#: One wire chunk. Big enough to amortize framing, small enough that a
#: slow peer never parks tens of MB in one socket write.
STORE_CHUNK = 1 << 20

#: How long a fetch loser waits for the lock winner's cache publication
#: before giving up on dedup and fetching directly (correctness beats
#: once-per-host when the winner crashed mid-fetch).
LOCK_WAIT_S = 10.0

_CONNECT_TIMEOUT = 30.0


class StoreFetchError(RuntimeError):
    """An ObjectRef could not be resolved (owner unreachable, object
    evicted and unspilled, injected chaos). The pool's storemiss path
    degrades the affected chunk to inline payloads."""


class StoreServer:
    """Serves one LocalStore on the transport plane. ``addr`` is what
    goes into ObjectRef.owner."""

    def __init__(self, store: LocalStore, ip: str) -> None:
        self.store = store
        self._ep = Endpoint("rep")
        self.addr = self._ep.bind(ip)
        self._stop = threading.Event()
        # chan -> [digest, size, chunks_remaining, parts] for an
        # in-flight chunked put (frames from one channel stay ordered;
        # interleaving across channels is keyed apart here).
        self._puts: Dict[Any, list] = {}
        self._stats_lock = threading.Lock()
        self._stats: Dict[str, int] = {
            "gets": 0, "get_misses": 0, "bytes_served": 0,
            "puts": 0, "bytes_received": 0, "errors": 0,
        }
        self._thread = threading.Thread(
            target=self._serve_loop, name="fiber-store-serve", daemon=True
        )
        self._thread.start()

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        self._ep.close()

    def stats(self) -> Dict[str, int]:
        with self._stats_lock:
            out = dict(self._stats)
        out.update({f"store_{k}": v for k, v in self.store.stats().items()})
        # Exact wire volume at the framing boundary (transport/tcp.py
        # channel counters): the tier-1 "one transfer per host" proof
        # asserts against these, not just the app-level byte counters.
        out["wire_bytes_tx"] = self._ep.bytes_tx
        out["wire_bytes_rx"] = self._ep.bytes_rx
        out["wire_frames_tx"] = self._ep.frames_tx
        out["wire_frames_rx"] = self._ep.frames_rx
        return out

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += n
        if key in ("bytes_served", "bytes_received"):
            _m_store_bytes.inc(n, direction=key, side="server")
        else:
            _m_store_ops.inc(n, op=key, side="server")

    # -- serve loop -----------------------------------------------------
    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                frame, chan = self._ep.recv_req(timeout=0.5)
            except TimeoutError:
                if self._puts:
                    # A client that died mid-put must not leak its
                    # half-assembled chunks forever.
                    self._puts = {c: p for c, p in self._puts.items()
                                  if c.alive}
                continue
            except (TransportClosed, OSError):
                return
            try:
                self._handle(frame, chan)
            except Exception:
                self._bump("errors")
                logger.exception("store server: dropping bad request")

    def _handle(self, frame, chan) -> None:
        pending = self._puts.get(chan)
        if pending is not None:
            self._absorb_put_chunk(pending, frame, chan)
            return
        msg = serialization.loads(frame)
        op = msg[0]
        if op == "get":
            self._handle_get(msg[1], chan)
        elif op == "put":
            _, digest, size, nchunks = msg
            if nchunks <= 0:
                self._finish_put(chan, digest, b"")
            else:
                self._puts[chan] = [digest, int(size), int(nchunks), []]
        elif op == "release":
            self.store.release(msg[1])
            self._reply(chan, ("ok",))
        elif op == "stats":
            self._reply(chan, ("ok", self.stats()))
        else:
            self._reply(chan, ("err", f"unknown store op {op!r}"))

    def _reply(self, chan, msg: Tuple) -> None:
        try:
            Endpoint.reply(chan, serialization.dumps(msg))
        except (TransportClosed, OSError):
            pass  # requester gone; nothing to clean up

    def _handle_get(self, digest: str, chan) -> None:
        plan = chaos._plan
        if plan is not None:
            plan.maybe_slow_store()
        data = self.store.get_bytes(digest, pin=True)
        if data is None:
            self._bump("get_misses")
            self._reply(chan, ("miss",))
            return
        try:
            view = memoryview(data)
            nchunks = -(-len(data) // STORE_CHUNK) if data else 0
            self._reply(chan, ("ok", len(data), nchunks))
            try:
                for off in range(0, len(data), STORE_CHUNK):
                    chan.send(view[off:off + STORE_CHUNK])
            except (TransportClosed, OSError):
                return  # reader died mid-stream; pin still released
            self._bump("gets")
            self._bump("bytes_served", len(data))
        finally:
            self.store.unpin(digest)

    def _absorb_put_chunk(self, pending, frame, chan) -> None:
        digest, size, remaining, parts = pending
        parts.append(bytes(frame))
        pending[2] = remaining - 1
        if pending[2] > 0:
            return
        del self._puts[chan]
        self._finish_put(chan, digest, b"".join(parts))

    def _finish_put(self, chan, digest: str, data: bytes) -> None:
        # Verify the content address: a corrupted or malicious payload
        # must not poison the cache under someone else's digest.
        if digest_of(data) != digest:
            self._bump("errors")
            self._reply(chan, ("err", "digest mismatch"))
            return
        # refs=1: owned-until-claimed — the consumer that resolves the
        # ref releases it (pool result path); a put that is never
        # claimed stays spillable but resident-or-on-disk.
        self.store.put_bytes(data, refs=1, digest=digest)
        self._bump("puts")
        self._bump("bytes_received", len(data))
        self._reply(chan, ("ok",))


class StoreClient:
    """Resolve/push ObjectRefs against remote owners, through the local
    store's RAM/disk tiers. One per process is enough (connections are
    cached per owner address); the pool worker creates it lazily."""

    def __init__(self, store: LocalStore,
                 resolve_cache_entries: int = 16) -> None:
        self.store = store
        self._conns: Dict[str, Endpoint] = {}
        self._conn_lock = threading.Lock()
        # digest -> deserialized object. Resolution cache: a broadcast
        # arg is deserialized (and jax.device_put) once per worker, not
        # once per task. Resolved objects are therefore SHARED across
        # tasks in this process — the store convention (same as Ray) is
        # that stored payloads are immutable.
        self._objs: Dict[str, Any] = {}
        self._obj_order: list = []
        self._obj_cap = int(resolve_cache_entries)
        self._stats: Dict[str, int] = {
            "resolves": 0, "obj_cache_hits": 0, "wire_fetches": 0,
            "wire_bytes": 0, "lock_waits": 0, "fetch_failures": 0,
        }

    def _count(self, key: str, n: int = 1) -> None:
        self._stats[key] += n
        if key == "wire_bytes":
            _m_store_bytes.inc(n, direction="fetched", side="client")
            # Accounting plane (docs/observability.md "Resource
            # accounting"): a wire fetch bills the map whose chunk
            # caused it — the worker sets the ambient billing key
            # around chunk processing; fetches outside any chunk land
            # in the explicit overhead bucket.
            from fiber_tpu.telemetry.accounting import COSTS

            COSTS.bill_ambient(store_fetch_bytes=n)
        else:
            _m_store_ops.inc(n, op=key, side="client")

    # -- resolution -----------------------------------------------------
    def resolve(self, ref: ObjectRef, device: bool = False) -> Any:
        """Deserialized object for ``ref``. With ``device=True`` the
        payload is device-destined: the resolution order gains a fourth,
        fastest tier in front of RAM/disk/wire — the device-resident
        store (docs/objectstore.md "Device tier"). A tier hit returns
        the already-replicated ``jax.Array`` pytree: zero wire bytes,
        zero H2D; a miss fills the tier so the NEXT resolution (this
        process or a co-located pool on the same chips) is free. The
        tier is a no-op when disabled, demoted by the ``hbm_fill``
        watchdog, or on a pure host plane.

        ``_objs`` holds HOST forms only — the tier owns every device-
        resident pytree. Caching the replicated form here would hand
        jax device arrays to later device=False callers, and (worse)
        pin the replicated HBM past an ``hbm_fill`` demotion: the
        remediation would shed the tier while this cache quietly keeps
        the bytes resident."""
        self._count("resolves")
        if device:
            tier = self._device_tier()
            if tier is not None:
                obj = tier.get(ref.digest)
                if obj is not None:
                    self._count("obj_cache_hits")
                    return obj
        obj = self._objs.get(ref.digest)
        if obj is not None or ref.digest in self._objs:
            self._count("obj_cache_hits")
            if device:
                tier = self._device_tier()
                if tier is not None:
                    # Replicate from the cached host form; a demoted
                    # tier hands the host object straight back.
                    return tier.put(ref.digest, obj)
            return obj
        data = self.fetch_bytes(ref)
        # Store resolution is a host->device boundary: deserializing a
        # broadcast payload is where its arrays land on the device
        # (device telemetry plane, docs/observability.md). Accounted
        # once per worker per object — the resolution cache above keeps
        # repeat tasks free.
        from fiber_tpu.telemetry.device import DEVICE

        with DEVICE.transfer("store_resolve", len(data)):
            obj = serialization.loads(data)
        self._objs[ref.digest] = obj
        self._obj_order.append(ref.digest)
        while len(self._obj_order) > self._obj_cap:
            self._objs.pop(self._obj_order.pop(0), None)
        if device:
            tier = self._device_tier()
            if tier is not None:
                # Replicate across the mesh now (accounted under the
                # `ici` site) and hand the device form ONLY to this
                # device-destined caller; the host copy above is what
                # re-promotion (and host-plane callers) resolve from.
                return tier.put(ref.digest, obj)
        return obj

    @staticmethod
    def _device_tier():
        from fiber_tpu import store as storemod

        return storemod.device_store_tier()

    def fetch_bytes(self, ref: ObjectRef) -> bytes:
        """Serialized payload for ``ref``: local tiers first, then the
        owner over the wire (once per host — lock-file dedup against
        sibling processes). Raises StoreFetchError when every source
        fails."""
        data = self.store.get_bytes(ref.digest)
        if data is not None:
            return data
        if not ref.owner:
            raise StoreFetchError(
                f"object {ref.digest[:12]} not present locally and the "
                "ref names no owner")
        return self._fetch_wire_deduped(ref)

    def _fetch_wire_deduped(self, ref: ObjectRef) -> bytes:
        root = self.store.root
        if root is None:
            data = self._fetch_wire(ref)
            self.store.put_bytes(data, digest=ref.digest)
            return data
        lock_path = os.path.join(root, f"{ref.digest}.fetch-lock")
        try:
            os.makedirs(root, exist_ok=True)
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)
        except FileExistsError:
            # A sibling process is already fetching this object; wait
            # for its atomic cache publication instead of duplicating
            # the transfer.
            self._count("lock_waits")
            deadline = time.monotonic() + LOCK_WAIT_S
            while time.monotonic() < deadline:
                data = self.store.get_bytes(ref.digest)
                if data is not None:
                    return data
                if not os.path.exists(lock_path):
                    break  # winner finished (or died); check once more
                time.sleep(0.01)
            data = self.store.get_bytes(ref.digest)
            if data is not None:
                return data
            # Winner crashed or is stuck: correctness over dedup.
            data = self._fetch_wire(ref)
            self.store.put_bytes(data, persist=True, digest=ref.digest)
            return data
        except OSError:
            data = self._fetch_wire(ref)
            self.store.put_bytes(data, digest=ref.digest)
            return data
        try:
            data = self._fetch_wire(ref)
            self.store.put_bytes(data, persist=True, digest=ref.digest)
            return data
        finally:
            try:
                os.unlink(lock_path)
            except OSError:
                pass

    def _fetch_wire(self, ref: ObjectRef) -> bytes:
        plan = chaos._plan
        if plan is not None:
            # Injected fetch failure (budgeted): models an unreachable
            # or lying store; no retry — the pool's inline fallback is
            # the behavior under test.
            try:
                plan.fail_point("store_fetch")
            except chaos.ChaosError as err:
                self._count("fetch_failures")
                raise StoreFetchError(str(err)) from err
        last_err: Optional[BaseException] = None
        t0 = time.perf_counter()
        for attempt in range(2):
            try:
                data = self._fetch_once(ref, fresh=attempt > 0)
                self._count("wire_fetches")
                self._count("wire_bytes", len(data))
                if FLIGHT.enabled:
                    # wire=True marks a LOCALITY MISS for explain: the
                    # payload was fetched where it did not already live.
                    FLIGHT.record(
                        "store", "fetch", digest=ref.digest[:8],
                        bytes=len(data), wire=True,
                        s=round(time.perf_counter() - t0, 4))
                return data
            except StoreFetchError:
                raise  # definitive (miss / digest mismatch): no retry
            except (TransportClosed, OSError, TimeoutError) as err:
                last_err = err
                self._drop_conn(ref.owner)
        self._count("fetch_failures")
        FLIGHT.record("store", "fetch_fail", digest=ref.digest[:8],
                      owner=str(ref.owner), reason=repr(last_err))
        raise StoreFetchError(
            f"fetch of {ref.digest[:12]} from {ref.owner} failed: "
            f"{last_err!r}")

    def _fetch_once(self, ref: ObjectRef, fresh: bool) -> bytes:
        ep = self._conn(ref.owner, fresh=fresh)
        ep.send(serialization.dumps(("get", ref.digest)),
                timeout=_CONNECT_TIMEOUT)
        head = serialization.loads(ep.recv(timeout=_CONNECT_TIMEOUT))
        if head[0] == "miss":
            self._count("fetch_failures")
            raise StoreFetchError(
                f"owner {ref.owner} no longer holds {ref.digest[:12]}")
        if head[0] != "ok":
            self._count("fetch_failures")
            raise StoreFetchError(f"store get error: {head!r}")
        _, size, nchunks = head
        buf = bytearray(size)
        off = 0
        for _ in range(nchunks):
            chunk = ep.recv(timeout=_CONNECT_TIMEOUT)
            buf[off:off + len(chunk)] = chunk
            off += len(chunk)
        if off != size or digest_of(buf) != ref.digest:
            raise StoreFetchError(
                f"fetched object {ref.digest[:12]} failed verification")
        return bytes(buf)

    # -- push (worker results -> owner store) ---------------------------
    def push(self, data: bytes, owner: str) -> ObjectRef:
        """Upload one serialized payload to ``owner``'s store, chunked.
        Raises on failure; callers fall back to inline shipping."""
        digest = digest_of(data)
        ep = self._conn(owner)
        view = memoryview(data)
        nchunks = -(-len(data) // STORE_CHUNK) if data else 0
        try:
            ep.send(serialization.dumps(("put", digest, len(data),
                                         nchunks)),
                    timeout=_CONNECT_TIMEOUT)
            for off in range(0, len(data), STORE_CHUNK):
                ep.send(view[off:off + STORE_CHUNK],
                        timeout=_CONNECT_TIMEOUT)
            reply = serialization.loads(ep.recv(timeout=_CONNECT_TIMEOUT))
        except (TransportClosed, OSError, TimeoutError):
            self._drop_conn(owner)
            raise
        if reply[0] != "ok":
            raise RuntimeError(f"store put rejected: {reply!r}")
        return ObjectRef(digest, len(data), owner)

    def release(self, ref: ObjectRef) -> None:
        """Best-effort remote ref release (lifecycle hint, never
        load-bearing for correctness)."""
        try:
            ep = self._conn(ref.owner)
            ep.send(serialization.dumps(("release", ref.digest)),
                    timeout=5.0)
            ep.recv(timeout=5.0)
        except Exception:
            pass

    def owner_stats(self, owner: str) -> Dict[str, int]:
        ep = self._conn(owner)
        ep.send(serialization.dumps(("stats",)), timeout=_CONNECT_TIMEOUT)
        reply = serialization.loads(ep.recv(timeout=_CONNECT_TIMEOUT))
        if reply[0] != "ok":
            raise RuntimeError(f"store stats failed: {reply!r}")
        return reply[1]

    def stats(self) -> Dict[str, int]:
        return dict(self._stats)

    # -- connections ----------------------------------------------------
    def _conn(self, owner: str, fresh: bool = False) -> Endpoint:
        with self._conn_lock:
            ep = self._conns.get(owner)
            if ep is not None and not fresh:
                return ep
            if ep is not None:
                try:
                    ep.close()
                except Exception:
                    pass
            # Python Endpoint, not the native client: the store protocol
            # interleaves control and raw chunk frames on one channel,
            # which only the Python req path speaks.
            ep = Endpoint("req").connect(owner, retries=1)
            self._conns[owner] = ep
            return ep

    def _drop_conn(self, owner: str) -> None:
        with self._conn_lock:
            ep = self._conns.pop(owner, None)
        if ep is not None:
            try:
                ep.close()
            except Exception:
                pass

    def close(self) -> None:
        with self._conn_lock:
            conns, self._conns = dict(self._conns), {}
        for ep in conns.values():
            try:
                ep.close()
            except Exception:
                pass

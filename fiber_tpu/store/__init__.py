"""fiber_tpu.store — the per-host object store (by-reference data plane).

Layer between L3 transport and the pool API: large task args/results are
``put`` once into a content-addressed host store and travel as tiny
:class:`ObjectRef` handles; workers resolve refs through a per-host
cache. See docs/objectstore.md for lifecycle, thresholds and failure
semantics.

Process-wide singletons: one LocalStore (and at most one StoreServer /
StoreClient) per process — "per-host" is the design point, so every
pool and queue in a process shares the same store, and worker processes
on one host share the on-disk cache tier under the staging root.
"""

from __future__ import annotations

import threading
from typing import Optional, Tuple

from fiber_tpu.store.core import (  # noqa: F401
    LocalStore,
    ObjectRef,
    default_store_root,
    digest_of,
)
from fiber_tpu.store.plane import (  # noqa: F401
    STORE_CHUNK,
    StoreClient,
    StoreFetchError,
    StoreServer,
)
from fiber_tpu.store.replicate import REPLICATOR  # noqa: F401

_lock = threading.Lock()
_store: Optional[LocalStore] = None
_server: Optional[StoreServer] = None
_client: Optional[StoreClient] = None
_dtier = None  # DeviceTier | None — peek convention, never instantiate


def device_store_tier():
    """The process-wide DeviceTier (docs/objectstore.md "Device tier"),
    built from config on first use; None when `store_device_enabled`
    is off. Per device-owning process — on TPU that IS per host.
    (Named apart from the ``store.device_tier`` SUBMODULE: importing
    that module rebinds the package attribute of the same name, so an
    accessor called ``device_tier`` would shadow itself on first use.)"""
    global _dtier
    with _lock:
        from fiber_tpu import config

        cfg = config.get()
        if not bool(cfg.store_device_enabled):
            # Live knob: an already-built tier is withheld (not torn
            # down) while disabled, so re-enabling keeps its contents.
            return None
        if _dtier is None:
            from fiber_tpu.store.device_tier import DeviceTier

            _dtier = DeviceTier(
                capacity_bytes=int(cfg.store_device_capacity_mb) << 20)
        return _dtier


def local_store() -> LocalStore:
    """The process-wide LocalStore, built from config on first use."""
    global _store
    with _lock:
        if _store is None:
            from fiber_tpu import config

            cfg = config.get()
            _store = LocalStore(
                capacity_bytes=int(cfg.store_capacity_mb) << 20,
                root=default_store_root(),
            )
        return _store


def ensure_server(ip: str) -> Tuple[StoreServer, str]:
    """The process-wide StoreServer (bound on first use); returns
    ``(server, addr)``. Masters call this; workers only ever dial."""
    global _server
    store = local_store()
    with _lock:
        if _server is None:
            _server = StoreServer(store, ip)
        return _server, _server.addr


def client() -> StoreClient:
    """The process-wide StoreClient (resolution cache + owner conns)."""
    global _client
    store = local_store()
    with _lock:
        if _client is None:
            _client = StoreClient(store)
        return _client


def reset(close: bool = True) -> None:
    """Drop the singletons (tests: rebuild against fresh config)."""
    global _store, _server, _client, _dtier
    with _lock:
        store, server, cli = _store, _server, _client
        dtier = _dtier
        _store = _server = _client = _dtier = None
    if dtier is not None:
        dtier.clear()
    if close:
        if server is not None:
            server.close()
        if cli is not None:
            cli.close()

"""Write-ahead map ledger: the durability layer under ``Pool.map(...,
job_id=...)`` (docs/robustness.md, "Durable maps").

The one failure domain the process/health/store planes cannot survive is
the **master itself**: a multi-hour ES/POET run dies with the process
that submitted it. The ledger closes that hole with the lineage posture
of Ray's fault-tolerance design — *recompute only what was lost, never
re-run what completed*:

* On submit, the map's **header** (task digest, chunking, trace id, and
  the content address of a resumable spec payload) is written — fsync'd
  — to an append-only file ``<staging>/ledger/<job_id>.ledger`` before
  the first chunk is dispatched.
* On each completed chunk, the master serializes the chunk's result
  values, persists them into the host object store's disk tier
  (``<staging>/objects/<digest>.obj`` — the same content-addressed
  cache agents serve), and appends a ``chunk`` record referencing the
  digest. Both happen on a dedicated writer thread: the result hot loop
  pays **one buffered append**, and fsyncs are batched per drain
  (``ledger_fsync_s``).
* On completion a ``done`` record closes the file.

Recovery — ``fiber-tpu resume <job_id>`` or re-calling ``map`` with the
same ``job_id`` — loads the ledger (tolerating a torn tail line from
the crash instant), restores every journaled chunk's results by digest
(local disk first, then the per-host caches via the backend's
``fetch_object``), and resubmits **only** the remainder. Records are
JSON lines, so ledgers are greppable operator artifacts as well as
recovery inputs.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from fiber_tpu import serialization
from fiber_tpu.store.core import digest_of
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Record schema version (bump on incompatible layout changes; load
#: refuses newer versions loudly instead of misreading them).
LEDGER_VERSION = 1

_JOB_ID_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def check_job_id(job_id: str) -> str:
    """Job ids become file names under the staging root, so anything
    path-shaped is rejected before it touches the filesystem."""
    if (not isinstance(job_id, str) or not job_id
            or len(job_id) > 128 or not set(job_id) <= _JOB_ID_OK):
        raise ValueError(
            f"invalid job_id {job_id!r}: use 1-128 chars from "
            "[A-Za-z0-9._-]")
    return job_id


def default_ledger_dir() -> str:
    """``ledger_dir`` config, or ``<staging root>/ledger`` — beside the
    ``objects/`` cache the journaled result payloads persist into."""
    from fiber_tpu import config

    configured = str(config.get().ledger_dir or "")
    if configured:
        return os.path.realpath(configured)
    from fiber_tpu.host_agent import default_staging_root

    return os.path.join(os.path.realpath(default_staging_root()), "ledger")


def job_path(job_id: str, ledger_dir: Optional[str] = None) -> str:
    return os.path.join(ledger_dir or default_ledger_dir(),
                        f"{check_job_id(job_id)}.ledger")


def list_jobs(ledger_dir: Optional[str] = None) -> list:
    try:
        names = os.listdir(ledger_dir or default_ledger_dir())
    except OSError:
        return []
    return sorted(n[:-len(".ledger")] for n in names
                  if n.endswith(".ledger"))


def task_digest(func: Callable, n_items: int, star: bool) -> str:
    """Weak identity of a map's task spec, stable across *processes*
    (a cloudpickle blob is not): the function's import path plus the
    item count and call shape. Guards job_id reuse against a different
    workload, not against same-named code edits — resumed tasks must be
    idempotent anyway (the resilient-pool contract)."""
    name = (getattr(func, "__module__", "?") or "?",
            getattr(func, "__qualname__",
                    getattr(func, "__name__", type(func).__name__)))
    spec = f"{name[0]}.{name[1]}|{int(n_items)}|{int(bool(star))}"
    return hashlib.sha256(spec.encode()).hexdigest()


def stream_task_digest(func: Callable, star: bool) -> str:
    """Stream-map task identity: like :func:`task_digest` but with NO
    item count — a stream's length is unknowable at submit time (the
    producer may not have run yet). Same guard scope: catches job_id
    reuse across different workloads, not same-named code edits."""
    name = (getattr(func, "__module__", "?") or "?",
            getattr(func, "__qualname__",
                    getattr(func, "__name__", type(func).__name__)))
    spec = f"{name[0]}.{name[1]}|stream|{int(bool(star))}"
    return hashlib.sha256(spec.encode()).hexdigest()


def load(path: str) -> Tuple[Dict[str, Any], Dict[int, Tuple[int, str]],
                             bool]:
    """Read one ledger: ``(header, completed, done)`` where completed
    maps ``base -> (n_items, payload_digest)``. A torn tail line (the
    crash landed mid-append) is skipped, never fatal; duplicate chunk
    records (speculation / resumed runs) keep the first occurrence.
    Stream ledgers (``kind="stream"`` headers) load too — callers
    branch on ``header["kind"]`` and use :func:`load_stream` for the
    admit/cursor records."""
    header: Dict[str, Any] = {}
    completed: Dict[int, Tuple[int, str]] = {}
    done = False
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                # Only the tail can be torn in an append-only file; a
                # mid-file parse failure would mean corruption, but the
                # safe degradation is identical: treat the rest as
                # unjournaled and re-execute it.
                logger.warning("ledger %s: skipping torn/corrupt record",
                               path)
                continue
            kind = rec.get("kind")
            if kind in ("map", "stream"):
                if int(rec.get("v", 0)) > LEDGER_VERSION:
                    raise ValueError(
                        f"ledger {path} is version {rec.get('v')}; this "
                        f"build reads <= {LEDGER_VERSION}")
                header = rec
            elif kind == "chunk":
                base = int(rec["base"])
                if base not in completed:
                    completed[base] = (int(rec["n"]), str(rec["digest"]))
            elif kind == "done":
                done = True
    if not header:
        raise ValueError(f"ledger {path} has no map header")
    return header, completed, done


def load_stream(path: str) -> Tuple[Dict[str, Any],
                                    Dict[int, Tuple[int, str]],
                                    Dict[int, Tuple[int, str]],
                                    int, bool]:
    """Read one STREAM ledger: ``(header, admits, completed, cursor,
    done)``. ``admits`` maps ``base -> (n, input_payload_digest)`` —
    the journaled input chunks, re-executable without the (dead)
    producer; ``completed`` maps ``base -> (n, result_digest)``;
    ``cursor`` is the LAST journaled consumer position (last-wins, not
    max: a fresh consumer restarting from zero must supersede the old
    run's high-water mark). The writer queue is FIFO, so journaled
    admits always form a contiguous prefix of admission order and
    ``completed``'s keys are a subset of ``admits``'s."""
    header: Dict[str, Any] = {}
    admits: Dict[int, Tuple[int, str]] = {}
    completed: Dict[int, Tuple[int, str]] = {}
    cursor = 0
    done = False
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                logger.warning("ledger %s: skipping torn/corrupt record",
                               path)
                continue
            kind = rec.get("kind")
            if kind == "stream":
                if int(rec.get("v", 0)) > LEDGER_VERSION:
                    raise ValueError(
                        f"ledger {path} is version {rec.get('v')}; this "
                        f"build reads <= {LEDGER_VERSION}")
                header = rec
            elif kind == "admit":
                base = int(rec["base"])
                if base not in admits:
                    admits[base] = (int(rec["n"]), str(rec["digest"]))
            elif kind == "chunk":
                base = int(rec["base"])
                if base not in completed:
                    completed[base] = (int(rec["n"]), str(rec["digest"]))
            elif kind == "cursor":
                cursor = int(rec["consumed"])
            elif kind == "done":
                done = True
    if not header:
        raise ValueError(f"ledger {path} has no stream header")
    return header, admits, completed, cursor, done


class MapLedger:
    """Writer side of one job's ledger.

    ``record_chunk`` is the hot-loop entry: one lock round + list append;
    a daemon writer thread persists the payload into ``store`` (disk
    tier, so it survives the process) and appends the record, batching
    file ``fsync``\\ s per drain. ``on_chunk(digest)`` fires after each
    record is durable (the replication hook registers precious digests
    through it)."""

    def __init__(self, path: str, store,
                 fsync_interval: float = 0.05,
                 on_chunk: Optional[Callable[[str], None]] = None) -> None:
        self.path = path
        self._store = store
        self._interval = max(0.0, float(fsync_interval))
        self._on_chunk = on_chunk
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # A crash mid-append leaves a torn final line WITHOUT a newline;
        # appending straight after it would weld the next record onto
        # the garbage and lose both. Terminate it first — load() then
        # skips exactly one unparseable line.
        torn = False
        try:
            with open(path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                torn = fh.read(1) != b"\n"
        except (OSError, ValueError):
            pass  # missing or empty file: nothing to terminate
        self._fh = open(path, "a")
        if torn:
            self._fh.write("\n")
        self._cond = threading.Condition()
        self._queue: list = []
        self._pending = 0        # queued + in-write records
        self._closed = False
        #: base -> (n, digest) of every durably journaled chunk,
        #: including records adopted from a prior (crashed) run.
        self.journaled: Dict[int, Tuple[int, str]] = {}
        #: base -> (n, digest) of every journaled stream ADMIT (input
        #: chunk payloads; empty for classic whole-map ledgers).
        self.admitted: Dict[int, Tuple[int, str]] = {}
        self.digests: set = set()
        self.chunks_journaled = 0
        #: Disk bytes this ledger cost: journal lines (header, chunk,
        #: done records) plus the serialized result payloads persisted
        #: into the store's disk tier — the accounting plane's
        #: ``ledger_bytes`` axis (docs/observability.md).
        self.bytes_written = 0
        self._thread = threading.Thread(
            target=self._writer_loop, name="fiber-map-ledger", daemon=True)
        self._thread.start()

    # -- hot-loop side ---------------------------------------------------
    def adopt(self, completed: Dict[int, Tuple[int, str]]) -> None:
        """Seed the dedup table from a loaded ledger (resume path): the
        prior run's chunks are already journaled and must not be
        re-appended when their restored fills echo through."""
        with self._cond:
            self.journaled.update(completed)
            self.digests.update(d for _, d in completed.values())
            self.chunks_journaled = len(self.journaled)

    def has(self, base: int) -> bool:
        with self._cond:
            return base in self.journaled

    # -- stream-ledger records (docs/streaming.md) -----------------------
    def adopt_admits(self, admits: Dict[int, Tuple[int, str]]) -> None:
        """Seed the admit dedup table on stream resume: the prior run's
        admitted input chunks are durable already and must not
        re-journal when the resumed producer re-admits them."""
        with self._cond:
            self.admitted.update(admits)
            self.digests.update(d for _, d in admits.values())

    def record_admit(self, base: int, n: int, items) -> bool:
        """Queue one admitted input chunk (the stream-ledger
        write-ahead leg): the writer persists the chunk's ITEMS into
        the store's disk tier and appends an ``admit`` record, so
        ``fiber-tpu resume`` can re-execute the chunk after a master
        crash without the (gone) producer iterator. Same hot-loop cost
        contract as record_chunk."""
        with self._cond:
            if self._closed or base in self.admitted:
                return False
            self.admitted[base] = (int(n), "")
            self._queue.append(("admit", base, int(n), items))
            self._pending += 1
            self._cond.notify_all()
        return True

    def record_cursor(self, consumed: int) -> bool:
        """Queue the consumer's position (count of results yielded, in
        order). Safe after close — the consumer may still be draining
        yielded results when the map's completion callbacks close the
        ledger; a dropped cursor only costs re-emitting a few consumed
        results on resume, never correctness. Pending cursor records
        coalesce: only the newest position is worth an fsync."""
        with self._cond:
            if self._closed:
                return False
            for i, rec in enumerate(self._queue):
                if rec[0] == "cursor":
                    self._queue[i] = ("cursor", int(consumed))
                    return True
            self._queue.append(("cursor", int(consumed)))
            self._pending += 1
            self._cond.notify_all()
        return True

    def record_chunk(self, base: int, n: int, values) -> bool:
        """Queue one completed chunk's result values for journaling —
        the writer thread serializes, persists the payload into the
        store's disk tier and appends the record, so the caller pays
        one lock round + list append. Returns False when the chunk is
        already journaled (speculative duplicates, resumed re-fills) or
        the ledger is closed."""
        with self._cond:
            if self._closed or base in self.journaled:
                return False
            # Reserve the base immediately: a duplicate result arriving
            # before the writer drains must not journal twice.
            self.journaled[base] = (int(n), "")
            self._queue.append(("chunk", base, int(n), values))
            self._pending += 1
            self._cond.notify_all()
        return True

    def record_done(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._queue.append(("done",))
            self._pending += 1
            self._cond.notify_all()

    def write_header(self, header: Dict[str, Any]) -> None:
        """Append + fsync the map header synchronously: the write-ahead
        contract — no chunk may dispatch before the header is durable."""
        rec = dict(header)
        rec.setdefault("kind", "map")
        rec.setdefault("v", LEDGER_VERSION)
        with self._cond:
            line = json.dumps(rec) + "\n"
            self._fh.write(line)
            self.bytes_written += len(line)
            self._fh.flush()
            os.fsync(self._fh.fileno())
        FLIGHT.record("store", "ledger", job=rec.get("job_id"),
                      event="header", n_items=rec.get("n_items"))

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until everything queued so far is durable (fsync'd)."""
        with self._cond:
            return self._cond.wait_for(lambda: self._pending == 0,
                                       timeout)

    def close(self) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(10.0)
        with self._cond:
            try:
                self._fh.close()
            except OSError:
                pass

    # -- writer thread ---------------------------------------------------
    def _writer_loop(self) -> None:
        import time

        from fiber_tpu.testing import chaos

        while True:
            with self._cond:
                self._cond.wait_for(
                    lambda: self._queue or self._closed)
                if not self._queue and self._closed:
                    return
                closing = self._closed
            if self._interval and not closing:
                # Accumulation window BEFORE the drain: a burst of chunk
                # completions lands in one write + one fsync instead of
                # paying the disk round trip per record.
                time.sleep(self._interval)
            with self._cond:
                batch, self._queue = self._queue, []
            wrote = 0
            for rec in batch:
                try:
                    line = self._durable_record(rec)
                except Exception:  # noqa: BLE001 - durability best-effort
                    # An unjournaled chunk degrades to re-execution on
                    # resume (tasks are idempotent); it must never take
                    # the pool down.
                    logger.warning("ledger %s: record failed; chunk will "
                                   "re-execute on resume", self.path,
                                   exc_info=True)
                    line = None
                if line is None:
                    continue
                with self._cond:
                    self._fh.write(line + "\n")
                    self.bytes_written += len(line) + 1
                wrote += 1
            with self._cond:
                if wrote:
                    try:
                        self._fh.flush()
                        os.fsync(self._fh.fileno())
                    except OSError:
                        logger.warning("ledger %s: fsync failed",
                                       self.path, exc_info=True)
                self._pending -= len(batch)
                self._cond.notify_all()
            if wrote:
                FLIGHT.record("store", "ledger",
                              path=os.path.basename(self.path),
                              event="append", records=wrote)
            # Post-fsync chaos hook: `kill_master_after_chunks` models a
            # master SIGKILL with exactly-N-journaled-chunks semantics
            # (the records above are durable when it fires).
            plan = chaos._plan
            if plan is not None:
                plan.maybe_kill_master(self.chunks_journaled)

    def _durable_record(self, rec) -> Optional[str]:
        if rec[0] == "done":
            return json.dumps({"kind": "done"})
        if rec[0] == "cursor":
            return json.dumps({"kind": "cursor", "consumed": rec[1]})
        if rec[0] == "admit":
            _, base, n, items = rec
            payload = serialization.dumps(items)
            digest = digest_of(payload)
            # Payload first, record second — same orphan-over-dangling
            # rule as result chunks.
            self._store.put_bytes(payload, refs=1, persist=True,
                                  digest=digest)
            with self._cond:
                self.admitted[base] = (n, digest)
                self.digests.add(digest)
                self.bytes_written += len(payload)
            if self._on_chunk is not None:
                try:  # admits are precious too: resume needs them
                    self._on_chunk(digest)
                except Exception:  # noqa: BLE001 - hook is observational
                    pass
            return json.dumps({"kind": "admit", "base": base, "n": n,
                               "digest": digest})
        _, base, n, values = rec
        payload = serialization.dumps(values)
        digest = digest_of(payload)
        # Payload first, record second: a crash between the two leaves
        # an orphan object (harmless), never a record pointing at
        # nothing.
        self._store.put_bytes(payload, refs=1, persist=True,
                              digest=digest)
        with self._cond:
            self.journaled[base] = (n, digest)
            self.digests.add(digest)
            self.chunks_journaled += 1
            self.bytes_written += len(payload)
        if self._on_chunk is not None:
            try:
                self._on_chunk(digest)
            except Exception:  # noqa: BLE001 - hook is observational
                pass
        return json.dumps({"kind": "chunk", "base": base, "n": n,
                           "digest": digest})

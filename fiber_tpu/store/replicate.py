"""Precious-digest replication: host-loss tolerance for the store plane.

Some store objects are **precious**: losing their last replica loses
work that cannot be cheaply recomputed — the map ledger's journaled
result payloads (docs/robustness.md "Durable maps") and the active
broadcast objects of in-flight maps. This module is the registry of
those digests plus the copy routine the health plane triggers: when the
backend's failure detector declares a host suspect, the master
re-replicates every precious digest to a second healthy host (agent
``store_put`` into its ``<staging>/objects`` cache), so a recovery —
even one that outlives the suspect host — never needs it.

Deliberately one-way and best-effort: replication is a durability
*bonus* on top of the master's own disk tier, never a correctness
dependency, and it must never take the health plane down with it
(``TpuBackend._on_host_suspect`` runs it on a throwaway thread).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List

from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Upper bound on digests copied per suspect declaration — a suspect
#: storm must not turn the master into a full-store mirror job.
MAX_PER_EVENT = 128


class Replicator:
    """Refcounted registry of precious digests + the fan-out copier."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._refs: Dict[str, int] = {}
        self.replicated_total = 0
        self.failed_total = 0
        #: Backend-installed entry point for policy-driven replication
        #: (callable(reason) -> copies made). The backend's suspect
        #: handler calls replicate_for_suspect directly; the policy
        #: plane goes through drive() below because it has no suspect,
        #: only an anomaly (heartbeat_age / throughput_drop).
        self._driver = None
        self.driven_total = 0

    # -- registry --------------------------------------------------------
    def note(self, digests: Iterable[str]) -> None:
        with self._lock:
            for d in digests:
                self._refs[d] = self._refs.get(d, 0) + 1

    def forget(self, digests: Iterable[str]) -> None:
        with self._lock:
            for d in digests:
                n = self._refs.get(d, 0) - 1
                if n <= 0:
                    self._refs.pop(d, None)
                else:
                    self._refs[d] = n

    def precious(self) -> List[str]:
        with self._lock:
            return list(self._refs)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {"precious": len(self._refs),
                    "replicated": self.replicated_total,
                    "failed": self.failed_total,
                    "driven": self.driven_total}

    # -- policy-plane driver ---------------------------------------------
    def register_driver(self, fn) -> None:
        """Install the backend's pre-emptive replication entry point
        (``fn(reason) -> int``). Last registration wins — one live
        backend per process is the operating regime."""
        with self._lock:
            self._driver = fn

    def has_driver(self) -> bool:
        with self._lock:
            return self._driver is not None

    def drive(self, reason: str = "policy") -> bool:
        """Kick one pre-emptive replication pass on a throwaway thread
        (same isolation posture as the suspect handler — replication
        must never wedge the caller, here the watchdog's anomaly hook).
        Returns whether a pass was started."""
        with self._lock:
            fn = self._driver
        if fn is None or not self.precious():
            return False

        def _run() -> None:
            try:
                fn(reason)
            except Exception:  # noqa: BLE001 - bonus, never load-bearing
                logger.exception("store: driven replication failed")

        threading.Thread(target=_run, name="fiber-store-replicate",
                         daemon=True).start()
        with self._lock:
            self.driven_total += 1
        return True

    # -- copy routine ----------------------------------------------------
    def replicate_for_suspect(self, suspect_key: str, targets,
                              get_bytes, host_has, host_put) -> int:
        """Copy every precious digest to the first healthy target that
        lacks it. Pure function over injected callables so backends and
        tests drive it identically:

        * ``targets`` — ordered healthy host keys (suspect excluded);
        * ``get_bytes(digest)`` — local payload source (the master's
          store: RAM or disk tier), None when unavailable;
        * ``host_has(host, digest)`` / ``host_put(host, digest, data)``
          — the agent cache probes/writes.

        Returns how many digests gained a replica."""
        digests = self.precious()[:MAX_PER_EVENT]
        if not digests or not targets:
            return 0
        copied = 0
        for digest in digests:
            placed = False
            try:
                data = get_bytes(digest)
            except Exception:  # noqa: BLE001 - local read must not wedge
                data = None
            if data is None:
                continue
            for host in targets:
                try:
                    if host_has(host, digest):
                        placed = True  # a live replica already exists
                        break
                    host_put(host, digest, bytes(data))
                    placed = True
                    copied += 1
                    FLIGHT.record(
                        "store", "replicate", digest=digest[:8],
                        host=str(host), suspect=str(suspect_key),
                        bytes=len(data),
                        reason="owner suspect; precious digest copied "
                               "to a second host")
                    break
                except Exception:  # noqa: BLE001 - try the next host
                    continue
            if not placed:
                with self._lock:
                    self.failed_total += 1
        with self._lock:
            self.replicated_total += copied
        if copied:
            logger.warning(
                "store: replicated %d precious object(s) away from "
                "suspect host %s", copied, suspect_key)
        return copied


#: Process-wide registry: the ledger and pool register through this, the
#: backend's suspect handler drains it.
REPLICATOR = Replicator()

"""Device-resident store tier: broadcast payloads live ON the mesh
(docs/objectstore.md "Device tier").

The host object store (core.py / plane.py) ends every resolution at the
host->device boundary: a worker that resolves a broadcast ref holds host
bytes, and each ``jax.device_put`` re-pays PCIe/H2D for content the
chips already saw last generation. This tier closes that gap: a bounded
LRU of ``digest -> (device-resident pytree, per-leaf sharding
metadata)`` so the resolution order becomes **device tier -> host RAM ->
disk -> wire**. An ES/POET master that re-broadcasts the same params
digest pays ZERO wire bytes and ZERO H2D on repeats — the replicated
``jax.Array`` is handed straight back.

Placement traffic is accounted honestly through the device telemetry
plane under the new ``ici`` transfer site (``DEVICE.transfer``): one
host->device ingest plus the ``(n_dev - 1) x nbytes`` mesh fan-out per
put, so ``Pool.cost()`` and ``fiber-tpu explain`` can split transfer
blame between ICI bytes and wire bytes.

Capacity discipline mirrors :class:`fiber_tpu.store.core.LocalStore`:
``refs`` are lifecycle hints, ``pins`` are hard (a pinned entry is never
evicted), and eviction walks LRU order. Unlike the host store, eviction
never *loses* data — the host tiers still hold the serialized bytes, so
dropping a device copy only costs the next resolution one H2D.

The ``hbm_fill`` watchdog rule (telemetry/monitor.py) DEMOTES the tier
under HBM pressure — the first closed-loop remediation in the stack:
every entry is dropped, a ``store``/``remediate`` flight event records
the action, and resolutions fall through to the host tiers with zero
lost tasks until the rule clears and the tier re-promotes.

Per-process by design: a ``jax.Array`` cannot be shared across OS
processes, but on TPU one process owns a host's chips — so per
device-owning process IS per host, and co-located host-plane workers
(which never device_put) are unaffected.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from fiber_tpu import telemetry
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

# Registry twins (docs/observability.md metric catalog): the same
# counters ``stats()`` exposes, mirrored so cluster_metrics / the
# Prometheus endpoint see device-tier behavior without a store RPC.
_m_dev_puts = telemetry.counter(
    "store_device_puts", "Objects placed into the device store tier")
_m_dev_hits = telemetry.counter(
    "store_device_hits", "Device store tier resolution hits")
_m_dev_evictions = telemetry.counter(
    "store_device_evictions",
    "Device store tier entries dropped, by cause")
_g_dev_bytes = telemetry.gauge(
    "store_device_bytes", "Device store tier resident bytes")


def _leaf_meta(leaf) -> Optional[Dict[str, Any]]:
    """Sharding metadata for one device-resident leaf: shape/dtype plus
    the NamedSharding spec when the array carries one (None fields are
    honest — a committed single-device array has no named spec)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    meta: Dict[str, Any] = {
        "shape": tuple(shape), "dtype": str(dtype),
        "nbytes": int(getattr(leaf, "nbytes", 0)),
        "sharding": None, "replicated": None,
    }
    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        spec = getattr(sharding, "spec", None)
        meta["sharding"] = str(spec) if spec is not None else \
            type(sharding).__name__
        try:
            meta["replicated"] = bool(
                sharding.is_fully_replicated)
        except Exception:  # noqa: BLE001 - exotic sharding objects
            pass
    return meta


class _DevEntry:
    __slots__ = ("obj", "nbytes", "refs", "pins", "meta")

    def __init__(self, obj: Any, nbytes: int, refs: int,
                 meta: List[Optional[Dict[str, Any]]]) -> None:
        self.obj = obj
        self.nbytes = int(nbytes)
        self.refs = int(refs)
        self.pins = 0
        self.meta = meta


class DeviceTier:
    """HBM-budgeted LRU of digest -> device-resident object; see module
    docstring. All jax imports are lazy — building the tier in a
    process that never resolves device payloads costs nothing."""

    def __init__(self, capacity_bytes: int = 256 << 20,
                 mesh=None) -> None:
        self.capacity = int(capacity_bytes)
        self.mesh = mesh  # None = fiber_tpu.parallel default mesh
        self._lock = threading.RLock()
        self._entries: "Dict[str, _DevEntry]" = {}
        self._order: List[str] = []  # LRU: oldest first
        self._demoted = False
        self._demote_reason = ""
        self._stats: Dict[str, int] = {
            "puts": 0, "hits": 0, "misses": 0, "evictions": 0,
            "bytes": 0, "demotions": 0, "put_dedup_hits": 0,
        }

    # -- placement ------------------------------------------------------
    def _mesh(self):
        if self.mesh is not None:
            return self.mesh
        from fiber_tpu.parallel.mesh import default_mesh

        return default_mesh()

    def _n_dev(self, mesh) -> int:
        try:
            n = 1
            for v in mesh.shape.values():
                n *= int(v)
            return max(1, n)
        except Exception:  # noqa: BLE001 - exotic mesh objects
            return 1

    def _replicate(self, host_leaf, mesh):
        """One H2D to the first mesh device, then the ICI fan-out —
        :func:`fiber_tpu.ops.collectives.broadcast_to_mesh`."""
        from fiber_tpu.ops.collectives import broadcast_to_mesh

        return broadcast_to_mesh(host_leaf, mesh)

    def put(self, digest: str, obj: Any,
            refs: int = 0) -> Any:
        """Place ``obj`` (a host pytree) into the tier under ``digest``:
        every array leaf is replicated across the mesh; the device-
        resident pytree is returned (and cached). A demoted or
        zero-capacity tier returns ``obj`` untouched — callers never
        need to care. Placement bytes account under the ``ici`` site:
        ingest (1x) + mesh fan-out ((n_dev - 1)x)."""
        with self._lock:
            if self._demoted or self.capacity <= 0:
                return obj
            entry = self._entries.get(digest)
            if entry is not None:
                self._stats["put_dedup_hits"] += 1
                self._touch(digest)
                return entry.obj
        import jax
        import numpy as np

        from fiber_tpu.telemetry.device import DEVICE

        mesh = self._mesh()
        n_dev = self._n_dev(mesh)
        leaves, treedef = jax.tree.flatten(obj)
        # nbytes straight off the leaf — np.ndarray and jax.Array both
        # expose it; np.asarray here would force a full D2H copy per
        # leaf whenever put() is handed an already-device-resident
        # pytree (the _objs-hit re-put after a demote/promote cycle).
        nbytes = sum(int(getattr(leaf, "nbytes", 0))
                     for leaf in leaves
                     if isinstance(leaf, (np.ndarray, np.generic))
                     or hasattr(leaf, "__jax_array__")
                     or hasattr(leaf, "sharding"))
        # Honest accounting: the ingest H2D plus the ICI fan-out to the
        # other devices, under the site explain/cost split on.
        with DEVICE.transfer("ici", nbytes * n_dev):
            dev_leaves = [
                self._replicate(leaf, mesh)
                if (isinstance(leaf, (np.ndarray, np.generic))
                    and getattr(leaf, "ndim", 0) > 0)
                or hasattr(leaf, "sharding")
                else leaf
                for leaf in leaves
            ]
        dev_obj = jax.tree.unflatten(treedef, dev_leaves)
        meta = [_leaf_meta(leaf) for leaf in dev_leaves]
        with self._lock:
            if self._demoted:
                return dev_obj  # raced a demotion: hand back, don't cache
            existing = self._entries.get(digest)
            if existing is not None:
                self._stats["put_dedup_hits"] += 1
                self._touch(digest)
                return existing.obj
            self._entries[digest] = _DevEntry(dev_obj, nbytes, refs, meta)
            self._order.append(digest)
            self._stats["puts"] += 1
            self._stats["bytes"] += nbytes
            self._evict_locked()
            _g_dev_bytes.set(float(self._stats["bytes"]))
        _m_dev_puts.inc()
        return dev_obj

    def get(self, digest: str, pin: bool = False) -> Optional[Any]:
        """The device-resident object for ``digest``, or None (miss /
        demoted). A hit refreshes LRU order."""
        with self._lock:
            if self._demoted:
                return None
            entry = self._entries.get(digest)
            if entry is None:
                self._stats["misses"] += 1
                return None
            if pin:
                entry.pins += 1
            self._touch(digest)
            self._stats["hits"] += 1
        _m_dev_hits.inc()
        return entry.obj

    def meta(self, digest: str) -> Optional[List[Optional[Dict[str, Any]]]]:
        """Per-leaf sharding metadata of a resident entry (shape, dtype,
        NamedSharding spec, replication), or None on miss."""
        with self._lock:
            entry = self._entries.get(digest)
            return None if entry is None else list(entry.meta)

    def contains(self, digest: str) -> bool:
        with self._lock:
            return not self._demoted and digest in self._entries

    # -- lifecycle (LocalStore parity) ----------------------------------
    def add_ref(self, digest: str, n: int = 1) -> None:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.refs += n

    def release(self, digest: str, n: int = 1) -> None:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.refs = max(0, entry.refs - n)

    def unpin(self, digest: str) -> None:
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                entry.pins = max(0, entry.pins - 1)

    def delete(self, digest: str) -> None:
        with self._lock:
            self._drop_locked(digest, cause="delete")
            _g_dev_bytes.set(float(self._stats["bytes"]))

    def _touch(self, digest: str) -> None:
        try:
            self._order.remove(digest)
        except ValueError:
            pass
        self._order.append(digest)

    def _drop_locked(self, digest: str, cause: str) -> None:
        entry = self._entries.pop(digest, None)
        if entry is None:
            return
        try:
            self._order.remove(digest)
        except ValueError:
            pass
        self._stats["bytes"] = max(0, self._stats["bytes"] - entry.nbytes)
        self._stats["evictions"] += 1
        _m_dev_evictions.inc(cause=cause)

    def _evict_locked(self) -> None:
        """LRU walk past capacity. Pins are untouchable; refs do NOT
        protect (unlike the host store there is nothing to spill — the
        host tiers still hold the bytes, so dropping a device copy only
        costs the next resolution one H2D)."""
        if self._stats["bytes"] <= self.capacity:
            return
        for digest in list(self._order):
            if self._stats["bytes"] <= self.capacity:
                break
            entry = self._entries.get(digest)
            if entry is None or entry.pins > 0:
                continue
            self._drop_locked(digest, cause="capacity")

    # -- closed-loop remediation (hbm_fill watchdog rule) ----------------
    def demote(self, reason: str = "hbm_fill") -> int:
        """Drop every unpinned entry and stop admitting new ones — the
        ``hbm_fill`` remediation (telemetry/monitor.py). Returns the
        bytes freed. Resolutions fall through to host RAM/disk/wire, so
        in-flight maps lose nothing; flight-evented so the postmortem
        trail shows the watchdog ACTED, not just observed."""
        with self._lock:
            if self._demoted:
                return 0
            freed = 0
            dropped = 0
            for digest in list(self._order):
                entry = self._entries.get(digest)
                if entry is None or entry.pins > 0:
                    continue
                freed += entry.nbytes
                dropped += 1
                self._drop_locked(digest, cause="demote")
            self._demoted = True
            self._demote_reason = str(reason)
            self._stats["demotions"] += 1
            _g_dev_bytes.set(float(self._stats["bytes"]))
        FLIGHT.record("store", "remediate", rule=str(reason),
                      action="demote_device_tier", dropped=dropped,
                      bytes=freed)
        logger.warning(
            "store: device tier demoted to host RAM (%s) — dropped %d "
            "entries / %d bytes; resolutions fall through to the host "
            "tiers", reason, dropped, freed)
        return freed

    def promote(self) -> None:
        """Re-admit entries (the breach cleared). Flight-evented like
        the demotion so the remediation window is visible end to end."""
        with self._lock:
            if not self._demoted:
                return
            self._demoted = False
            reason, self._demote_reason = self._demote_reason, ""
        FLIGHT.record("store", "remediate", rule=reason,
                      action="promote_device_tier")
        logger.info("store: device tier re-promoted (%s cleared)", reason)

    @property
    def demoted(self) -> bool:
        with self._lock:
            return self._demoted

    # -- read side ------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._stats)
            out["entries"] = len(self._entries)
            out["capacity_bytes"] = self.capacity
            out["demoted"] = int(self._demoted)
        return out

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._order.clear()
            self._demoted = False
            self._demote_reason = ""
            for key in self._stats:
                self._stats[key] = 0
            _g_dev_bytes.set(0.0)

"""Shared-state managers: a server process owning Python objects, driven by
method-call proxies from anywhere in the process tree.

Reference parity: fiber/managers.py (SyncManager + AsyncManager). Design
choices kept from the reference:

* The proxy RPC rides stdlib ``multiprocessing.connection`` (length-prefixed
  pickle with HMAC auth) — a deliberately separate, battle-tested transport
  from the queue data plane (reference: fiber/managers.py:26-31).
* The server runs inside a ``fiber_tpu.Process`` and hands its address back
  through a fiber Pipe (reference: fiber/managers.py:154-187).
* ``AsyncManager`` proxies return futures immediately; each in-flight call
  owns a connection, and the server serves connections in parallel threads,
  so N slow calls overlap (reference: fiber/managers.py:433-586).
"""

from __future__ import annotations

import queue as pyqueue
import socket
import threading
import traceback
from multiprocessing.connection import Client, Listener
from typing import Any, Callable, Dict, Optional, Tuple

from fiber_tpu.utils.logging import get_logger
from fiber_tpu.utils.serve import serve_authenticated

logger = get_logger()

_CREATE = "#CREATE"
_SHUTDOWN = "#SHUTDOWN"
_PING = "#PING"


class Namespace:
    def __init__(self, **kwargs: Any) -> None:
        self.__dict__.update(kwargs)

    def __repr__(self) -> str:
        items = ", ".join(f"{k}={v!r}" for k, v in self.__dict__.items())
        return f"Namespace({items})"


class _Value:
    def __init__(self, typecode: str, value: Any) -> None:
        self._typecode = typecode
        self._value = value

    def get(self) -> Any:
        return self._value

    def set(self, value: Any) -> None:
        self._value = value


def _make_array(typecode: str, seq) -> list:
    return list(seq)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class Server:
    """Owns the shared objects; serves one thread per client connection so
    independent proxies progress in parallel."""

    def __init__(self, registry: Dict[str, Callable], authkey: bytes) -> None:
        from fiber_tpu.backends import get_backend

        self._registry = registry
        # Bind only the address consumers actually dial (the backend's
        # listen ip) — 0.0.0.0 exposed the HMAC-pickle RPC to every
        # interface even for purely local backends (advisor, round 1).
        ip, _, _ = get_backend().get_listen_addr()
        # No authkey on the Listener: the shared hardened loop runs the
        # same mutual challenge per connection instead, so a hostile
        # client (connect-close, connect-and-hold, wrong key) can
        # neither kill this plane's accept loop nor stall other
        # proxies (fiber_tpu/utils/serve.py; the host agent had the
        # identical exposure).
        self._authkey = bytes(authkey)
        self._listener = Listener((ip, 0))
        self.address: Tuple[str, int] = (ip, self._listener.address[1])
        self._objects: Dict[int, Any] = {}
        self._next_ident = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()

    def serve_forever(self) -> None:
        serve_authenticated(self._listener, self._authkey, self._stop,
                            self._serve_connection, "fiber-manager-conn")
        try:
            self._listener.close()
        except OSError:
            pass

    def _serve_connection(self, conn) -> None:
        try:
            while True:
                request = conn.recv()
                ident, method, args, kwargs = request
                try:
                    result = self._dispatch(ident, method, args, kwargs)
                except SystemExit:
                    conn.send((True, None))
                    raise
                except BaseException as exc:  # noqa: BLE001 - shipped back
                    conn.send((False, (exc, traceback.format_exc())))
                    continue
                conn.send((True, result))
        except (EOFError, OSError):
            pass
        except SystemExit:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, ident, method, args, kwargs):
        if ident == 0:  # control plane
            if method == _CREATE:
                typeid = args[0]
                factory = self._registry[typeid]
                obj = factory(*args[1:], **kwargs)
                with self._lock:
                    self._next_ident += 1
                    new_ident = self._next_ident
                    self._objects[new_ident] = obj
                return new_ident
            if method == _PING:
                return "pong"
            if method == _SHUTDOWN:
                self._stop.set()
                try:
                    self._listener.close()
                except OSError:
                    pass
                # Wake the parked accept — closing the fd alone doesn't:
                # the in-flight accept syscall pins the listen socket
                # open, so one drain connect completes it and the loop
                # sees the stop flag (same pattern as ServeDaemon.stop).
                # Without this the server process never exits and the
                # parent's shutdown() burns its full join timeout.
                try:
                    socket.create_connection(self.address, 0.5).close()
                except OSError:
                    pass
                raise SystemExit(0)
            raise ValueError(f"unknown control method {method!r}")
        obj = self._objects[ident]
        if method == "#GETVALUE":
            return obj
        fn = getattr(obj, method)
        result = fn(*args, **kwargs)
        # Views/iterators can't pickle; ship a snapshot list instead.
        if isinstance(result, (type({}.keys()), type({}.values()),
                               type({}.items()))):
            result = list(result)
        return result


def _run_server(registry, writer, authkey) -> None:
    server = Server(registry, authkey)
    writer.send(server.address)
    writer.close()
    server.serve_forever()


# ---------------------------------------------------------------------------
# Proxies
# ---------------------------------------------------------------------------


class BaseProxy:
    """Synchronous proxy: one lazily-opened, lock-serialized connection per
    proxy instance per process; picklable as (address, ident, typeid).

    Proxies for *blocking* primitives set ``_per_thread_conn = True``: each
    user thread then gets its own connection (and therefore its own server
    thread), which (a) lets another thread release/abort while one blocks
    in acquire()/wait() on the same proxy, and (b) maps thread ownership
    (RLock reentrancy) onto server threads correctly."""

    _exposed_: Tuple[str, ...] = ()
    _per_thread_conn = False

    def __init__(self, address, ident: int, typeid: str,
                 authkey: Optional[bytes] = None) -> None:
        self._address = tuple(address)
        self._ident = ident
        self._typeid = typeid
        self._authkey = authkey
        self._conn = None
        self._conn_lock = threading.Lock()
        self._tl = threading.local()

    def _resolve_authkey(self) -> bytes:
        if self._authkey is not None:
            return bytes(self._authkey)
        from fiber_tpu.process import current_process

        return bytes(current_process().authkey)

    def _get_conn(self):
        if self._per_thread_conn:
            if getattr(self._tl, "conn", None) is None:
                self._tl.conn = Client(self._address,
                                       authkey=self._resolve_authkey())
                self._tl.lock = threading.Lock()
            return self._tl.conn, self._tl.lock
        with self._conn_lock:
            if self._conn is None:
                self._conn = Client(self._address,
                                    authkey=self._resolve_authkey())
        return self._conn, self._conn_lock

    def _call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        conn, lock = self._get_conn()
        with lock:
            conn.send((self._ident, method, args, kwargs))
            ok, payload = conn.recv()
        if ok:
            return payload
        exc, tb = payload
        raise type(exc)(*exc.args) if _rebuildable(exc) else RuntimeError(
            f"{exc!r}\n\nRemote traceback:\n{tb}"
        )

    # pickling: authkey travels implicitly via the fiber process tree
    def __reduce__(self):
        return (
            _rebuild_proxy,
            (type(self), self._address, self._ident, self._typeid),
        )

    def __repr__(self) -> str:
        return (f"<{type(self).__name__} {self._typeid} ident={self._ident} "
                f"at {self._address}>")


def _rebuildable(exc: BaseException) -> bool:
    try:
        type(exc)(*exc.args)
        return True
    except Exception:
        return False


def _rebuild_proxy(cls, address, ident, typeid):
    return cls(address, ident, typeid)


def MakeProxyType(name: str, exposed: Tuple[str, ...],
                  base=BaseProxy) -> type:
    """Generate a proxy class whose listed methods forward remotely
    (reference: fiber/managers.py:304-345)."""

    namespace: Dict[str, Any] = {"_exposed_": tuple(exposed)}
    for method in exposed:
        def make(m):
            def call(self, *args, **kwargs):
                return self._call(m, *args, **kwargs)

            call.__name__ = m
            return call

        namespace[method] = make(method)
    return type(name, (base,), namespace)


_LIST_METHODS = (
    "append", "extend", "insert", "pop", "remove", "index", "count",
    "sort", "reverse", "clear", "__getitem__", "__setitem__",
    "__delitem__", "__len__", "__contains__",
)
_DICT_METHODS = (
    "get", "keys", "values", "items", "update", "pop", "clear",
    "setdefault", "__getitem__", "__setitem__", "__delitem__", "__len__",
    "__contains__",
)
_QUEUE_METHODS = ("put", "get", "put_nowait", "get_nowait", "qsize",
                  "empty", "full")
_JQUEUE_METHODS = _QUEUE_METHODS + ("task_done", "join")
_EVENT_METHODS = ("set", "clear", "is_set", "wait")
_LOCK_METHODS = ("acquire", "release")
_BARRIER_METHODS = ("wait", "reset", "abort")

ListProxy = MakeProxyType("ListProxy", _LIST_METHODS)
DictProxy = MakeProxyType("DictProxy", _DICT_METHODS)
QueueProxy = MakeProxyType("QueueProxy", _QUEUE_METHODS)
JoinableQueueProxy = MakeProxyType("JoinableQueueProxy", _JQUEUE_METHODS)
EventProxy = MakeProxyType("EventProxy", _EVENT_METHODS)
class BarrierProxy(MakeProxyType("_BarrierProxyBase", _BARRIER_METHODS)):
    _per_thread_conn = True  # abort() must work while wait() blocks


class LockProxy(MakeProxyType("_LockProxyBase", _LOCK_METHODS)):
    """Distributed lock/semaphore: context-manager capable. Per-thread
    connections give each user thread its own server thread, so blocking
    acquires don't wedge the proxy and RLock ownership/reentrancy follows
    the calling thread."""

    _per_thread_conn = True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc_info):
        self.release()


SemaphoreProxy = LockProxy  # same surface: acquire/release + `with`


class ConditionProxy(MakeProxyType(
        "_ConditionProxyBase",
        ("acquire", "release", "wait", "notify", "notify_all"),
        base=LockProxy)):
    # wait() must not wedge notify() callers: per-thread conns inherited
    # from LockProxy, along with the context-manager protocol.

    def wait_for(self, predicate, timeout: Optional[float] = None) -> bool:
        """Client-side wait_for: the predicate runs HERE (it usually reads
        client-visible state), looping over remote wait()s — shipping it
        to the server would evaluate it in the wrong process (and most
        predicates don't pickle anyway)."""
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return bool(result)
_ValueProxyBase = MakeProxyType("_ValueProxyBase", ("get", "set"))
ArrayProxy = MakeProxyType("ArrayProxy", (
    "__getitem__", "__setitem__", "__len__",
))


class ValueProxy(_ValueProxyBase):
    @property
    def value(self):
        return self._call("get")

    @value.setter
    def value(self, v):
        self._call("set", v)


class _IterMixin:
    def __iter__(self):
        return iter(self._call("#GETVALUE"))


class ListProxyIter(ListProxy, _IterMixin):
    def _getcopy(self):
        return self._call("#GETVALUE")


class DictProxyIter(DictProxy, _IterMixin):
    def _getcopy(self):
        return self._call("#GETVALUE")


class NamespaceProxy(BaseProxy):
    _exposed_ = ("__getattribute__", "__setattr__", "__delattr__")

    def __getattr__(self, name):
        if name.startswith("_"):
            return object.__getattribute__(self, name)
        return self._call("__getattribute__", name)

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        self._call("__setattr__", name, value)

    def __delattr__(self, name):
        if name.startswith("_"):
            object.__delattr__(self, name)
            return
        self._call("__delattr__", name)


# ---------------------------------------------------------------------------
# Async proxies (futures)
# ---------------------------------------------------------------------------


class AsyncProxyResult:
    """Future for one async proxy call; holds its connection until read
    (reference: fiber/managers.py:433-458)."""

    def __init__(self, proxy: "AsyncBaseProxy", conn) -> None:
        self._proxy = proxy
        self._conn = conn
        self._done = False
        self._ok: Optional[bool] = None
        self._payload: Any = None

    def get(self, timeout: Optional[float] = None) -> Any:
        if not self._done:
            if timeout is not None and not self._conn.poll(timeout):
                raise TimeoutError("async manager call timed out")
            self._ok, self._payload = self._conn.recv()
            self._done = True
            self._proxy._release_conn(self._conn)
            self._conn = None
        if self._ok:
            return self._payload
        exc, tb = self._payload
        raise type(exc)(*exc.args) if _rebuildable(exc) else RuntimeError(
            f"{exc!r}\n\nRemote traceback:\n{tb}"
        )

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._done:
            return True
        return self._conn.poll(timeout)


class AsyncBaseProxy(BaseProxy):
    """Async proxy: every method returns AsyncProxyResult immediately.
    Each outstanding call owns a pooled connection, so calls overlap."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        self._free_conns: list = []
        self._pool_lock = threading.Lock()

    def _acquire_conn(self):
        with self._pool_lock:
            if self._free_conns:
                return self._free_conns.pop()
        return Client(self._address, authkey=self._resolve_authkey())

    def _release_conn(self, conn) -> None:
        with self._pool_lock:
            if len(self._free_conns) < 16:
                self._free_conns.append(conn)
                return
        try:
            conn.close()
        except OSError:
            pass

    def _call(self, method: str, *args: Any, **kwargs: Any):
        conn = self._acquire_conn()
        conn.send((self._ident, method, args, kwargs))
        return AsyncProxyResult(self, conn)

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)


def MakeAsyncProxyType(name: str, exposed: Tuple[str, ...]) -> type:
    return MakeProxyType(name, exposed, base=AsyncBaseProxy)


# ---------------------------------------------------------------------------
# Managers
# ---------------------------------------------------------------------------


class BaseManager:
    """Starts/stops the server process; factory methods create shared
    objects and wrap them in proxies."""

    _registry: Dict[str, Tuple[Callable, type]] = {}

    def __init__(self) -> None:
        self._process = None
        self._address: Optional[Tuple[str, int]] = None
        self._authkey: Optional[bytes] = None
        self._control: Optional[BaseProxy] = None

    # -- registration -------------------------------------------------
    @classmethod
    def register(cls, typeid: str, factory: Callable, proxytype: type) -> None:
        # subclasses get their own registry dict
        if "_registry" not in cls.__dict__:
            cls._registry = dict(cls._registry)
        cls._registry[typeid] = (factory, proxytype)

        def make(self, *args: Any, **kwargs: Any):
            return self._create(typeid, *args, **kwargs)

        make.__name__ = typeid
        setattr(cls, typeid, make)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "BaseManager":
        from fiber_tpu.process import Process, current_process
        from fiber_tpu.queues import Pipe

        if self._process is not None:
            raise AssertionError("manager already started")
        self._authkey = bytes(current_process().authkey)
        reader, writer = Pipe(duplex=False)
        factories = {tid: fac for tid, (fac, _) in self._registry.items()}
        from fiber_tpu.launcher import ProcessStartError

        for attempt in (1, 2):
            self._process = Process(
                target=_run_server,
                args=(factories, writer, self._authkey),
                name=f"Manager-{id(self):x}",
                daemon=True,
            )
            try:
                self._process.start()
                break
            except ProcessStartError:
                # Start-failure absorption (reference posture,
                # fiber/pool.py:96-104): a transient launch failure —
                # e.g. the admin handshake losing a race on a loaded
                # host — is retried once before surfacing; the dead
                # launch left no job behind (the launcher reaped it).
                if attempt == 2:
                    raise
                logger.warning("manager server start failed; retrying")
                self._process = None
        self._address = tuple(reader.recv(60))
        reader.close()
        self._control = BaseProxy(self._address, 0, "#control",
                                  authkey=self._authkey)
        return self

    @property
    def address(self):
        return self._address

    def _create(self, typeid: str, *args: Any, **kwargs: Any):
        if self._control is None:
            raise AssertionError("manager not started")
        ident = self._control._call(_CREATE, typeid, *args, **kwargs)
        proxytype = self._registry[typeid][1]
        return proxytype(self._address, ident, typeid, authkey=self._authkey)

    def shutdown(self) -> None:
        if self._control is not None:
            try:
                self._control._call(_SHUTDOWN)
            except Exception:
                pass
            self._control = None
        if self._process is not None:
            self._process.join(15)
            if self._process.is_alive():
                self._process.terminate()
                self._process.join(10)
            self._process = None

    def join(self, timeout: Optional[float] = None) -> None:
        if self._process is not None:
            self._process.join(timeout)

    def __enter__(self) -> "BaseManager":
        if self._process is None:
            self.start()
        return self

    def __exit__(self, *exc_info) -> None:
        self.shutdown()


class SyncManager(BaseManager):
    pass


SyncManager.register("Queue", pyqueue.Queue, QueueProxy)
SyncManager.register("JoinableQueue", pyqueue.Queue, JoinableQueueProxy)
SyncManager.register("Event", threading.Event, EventProxy)
SyncManager.register("Lock", threading.Lock, LockProxy)
SyncManager.register("RLock", threading.RLock, LockProxy)
SyncManager.register("Semaphore", threading.Semaphore, SemaphoreProxy)
SyncManager.register("BoundedSemaphore", threading.BoundedSemaphore,
                     SemaphoreProxy)
SyncManager.register("Barrier", threading.Barrier, BarrierProxy)
SyncManager.register("Condition", threading.Condition, ConditionProxy)
SyncManager.register("list", list, ListProxyIter)
SyncManager.register("dict", dict, DictProxyIter)
SyncManager.register("Namespace", Namespace, NamespaceProxy)
SyncManager.register("Value", _Value, ValueProxy)
SyncManager.register("Array", _make_array, ArrayProxy)


class AsyncManager(BaseManager):
    """Same registry, but every proxy method returns a future
    (reference: fiber/managers.py AsyncManager)."""


def _register_async(typeid: str, factory: Callable,
                    sync_proxy: type) -> None:
    exposed = getattr(sync_proxy, "_exposed_", ())
    async_proxy = MakeAsyncProxyType(f"Async{sync_proxy.__name__}", exposed)
    AsyncManager.register(typeid, factory, async_proxy)


for _tid, (_fac, _proxy) in list(SyncManager._registry.items()):
    if _tid in ("RLock", "Condition"):
        # Unsound async: overlapping calls ride different pooled
        # connections (different server threads), so thread ownership
        # (RLock reentrancy, Condition's held-lock requirement) can't be
        # honored. Use the sync manager for these.
        continue
    _register_async(_tid, _fac, _proxy)

# A generic callable wrapper so AsyncManager can host arbitrary user
# objects: manager.register_instance-style usage via `Object`.

"""Length-prefixed message framing shared by the admin channel and the
host-plane transport.

Wire format: 8-byte big-endian unsigned length, then payload. One framing
for everything (the reference uses three: nanomsg's own, raw struct-packed
admin messages, and multiprocessing.connection — fiber/socket.py,
fiber/popen_fiber_spawn.py:56-72, fiber/managers.py:26-31; unifying them is
deliberate simplification).
"""

from __future__ import annotations

import socket
import struct
from typing import Optional

_LEN = struct.Struct(">Q")

#: Sanity ceiling for one frame (1 TiB) — catches corrupted streams early.
MAX_FRAME = 1 << 40


class ConnectionClosed(OSError):
    """Peer closed the connection mid-frame or before a frame."""


def send_frame(sock: socket.socket, payload, prefix: bytes = b"") -> None:
    """Send one frame; ``prefix`` rides inside the frame before the payload
    (used by the transport for its 1-byte frame-type tag) without copying
    large payloads. ``payload`` may be any bytes-like (the object-store
    plane streams memoryview slices)."""
    header = _LEN.pack(len(payload) + len(prefix))
    if len(payload) > 65536:
        # Avoid duplicating large payloads (host-plane tensors) in memory.
        sock.sendall(header + prefix)
        sock.sendall(payload)
    else:
        sock.sendall(header + prefix + bytes(payload))


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into one preallocated buffer.

    ``recv_into`` against a single bytearray instead of accumulating
    chunks + ``b"".join(...)``: the old path held every chunk AND the
    joined copy alive at once — 2x peak memory on large frames (host-
    plane tensors). The returned bytearray is freshly allocated and
    never aliased, so handing it to callers (which treat frames as
    read-only bytes-likes) is safe."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        nread = sock.recv_into(view[got:], min(n - got, 1 << 20))
        if not nread:
            raise ConnectionClosed("connection closed while reading frame")
        got += nread
    return buf


def recv_frame(sock: socket.socket) -> bytearray:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise OSError(f"frame too large: {length}")
    if length == 0:
        return bytearray()
    return _recv_exact(sock, length)


def recv_frame_timeout(
    sock: socket.socket, timeout: Optional[float]
) -> Optional[bytes]:
    """recv_frame with a timeout; returns None if no frame *starts* within
    the timeout. The wait applies only before the first byte — once a frame
    has begun, it is read to completion, so a timeout can never strand
    partially-consumed bytes and desynchronize the stream.

    poll(), not select(): select.select rejects any fd >= FD_SETSIZE
    (1024) with "filedescriptor out of range", which a busy master —
    hundreds of workers x (socket + log file + pipe) — exceeds in
    normal operation (reference regression: fiber
    tests/test_popen.py:96-113; pinned by
    tests/test_process.py::test_transport_works_past_1024_fds)."""
    import math
    import select

    poller = select.poll()
    poller.register(sock.fileno(), select.POLLIN)
    # ceil, not truncate: a 0.5 ms wait must not silently become a
    # busy-poll (poll takes whole milliseconds).
    timeout_ms = (None if timeout is None
                  else max(0, math.ceil(timeout * 1000)))
    if not poller.poll(timeout_ms):
        return None
    return recv_frame(sock)

"""Length-prefixed message framing shared by the admin channel and the
host-plane transport.

Wire format: 8-byte big-endian unsigned length, then payload. One framing
for everything (the reference uses three: nanomsg's own, raw struct-packed
admin messages, and multiprocessing.connection — fiber/socket.py,
fiber/popen_fiber_spawn.py:56-72, fiber/managers.py:26-31; unifying them is
deliberate simplification).

Two decode surfaces (docs/transport.md):

* :func:`recv_frame` — one-shot blocking read on a raw socket, for
  sequential protocol exchanges (auth handshake, spawn bootstrap, ring
  collectives) where buffering ahead would steal bytes from the next
  protocol layer;
* :class:`FrameBuffer` / :class:`FrameReader` — incremental decode from a
  per-connection receive buffer, for long-lived channels: the 8-byte
  length prefix no longer costs its own ``recv_into`` round, so a tiny
  frame needs ONE syscall and a burst of tiny frames arriving together
  needs one syscall *total*. Large frames switch to a preallocated
  buffer filled with ``recv_into`` directly (no append-and-slice copy).
"""

from __future__ import annotations

import socket
import struct
from typing import List, Optional, Union

_LEN = struct.Struct(">Q")

#: Wire overhead per transport frame: the 8-byte length header plus the
#: transport's 1-byte frame-type tag. The single authority for billing —
#: every I/O engine (threads/selector/shm) and the accounting plane's
#: ``wire_size`` derive from this constant, so billed wire and endpoint
#: counters can never drift apart.
FRAME_OVERHEAD = _LEN.size + 1

#: Sanity ceiling for one frame (1 TiB) — catches corrupted streams early.
MAX_FRAME = 1 << 40

#: Payloads above this are sent vectored (scatter-gather) instead of being
#: concatenated with the header — one syscall either way, zero large copies.
SMALL_FRAME_MAX = 65536

_HAVE_SENDMSG = hasattr(socket.socket, "sendmsg")


def pack_header(length: int) -> bytes:
    """The 8-byte length prefix for a frame body of ``length`` bytes —
    exposed so callers that queue frames ahead of the flush (the
    selector loop's write path) pack it once at enqueue."""
    return _LEN.pack(length)


class ConnectionClosed(OSError):
    """Peer closed the connection mid-frame or before a frame."""


def sendmsg_all(sock: socket.socket, buffers) -> int:
    """Vectored (scatter-gather) send of every buffer in ``buffers``,
    looping on partial writes — ``sendall`` semantics for an iovec.
    Unlike ``sendall``, ``sendmsg`` may accept only part of the vector
    in one call (and always may on a non-blocking socket), so the tail
    is re-sent with memoryview slices — never copied. Returns the total
    byte count."""
    bufs: List[memoryview] = [
        m for m in (memoryview(b) for b in buffers) if m.nbytes
    ]
    total = sum(m.nbytes for m in bufs)
    done = 0
    while bufs:
        sent = sock.sendmsg(bufs)
        done += sent
        if done >= total:
            break
        while bufs and sent >= bufs[0].nbytes:
            sent -= bufs[0].nbytes
            bufs.pop(0)
        if sent:
            bufs[0] = bufs[0][sent:]
    return done


def send_frame(sock: socket.socket, payload, prefix: bytes = b"",
               header: Optional[bytes] = None) -> None:
    """Send one frame; ``prefix`` rides inside the frame before the payload
    (used by the transport for its 1-byte frame-type tag) without copying
    large payloads. ``payload`` may be any bytes-like (the object-store
    plane streams memoryview slices). ``header`` lets a caller that has
    already packed the 8-byte length prefix (the event loop's write queue
    builds frames ahead of the flush) hand it in instead of re-packing."""
    if header is None:
        header = _LEN.pack(len(payload) + len(prefix))
    if len(payload) > SMALL_FRAME_MAX:
        # Large path: one vectored syscall, zero payload copies (the old
        # shape was two sendall syscalls; header+payload in separate
        # TCP segments also cost the peer an extra wakeup).
        if _HAVE_SENDMSG:
            sendmsg_all(sock, (header, prefix, payload))
        else:  # pragma: no cover - platforms without sendmsg
            sock.sendall(header + prefix)
            sock.sendall(payload)
    else:
        # Small path: concatenate once so the frame leaves in one
        # segment. bytes/bytearray concatenate directly — only exotic
        # bytes-likes (memoryview slices) need materializing first.
        if not isinstance(payload, (bytes, bytearray)):
            payload = bytes(payload)
        sock.sendall(header + prefix + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes into one preallocated buffer.

    ``recv_into`` against a single bytearray instead of accumulating
    chunks + ``b"".join(...)``: the old path held every chunk AND the
    joined copy alive at once — 2x peak memory on large frames (host-
    plane tensors). The returned bytearray is freshly allocated and
    never aliased, so handing it to callers (which treat frames as
    read-only bytes-likes) is safe."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        nread = sock.recv_into(view[got:], min(n - got, 1 << 20))
        if not nread:
            raise ConnectionClosed("connection closed while reading frame")
        got += nread
    return buf


def recv_frame(sock: socket.socket) -> bytearray:
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise OSError(f"frame too large: {length}")
    if length == 0:
        return bytearray()
    return _recv_exact(sock, length)


class FrameBuffer:
    """Incremental frame decoder over an internal receive buffer.

    Feed it with :meth:`fill` (one ``recv`` against the socket — blocking
    or not is the socket's business) and drain completed frames with
    :meth:`pop`. Small frames are sliced out of the shared buffer; a
    frame whose length crosses :data:`LARGE_DIRECT` switches to a
    dedicated preallocated bytearray that later fills ``recv_into``
    directly — large payloads are written by the kernel exactly once.
    """

    #: One recv per readiness event pulls up to this much.
    RECV_CHUNK = 256 * 1024
    #: Frames at least this long bypass the append buffer.
    LARGE_DIRECT = 64 * 1024

    __slots__ = ("_buf", "_pos", "_big", "_big_view", "_big_got")

    def __init__(self) -> None:
        self._buf = bytearray()
        self._pos = 0  # parse offset into _buf (compacted in fill)
        self._big: Optional[bytearray] = None
        self._big_view: Optional[memoryview] = None
        self._big_got = 0

    def fill(self, sock: socket.socket) -> int:
        """One receive into the decode state. Returns the byte count
        (0 = EOF). Propagates ``BlockingIOError`` on a non-blocking
        socket with nothing to read."""
        if self._big is not None and self._big_got < len(self._big):
            n = sock.recv_into(
                self._big_view[self._big_got:],
                min(len(self._big) - self._big_got, 1 << 20),
            )
            self._big_got += n
            return n
        if self._pos:
            # Compact consumed bytes once per refill (between fills any
            # number of frames pop with a pure offset advance).
            del self._buf[:self._pos]
            self._pos = 0
        data = sock.recv(self.RECV_CHUNK)
        if not data:
            return 0
        self._buf += data
        return len(data)

    def pending(self) -> int:
        """Bytes buffered but not yet returned as frames."""
        n = len(self._buf) - self._pos
        if self._big is not None:
            n += self._big_got
        return n

    def pop(self) -> Optional[bytearray]:
        """Next complete frame, or None if more bytes are needed."""
        if self._big is not None:
            if self._big_got < len(self._big):
                return None
            frame = self._big
            self._big = self._big_view = None
            self._big_got = 0
            return frame
        avail = len(self._buf) - self._pos
        if avail < _LEN.size:
            return None
        (length,) = _LEN.unpack_from(self._buf, self._pos)
        if length > MAX_FRAME:
            raise OSError(f"frame too large: {length}")
        if length >= self.LARGE_DIRECT:
            # Switch to the direct path: move whatever payload is already
            # buffered (at most RECV_CHUNK) into the dedicated buffer and
            # recv_into the rest — the one copy is bounded and small.
            frame = bytearray(length)
            start = self._pos + _LEN.size
            take = min(avail - _LEN.size, length)
            frame[:take] = self._buf[start:start + take]
            self._pos = start + take
            self._big = frame
            self._big_view = memoryview(frame)
            self._big_got = take
            return self.pop()
        if avail - _LEN.size < length:
            return None
        start = self._pos + _LEN.size
        # A bytearray slice IS a fresh bytearray — no second copy.
        frame = self._buf[start:start + length]
        self._pos = start + length
        return frame


class FrameReader:
    """Blocking buffered frame reader for one long-lived socket: header
    and payload of a tiny frame arrive in one syscall, and several frames
    already queued in the kernel drain in one. Do NOT mix with raw
    :func:`recv_frame` on the same socket — buffered bytes would be
    invisible to it."""

    __slots__ = ("_sock", "_fb")

    def __init__(self, sock: socket.socket) -> None:
        self._sock = sock
        self._fb = FrameBuffer()

    def recv(self) -> bytearray:
        while True:
            frame = self._fb.pop()
            if frame is not None:
                return frame
            if self._fb.fill(self._sock) == 0:
                raise ConnectionClosed(
                    "connection closed while reading frame")


def recv_frame_timeout(
    sock: socket.socket, timeout: Optional[float]
) -> Optional[bytes]:
    """recv_frame with a timeout; returns None if no frame *starts* within
    the timeout. The wait applies only before the first byte — once a frame
    has begun, it is read to completion, so a timeout can never strand
    partially-consumed bytes and desynchronize the stream.

    poll(), not select(): select.select rejects any fd >= FD_SETSIZE
    (1024) with "filedescriptor out of range", which a busy master —
    hundreds of workers x (socket + log file + pipe) — exceeds in
    normal operation (reference regression: fiber
    tests/test_popen.py:96-113; pinned by
    tests/test_process.py::test_transport_works_past_1024_fds)."""
    import math
    import select

    poller = select.poll()
    poller.register(sock.fileno(), select.POLLIN)
    # ceil, not truncate: a 0.5 ms wait must not silently become a
    # busy-poll (poll takes whole milliseconds).
    timeout_ms = (None if timeout is None
                  else max(0, math.ceil(timeout * 1000)))
    if not poller.poll(timeout_ms):
        return None
    return recv_frame(sock)

"""Test-facing utilities shipped with the package (not just the test
suite): the deterministic fault-injection harness lives here so users can
chaos-test their own pool workloads, and so the injection hooks compiled
into pool/transport/launcher code resolve in every process of the tree
(workers import the same module the master does)."""

"""Deterministic, seed-driven fault injection (the chaos harness).

Every robustness claim above the process layer — transport reconnects,
failure-detector declarations, spawn-target breaking — is untestable
folklore without a way to *induce* the faults reproducibly. This module
is that way: a :class:`ChaosPlan` describes a fault schedule; hook sites
compiled into pool.py, transport/tcp.py, launcher.py, host_agent.py and
backends/local.py consult the active plan (a single ``is None`` check
when chaos is off, so the hot paths pay nothing).

Activation:

* programmatic (tests): ``chaos.install(ChaosPlan(seed=7, ...))`` /
  ``chaos.uninstall()`` — install also exports the plan to the
  ``FIBER_CHAOS`` environment variable so every child process of the
  tree (pool workers, sim agents) reconstructs the SAME plan at import;
* environment: ``FIBER_CHAOS="seed=7,kill_after_chunks=3,..."`` set
  before the master starts.

Determinism: the plan itself is a pure function of its spec string, and
cluster-wide budgets ("kill at most N workers total") are token files
under ``token_dir`` acquired with ``O_EXCL`` — any process of the tree
can claim a token, exactly ``limit`` ever succeed, and a fresh
``token_dir`` (the test fixture uses tmp_path) resets the schedule.
Which worker draws a given token is scheduling-dependent; the *assertion
level* (map completes, with correct results, having survived the
scheduled faults) is deterministic, which is what the seeds pin in CI.

Injection points (all no-ops unless the matching knob is set):

====================  ====================================================
kill_after_chunks     pool worker ``os._exit``\\ s after completing its
                      N-th chunk (budget ``kill_times``) — induced
                      worker death mid-map
kill_master_after_chunks  the MASTER process SIGKILLs itself once its
                      map ledger has journaled N chunks (budget
                      ``kill_master_times``) — master crash mid-map;
                      the journaled records are fsync'd first, so
                      ``fiber-tpu resume`` recovery is what's under
                      test (docs/robustness.md)
partition_after       a bound-``r`` ingress channel is PARTITIONED from
                      its peer after its N-th data frame: every frame
                      (results, heartbeats, spans) is severed for
                      ``partition_s`` seconds, then flow resumes
                      (budget ``partition_times``) — a network
                      partition between a host pair; the peer is
                      suspect, NOT dead, and its late duplicates must
                      dedupe after the heal. Both I/O engines share the
                      schedule via ``recv_frame_actions``.
corrupt_store_disk    the object store's next N disk writes (spill /
                      host-cache publication) write CORRUPTED bytes —
                      models silent disk corruption; the digest check
                      in ``LocalStore._read_disk`` must degrade it to
                      a refetch, never a wrong payload
hang_after_chunks     pool worker freezes (compute stalls AND heartbeats
                      stop) for ``hang_s`` seconds when about to run its
                      N-th chunk (budget ``hang_times``) — a hung host
slow_worker_after_chunks  pool worker turns into a STRAGGLER from its
                      N-th chunk on: every later chunk sleeps
                      ``slow_worker_s`` first, heartbeats keep flowing
                      (budget ``slow_worker_times``) — a degraded host
                      the failure detector must NOT declare dead but
                      the scheduler's speculation should route around
fail_local_spawn      LocalBackend.create_job raises (budget) — spawn
                      failure burst at the backend boundary
fail_launch           JobLauncher raises before create_job (budget)
fail_agent_spawn      host agent's spawn op raises (budget)
fail_store_fetch      object-store client's wire fetch raises (budget) —
                      workers fall back to inline payloads via the
                      pool's storemiss path instead of losing tasks
slow_store_every/_s   object-store server serves every N-th get
                      ``slow_store_s`` late — degraded-store latency
stall_recv_after      one bound-``r`` ingress channel's reader sleeps
                      ``stall_recv_s`` seconds after its N-th data frame
                      (budget ``stall_recv_times``) — a silent network
                      stall the failure detector must beat TCP to
drop_recv_every       bound-``r`` ingress drops every N-th data frame —
                      lossy-path transport testing (NOTE: dropped result
                      frames are only recovered through worker death or
                      detector declaration; don't combine with
                      completion assertions unless one of those fires)
send_delay_every/_s   every N-th Endpoint.send sleeps first — latency
                      injection on the master's egress
====================  ====================================================
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Optional

ENV_VAR = "FIBER_CHAOS"

#: Chaos-killed workers exit with this code (distinct from user exits and
#: the subworker recycle/transport codes in pool.py).
CHAOS_EXIT_CODE = 44

#: Budget-bearing fail points (``fail_<site>`` knobs / token kinds).
FAIL_SITES = ("local_spawn", "launch", "agent_spawn", "store_fetch")

_INT_FIELDS = (
    "seed", "kill_after_chunks", "kill_times",
    "kill_master_after_chunks", "kill_master_times",
    "hang_after_chunks", "hang_times",
    "slow_worker_after_chunks", "slow_worker_times",
    "fail_local_spawn", "fail_launch", "fail_agent_spawn",
    "fail_store_fetch", "slow_store_every",
    "stall_recv_after", "stall_recv_times",
    "drop_recv_every", "send_delay_every",
    "partition_after", "partition_times",
    "corrupt_store_disk",
)
_FLOAT_FIELDS = ("hang_s", "slow_worker_s", "stall_recv_s",
                 "send_delay_s", "slow_store_s", "partition_s")


class ChaosError(RuntimeError):
    """An injected failure. Deliberately a plain RuntimeError subclass:
    the code under test must treat it exactly like the real fault it
    models (a refused spawn, a dead agent), never special-case it."""


class ChaosPlan:
    """One immutable fault schedule (see module docstring for knobs)."""

    def __init__(self, seed: int = 0, token_dir: Optional[str] = None,
                 kill_after_chunks: int = 0, kill_times: int = 1,
                 kill_master_after_chunks: int = 0,
                 kill_master_times: int = 1,
                 hang_after_chunks: int = 0, hang_s: float = 3.0,
                 hang_times: int = 1,
                 slow_worker_after_chunks: int = 0,
                 slow_worker_s: float = 1.0,
                 slow_worker_times: int = 1,
                 fail_local_spawn: int = 0, fail_launch: int = 0,
                 fail_agent_spawn: int = 0,
                 fail_store_fetch: int = 0,
                 slow_store_every: int = 0, slow_store_s: float = 0.0,
                 stall_recv_after: int = 0, stall_recv_s: float = 0.0,
                 stall_recv_times: int = 1,
                 drop_recv_every: int = 0,
                 send_delay_every: int = 0,
                 send_delay_s: float = 0.0,
                 partition_after: int = 0, partition_s: float = 0.0,
                 partition_times: int = 1,
                 corrupt_store_disk: int = 0) -> None:
        self.seed = int(seed)
        self.token_dir = token_dir or os.path.join(
            tempfile.gettempdir(), f"fiber-chaos-{self.seed}")
        self.kill_after_chunks = int(kill_after_chunks)
        self.kill_times = int(kill_times)
        self.kill_master_after_chunks = int(kill_master_after_chunks)
        self.kill_master_times = int(kill_master_times)
        self.partition_after = int(partition_after)
        self.partition_s = float(partition_s)
        self.partition_times = int(partition_times)
        self.corrupt_store_disk = int(corrupt_store_disk)
        self.hang_after_chunks = int(hang_after_chunks)
        self.hang_s = float(hang_s)
        self.hang_times = int(hang_times)
        self.slow_worker_after_chunks = int(slow_worker_after_chunks)
        self.slow_worker_s = float(slow_worker_s)
        self.slow_worker_times = int(slow_worker_times)
        self.fail_local_spawn = int(fail_local_spawn)
        self.fail_launch = int(fail_launch)
        self.fail_agent_spawn = int(fail_agent_spawn)
        self.fail_store_fetch = int(fail_store_fetch)
        self.slow_store_every = int(slow_store_every)
        self.slow_store_s = float(slow_store_s)
        self.stall_recv_after = int(stall_recv_after)
        self.stall_recv_s = float(stall_recv_s)
        self.stall_recv_times = int(stall_recv_times)
        self.drop_recv_every = int(drop_recv_every)
        self.send_delay_every = int(send_delay_every)
        self.send_delay_s = float(send_delay_s)
        # Process-local state.
        self._lock = threading.Lock()
        self._hang_until = 0.0
        self._send_count = 0
        self._store_gets = 0
        self._slow = False  # this process claimed a slow-worker token

    # -- spec (env) form ------------------------------------------------
    @classmethod
    def from_env(cls, spec: Optional[str]) -> Optional["ChaosPlan"]:
        """Parse ``k=v,k=v,...``; None/empty → no plan. Unknown keys
        raise (a typo'd knob silently injecting nothing would make a
        chaos run vacuously green)."""
        if not spec:
            return None
        kwargs = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, raw = part.partition("=")
            key = key.strip()
            raw = raw.strip()
            if key in _INT_FIELDS:
                kwargs[key] = int(raw)
            elif key in _FLOAT_FIELDS:
                kwargs[key] = float(raw)
            elif key == "token_dir":
                kwargs[key] = raw
            else:
                raise ValueError(f"unknown chaos knob {key!r} in "
                                 f"{ENV_VAR}")
        return cls(**kwargs)

    def to_env(self) -> str:
        parts = [f"seed={self.seed}", f"token_dir={self.token_dir}"]
        for field in _INT_FIELDS + _FLOAT_FIELDS:
            if field == "seed":
                continue
            parts.append(f"{field}={getattr(self, field)}")
        return ",".join(parts)

    # -- cluster-wide budgets -------------------------------------------
    def acquire(self, kind: str, limit: int) -> bool:
        """Claim one token of ``kind``; at most ``limit`` claims succeed
        across ALL processes sharing this plan's token_dir (O_EXCL file
        creation is the atomic arbiter)."""
        if limit <= 0:
            return False
        try:
            os.makedirs(self.token_dir, exist_ok=True)
        except OSError:
            return False
        for i in range(limit):
            path = os.path.join(self.token_dir, f"{kind}-{i}")
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                continue
            except OSError:
                return False
            os.close(fd)
            return True
        return False

    def spent(self, kind: str) -> int:
        """How many ``kind`` tokens have been claimed so far."""
        try:
            names = os.listdir(self.token_dir)
        except OSError:
            return 0
        return sum(1 for n in names if n.startswith(kind + "-"))

    # -- injection points ------------------------------------------------
    def maybe_kill_worker(self, completed_chunks: int) -> None:
        """pool worker, after completing a chunk: die hard mid-map."""
        if (self.kill_after_chunks
                and completed_chunks == self.kill_after_chunks
                and self.acquire("kill", self.kill_times)):
            # Flight-recorder contract: the black box survives the
            # crash. os._exit fires no signal and no atexit, so the
            # postmortem flush happens HERE — a no-op unless the worker
            # armed its crash handler (docs/observability.md).
            try:
                from fiber_tpu.telemetry import postmortem

                postmortem.crash_flush("chaos-kill")
            except Exception:
                pass
            os._exit(CHAOS_EXIT_CODE)

    def maybe_kill_master(self, journaled_chunks: int) -> None:
        """Map-ledger writer, after a durable batch: SIGKILL the MASTER
        once N chunks are journaled — no signal handlers, no atexit, the
        hardest crash the OS can deliver. Fires at ``>= N`` (the batched
        fsync may jump past an exact count) under a cluster-wide token
        budget, so exactly ``kill_master_times`` masters ever die."""
        if (self.kill_master_after_chunks
                and journaled_chunks >= self.kill_master_after_chunks
                and self.acquire("kill-master", self.kill_master_times)):
            import signal

            os.kill(os.getpid(), signal.SIGKILL)

    def corrupt_disk_write(self, data: bytes) -> bytes:
        """Object-store disk publication (spill / host cache): while the
        budget lasts, the bytes that hit disk are corrupted (first and
        last bytes flipped) — the read-side digest check is what's under
        test."""
        if (self.corrupt_store_disk
                and self.acquire("corrupt-disk", self.corrupt_store_disk)):
            bad = bytearray(data)
            if bad:
                bad[0] ^= 0xFF
                bad[-1] ^= 0xFF
            return bytes(bad)
        return data

    def maybe_hang_worker(self, completed_chunks: int) -> None:
        """pool worker, before running a chunk: freeze compute AND
        heartbeats — a hung host, as seen from the master."""
        if (self.hang_after_chunks
                and completed_chunks == self.hang_after_chunks
                and self.acquire("hang", self.hang_times)):
            with self._lock:
                self._hang_until = time.monotonic() + self.hang_s
            time.sleep(self.hang_s)

    def maybe_slow_worker(self, completed_chunks: int) -> None:
        """pool worker, before running a chunk: once this worker claims
        a slow token (at its ``slow_worker_after_chunks``-th chunk) it
        stays a straggler for life — every subsequent chunk sleeps
        ``slow_worker_s`` first while heartbeats keep flowing. Models a
        degraded-but-alive host: the failure detector must not fire,
        the scheduler's speculation path is what's under test."""
        if not self.slow_worker_after_chunks:
            return
        if (not self._slow
                and completed_chunks >= self.slow_worker_after_chunks
                and self.acquire("slow", self.slow_worker_times)):
            self._slow = True
        if self._slow:
            time.sleep(self.slow_worker_s)

    def heartbeats_allowed(self) -> bool:
        with self._lock:
            return time.monotonic() >= self._hang_until

    def fail_point(self, site: str) -> None:
        """Raise ChaosError at a named spawn-path site while its budget
        lasts (``fail_<site>`` knob)."""
        budget = getattr(self, f"fail_{site}")
        if budget and self.acquire(f"fail-{site}", budget):
            raise ChaosError(f"chaos: injected {site} failure "
                             f"(seed={self.seed})")

    def recv_frame_actions(self, chan):
        """Bound-``r`` ingress, per data frame: the fault decision WITHOUT
        its side effect — returns ``(stall_s, drop)``. Counters ride the
        channel object so each connection has its own schedule. Both I/O
        modes consult this one method, so a plan's schedule is identical
        under ``transport_io=threads`` (the reader thread sleeps
        ``stall_s`` itself) and ``=selector`` (the poller parks the
        channel for ``stall_s`` instead of sleeping — one stalled
        connection must not stall every channel in the process)."""
        count = getattr(chan, "_chaos_rx", 0) + 1
        chan._chaos_rx = count
        stall_s = 0.0
        if (self.stall_recv_after and count == self.stall_recv_after
                and self.acquire("stall", self.stall_recv_times)):
            stall_s = self.stall_recv_s
        drop = bool(self.drop_recv_every
                    and count % self.drop_recv_every == 0)
        # Partition: from frame N, sever EVERYTHING on this channel for
        # partition_s seconds — the host pair is cut, not slowed. The
        # peer keeps sending (it is alive), so the master's failure
        # detector must declare it suspect, and the post-heal late
        # frames must dedupe — suspect != dead, proven.
        if (self.partition_after
                and count == self.partition_after
                and self.acquire("partition", self.partition_times)):
            chan._chaos_partition_until = (
                time.monotonic() + self.partition_s)
            try:
                from fiber_tpu.telemetry.flightrec import FLIGHT

                FLIGHT.record("transport", "partition",
                              cid=getattr(chan, "cid", None),
                              s=self.partition_s,
                              reason="chaos: host pair severed")
            except Exception:
                pass
        until = getattr(chan, "_chaos_partition_until", 0.0)
        if until and time.monotonic() < until:
            drop = True
        return stall_s, drop

    def on_recv_frame(self, chan) -> bool:
        """Blocking-reader form of :meth:`recv_frame_actions`: sleeps the
        stall in place and returns False to drop the frame."""
        stall_s, drop = self.recv_frame_actions(chan)
        if stall_s > 0.0:
            time.sleep(stall_s)
        return not drop

    def on_send_frame(self) -> None:
        """Endpoint.send, per frame: latency injection."""
        if not self.send_delay_every:
            return
        with self._lock:
            self._send_count += 1
            delay = self._send_count % self.send_delay_every == 0
        if delay:
            time.sleep(self.send_delay_s)

    def maybe_slow_store(self) -> None:
        """Object-store server, per get: every N-th object is served
        ``slow_store_s`` late — a saturated or degraded store the
        by-reference data plane must absorb without failing tasks."""
        if not self.slow_store_every:
            return
        with self._lock:
            self._store_gets += 1
            slow = self._store_gets % self.slow_store_every == 0
        if slow:
            time.sleep(self.slow_store_s)


#: The active plan. Hook sites read this attribute directly — None means
#: chaos is off and costs one attribute load.
_plan: Optional[ChaosPlan] = ChaosPlan.from_env(os.environ.get(ENV_VAR))


def active() -> Optional[ChaosPlan]:
    return _plan


def install(plan: ChaosPlan) -> ChaosPlan:
    """Activate ``plan`` in this process AND export it so child
    processes (pool workers, sim agents) reconstruct it at import."""
    global _plan
    _plan = plan
    os.environ[ENV_VAR] = plan.to_env()
    return plan


def uninstall() -> None:
    global _plan
    _plan = None
    os.environ.pop(ENV_VAR, None)


def heartbeats_allowed() -> bool:
    """Gate for Heartbeater: False while the active plan simulates a
    hung host in this process."""
    plan = _plan
    return plan is None or plan.heartbeats_allowed()

"""fiber_tpu.telemetry — the cluster observability plane.

Three parts (docs/observability.md):

* **Metrics registry** (:mod:`.metrics`) — thread-safe Counter / Gauge /
  Histogram with bounded label sets and a near-zero-cost disabled path,
  instrumenting the pool task loop, transport framing, object store,
  health plane and launcher.
* **Task-lifecycle tracing** (:mod:`.tracing`) — Dapper-style spans with
  a propagated ``(trace_id, parent_span_id)`` context: the master stamps
  it into each task envelope, workers adopt it, finished spans ride back
  on the existing result stream into the master's ring-buffer span
  store.
* **Export** (:mod:`.export`) — Chrome trace-event JSON (Perfetto),
  Prometheus v0.0.4 text exposition, and an authenticated metrics
  endpoint on the shared serve plane.

Enablement follows config (``telemetry_enabled``, ``trace_sample_rate``,
``span_buffer_size``): :func:`refresh` re-reads it, and is called from
``fiber_tpu.init`` and the worker bootstrap so the whole process tree
observes one setting.
"""

from __future__ import annotations

import os
import random
from typing import Any, Dict, Optional

from fiber_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    merge_snapshots,
)
from fiber_tpu.telemetry import tracing  # noqa: F401
from fiber_tpu.telemetry.flightrec import FLIGHT  # noqa: F401
from fiber_tpu.telemetry.profiler import PROFILER  # noqa: F401
from fiber_tpu.telemetry.timeseries import TIMESERIES  # noqa: F401
from fiber_tpu.telemetry.tracing import (  # noqa: F401
    SPANS,
    current_trace_id,
    host_id,
    span,
    trace_context,
)

#: The process-wide registry every fiber_tpu instrument reports into.
REGISTRY = MetricsRegistry(enabled=True)

_sample_rate = 1.0
_rng = random.Random()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", **kwargs) -> Histogram:
    return REGISTRY.histogram(name, help, **kwargs)


def enabled() -> bool:
    return REGISTRY.enabled


def tracing_active() -> bool:
    """Spans are recorded at all (the per-map sampling decision is
    separate — :func:`maybe_start_trace`)."""
    return REGISTRY.enabled and _sample_rate > 0.0


def maybe_start_trace() -> Optional[str]:
    """Sampling decision for one logical operation (one Pool map):
    a fresh trace id, or None when telemetry is off / the sample is
    skipped."""
    if not REGISTRY.enabled or _sample_rate <= 0.0:
        return None
    if _sample_rate < 1.0 and _rng.random() >= _sample_rate:
        return None
    return tracing.new_id()


def refresh() -> None:
    """Re-read the telemetry config knobs (called from fiber_tpu.init
    and the worker bootstrap after config adoption)."""
    global _sample_rate
    from fiber_tpu import config

    cfg = config.get()
    REGISTRY.enabled = bool(cfg.telemetry_enabled)
    _sample_rate = max(0.0, min(1.0, float(cfg.trace_sample_rate)))
    if SPANS._spans.maxlen != int(cfg.span_buffer_size):
        SPANS.resize(int(cfg.span_buffer_size))
    # Flight recorder rides the same master switch plus its own knob
    # (docs/observability.md).
    FLIGHT.enabled = bool(cfg.telemetry_enabled) \
        and bool(cfg.flightrec_enabled)
    if FLIGHT._events.maxlen != int(cfg.flightrec_buffer_size):
        FLIGHT.resize(int(cfg.flightrec_buffer_size))
    # Continuous monitor plane (docs/observability.md): the sampler
    # thread + anomaly watchdog ride the same master switch; the
    # profiler arms on its own hz knob. Lazy import keeps the module
    # graph acyclic (monitor registers instruments against THIS
    # module).
    from fiber_tpu.telemetry.monitor import WATCHDOG

    WATCHDOG.configure(cfg)
    TIMESERIES.add_observer(WATCHDOG.observe)
    TIMESERIES.configure(
        enabled=bool(cfg.telemetry_enabled) and bool(cfg.monitor_enabled),
        interval=float(cfg.monitor_interval_s),
        capacity=int(cfg.monitor_history))
    PROFILER.set_hz(
        float(cfg.profiler_hz) if cfg.telemetry_enabled else 0.0)
    # Device telemetry plane (docs/observability.md "Device telemetry"):
    # transfer accounting, jax.monitoring compile listeners, and the
    # HBM/live-array gauges the monitor sampler reads each tick. Lazy
    # import, same posture as monitor above.
    from fiber_tpu.telemetry.device import DEVICE

    DEVICE.configure(cfg)
    TIMESERIES.add_probe(DEVICE.update_gauges)
    # Accounting plane (docs/observability.md "Resource accounting"):
    # per-map/per-tenant cost attribution. Lazy import, same posture as
    # monitor/device above.
    from fiber_tpu.telemetry.accounting import COSTS

    COSTS.configure(cfg)
    # Policy plane (docs/observability.md "Autonomous operations"):
    # watchdog anomalies -> remediation actions with verified outcomes.
    # Lazy import, same posture as monitor/device/accounting above.
    from fiber_tpu.telemetry.policy import POLICY

    POLICY.configure(cfg)
    # Persistent archive + SLO plane (docs/observability.md "SLOs and
    # the archive"): the archive flushes each sampler tick through its
    # observer hook (near-zero when disarmed); the SLO tracker is
    # driven by the serve daemon's tick. Lazy imports, same posture.
    from fiber_tpu.telemetry.archive import ARCHIVE
    from fiber_tpu.telemetry.slo import SLO

    ARCHIVE.configure(cfg)
    TIMESERIES.add_observer(ARCHIVE.on_sample)
    SLO.configure(cfg)


def snapshot() -> Dict[str, Any]:
    """One process's telemetry state, picklable — the payload of the
    host agent's ``telemetry_snapshot`` op and of ``cluster_metrics``."""
    from fiber_tpu.utils.profiling import global_timer

    try:
        # Scheduler plane (docs/scheduling.md): per-pool queue depths,
        # per-host in-flight chunk counts and decision totals for every
        # live scheduler in this process (empty for agents without
        # pools).
        from fiber_tpu import sched as _sched

        sched_snaps = _sched.snapshots()
    except Exception:  # pragma: no cover - snapshot must never fail
        sched_snaps = []
    return {
        "host": host_id(),
        "pid": os.getpid(),
        "enabled": REGISTRY.enabled,
        "trace_sample_rate": _sample_rate,
        "metrics": REGISTRY.snapshot(),
        "timers": global_timer.stats(),
        "spans_buffered": len(SPANS),
        "spans_dropped": SPANS.dropped,
        "flight_buffered": len(FLIGHT),
        "flight_recorded": FLIGHT.recorded,
        "flight_dropped": FLIGHT.dropped,
        "monitor": TIMESERIES.last_sample(),
        "monitor_samples": TIMESERIES.samples,
        "profiler_hz": PROFILER.hz,
        "profiler_samples": PROFILER.samples,
        "sched": sched_snaps,
        "device": _device_snapshot(),
        "costs": _cost_snapshot(),
        "policy": _policy_snapshot(),
    }


def _policy_snapshot() -> Dict[str, Any]:
    """Policy-plane surface for the generic snapshot (null-safe: a
    snapshot must never fail)."""
    try:
        from fiber_tpu.telemetry.policy import POLICY

        return POLICY.snapshot()
    except Exception:  # pragma: no cover - snapshot must never fail
        return {}


def _cost_snapshot() -> Dict[str, Any]:
    """Accounting-plane surface for the generic snapshot (null-safe:
    a snapshot must never fail)."""
    try:
        from fiber_tpu.telemetry.accounting import COSTS

        return COSTS.snapshot()
    except Exception:  # pragma: no cover - snapshot must never fail
        return {}


def _device_snapshot() -> Dict[str, Any]:
    """Device-plane surface for the generic snapshot (null-safe: a
    snapshot must never fail, and must never initialize a backend)."""
    try:
        from fiber_tpu.telemetry.device import DEVICE

        return DEVICE.snapshot()
    except Exception:  # pragma: no cover - snapshot must never fail
        return {}


def serve_metrics(port: int = 0, bind: str = "127.0.0.1"):
    """Start the authenticated Prometheus endpoint for this process;
    returns the server (``.port``, ``.stop()``)."""
    from fiber_tpu.telemetry.export import MetricsServer

    return MetricsServer(port=port, bind=bind)


# Initial enablement from whatever config is already resolved (workers
# re-sync in their bootstrap once the master's config arrives).
try:  # pragma: no cover - import-order safety net
    refresh()
except Exception:
    pass

"""Telemetry exporters: Chrome trace-event JSON (Perfetto / chrome://
tracing) and Prometheus v0.0.4 text exposition, plus the authenticated
metrics endpoint.

The Prometheus handler deliberately rides the SAME hardened
accept/authenticate plane as the host agent and managers server
(fiber_tpu/utils/serve.py) instead of opening an unauthenticated HTTP
port: the metrics of a cluster that moves pickled closures around are
operator data, and every listening fiber_tpu socket shares one threat
posture. Scrape with ``fiber-tpu metrics --hosts … --prom`` or any
client that speaks multiprocessing.connection with the cluster key.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from fiber_tpu.telemetry import metrics as _metrics
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Exposition content type (the v0.0.4 text format Prometheus scrapes).
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4"

_PREFIX = "fiber_"


# ---------------------------------------------------------------------------
# Chrome trace-event JSON (load in Perfetto / chrome://tracing)
# ---------------------------------------------------------------------------


def chrome_trace(spans: List[Dict]) -> Dict:
    """Span dicts -> a Chrome trace-event JSON object. Mapping:
    pid = host (one process row per cluster host), tid = the worker
    process's OS pid on that host — so a pool map renders as the
    master's serialize span followed by per-worker execute lanes."""
    hosts: Dict[str, int] = {}
    events: List[Dict] = []
    for sp in spans:
        host = str(sp.get("host", "host"))
        pid = hosts.setdefault(host, len(hosts) + 1)
        tid = int(sp.get("pid", 0))
        args = {k: v for k, v in sp.items()
                if k not in ("name", "ts", "dur", "host", "pid")}
        events.append({
            "name": str(sp.get("name", "span")),
            "ph": "X",
            "ts": float(sp.get("ts", 0.0)) * 1e6,
            "dur": max(float(sp.get("dur", 0.0)), 1e-7) * 1e6,
            "pid": pid,
            "tid": tid,
            "cat": str(sp.get("name", "span")).split(".", 1)[0],
            "args": args,
        })
    meta = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": host}}
        for host, pid in hosts.items()
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, spans: List[Dict],
                       xla_dir: Optional[str] = None,
                       xla_wall_start: Optional[float] = None) -> str:
    """Write spans as Chrome trace JSON; with ``xla_dir`` the newest
    XLA profiler capture under it (``jax.profiler.trace`` output) is
    merged in so device ops render beside the host spans — the unified
    timeline (docs/observability.md "Device telemetry")."""
    doc = chrome_trace(spans)
    if xla_dir:
        merge_xla_trace(doc, xla_dir, wall_start=xla_wall_start)
    with open(path, "w") as fh:
        json.dump(doc, fh)
    return path


# ---------------------------------------------------------------------------
# Unified host+device timeline: merge an XLA profiler capture
# ---------------------------------------------------------------------------


def find_xla_chrome_trace(log_dir: str) -> Optional[str]:
    """Newest ``*.trace.json[.gz]`` under a ``jax.profiler.trace`` log
    directory (the profiler writes Chrome trace-event JSON beside its
    TensorBoard protos, under ``plugins/profile/<run>/``), or None."""
    newest: Optional[str] = None
    newest_mtime = -1.0
    for root, _dirs, files in os.walk(log_dir):
        for name in files:
            if not (name.endswith(".trace.json.gz")
                    or name.endswith(".trace.json")):
                continue
            path = os.path.join(root, name)
            try:
                mtime = os.path.getmtime(path)
            except OSError:
                continue
            if mtime > newest_mtime:
                newest, newest_mtime = path, mtime
    return newest


def load_xla_chrome_trace(path: str) -> Optional[Dict]:
    """Parse one XLA Chrome trace file (plain or gzipped); None when the
    file is unreadable or not a trace (merging is best-effort — a
    missing device capture must never fail a host trace dump)."""
    import gzip

    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return None
    return doc


def merge_xla_trace(doc: Dict, log_dir: str,
                    wall_start: Optional[float] = None) -> int:
    """Merge the newest XLA capture under ``log_dir`` into a host
    Chrome-trace ``doc`` (chrome_trace output), in place. Host spans
    carry wall-epoch timestamps; XLA events carry the profiler's own
    µs origin — with ``wall_start`` (the wall clock when the capture
    began, noted by ``utils.profiling.trace``) the device events are
    rebased onto the wall axis so both planes line up on the dual
    clock; without it they are rebased to the host trace's start.
    Device pids are offset past the host rows (and their process_name
    metadata prefixed ``XLA``) so Perfetto renders separate device
    lanes. Returns the number of device events merged (0 = no capture
    found; never raises)."""
    try:
        path = find_xla_chrome_trace(log_dir)
        if path is None:
            return 0
        xla = load_xla_chrome_trace(path)
        if xla is None:
            return 0
        host_events = doc.setdefault("traceEvents", [])
        pid_base = max((int(e.get("pid", 0)) for e in host_events),
                       default=0) + 1000
        xla_events = xla.get("traceEvents", [])
        timed = [float(e["ts"]) for e in xla_events if "ts" in e]
        xla_t0 = min(timed) if timed else 0.0
        if wall_start is None:
            wall_start = min(
                (float(e["ts"]) / 1e6 for e in host_events
                 if e.get("ph") == "X"), default=0.0)
        offset_us = float(wall_start) * 1e6 - xla_t0
        merged = 0
        for ev in xla_events:
            if "ph" not in ev:
                # Chrome trace arrays may end with a bare {} (and some
                # producers emit phase-less entries); a merged artifact
                # must stay iterable by strict consumers.
                continue
            ev = dict(ev)
            if "pid" in ev:
                ev["pid"] = int(ev["pid"]) + pid_base
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                args = dict(ev.get("args") or {})
                args["name"] = f"XLA {args.get('name', 'device')}"
                ev["args"] = args
            if "ts" in ev:
                ev["ts"] = float(ev["ts"]) + offset_us
            host_events.append(ev)
            merged += 1
        return merged
    except Exception:  # noqa: BLE001 - merging is strictly best-effort
        logger.exception("telemetry: XLA trace merge failed; "
                         "writing host spans only")
        return 0


# ---------------------------------------------------------------------------
# Prometheus v0.0.4 text exposition
# ---------------------------------------------------------------------------


def _prom_name(name: str, kind: str) -> str:
    full = name if name.startswith(_PREFIX) else _PREFIX + name
    if kind == "counter" and not full.endswith("_total"):
        full += "_total"
    return full


def _prom_labels(key: str, extra: str = "") -> str:
    parts = [p for p in (extra, key) if p]
    if not parts:
        return ""
    rendered = []
    for part in parts:
        for pair in part.split(","):
            k, _, v = pair.partition("=")
            v = v.replace("\\", "\\\\").replace('"', '\\"')
            rendered.append(f'{k}="{v}"')
    return "{" + ",".join(rendered) + "}"


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None) -> str:
    """Render a ``registry.snapshot()`` dict (default: the process
    registry) as Prometheus v0.0.4 text exposition."""
    if snapshot is None:
        from fiber_tpu import telemetry

        snapshot = telemetry.REGISTRY.snapshot()
    lines: List[str] = []
    for name in sorted(snapshot):
        entry = snapshot[name]
        kind = entry.get("type", "untyped")
        full = _prom_name(name, kind)
        if entry.get("help"):
            lines.append(f"# HELP {full} {entry['help']}")
        lines.append(f"# TYPE {full} "
                     f"{kind if kind != 'untyped' else 'untyped'}")
        series = entry.get("series", {})
        if kind == "histogram":
            bounds = entry.get("buckets", [])
            for key in sorted(series):
                values = series[key]
                cum = 0
                for i, bound in enumerate(bounds):
                    cum += values[i]
                    lines.append(
                        f"{full}_bucket"
                        f"{_prom_labels(key, f'le={bound:g}')} {cum}")
                cum += values[len(bounds)]
                lines.append(
                    f"{full}_bucket{_prom_labels(key, 'le=+Inf')} {cum}")
                lines.append(f"{full}_sum{_prom_labels(key)} "
                             f"{values[-2]:g}")
                lines.append(f"{full}_count{_prom_labels(key)} "
                             f"{values[-1]}")
        else:
            for key in sorted(series):
                lines.append(f"{full}{_prom_labels(key)} "
                             f"{float(series[key]):g}")
    return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, float]:
    """Minimal exposition parser (tests + CLI sanity): sample name with
    its label string -> value. Raises ValueError on malformed lines."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed exposition line: {line!r}")
        out[name_part] = float(value_part)
    return out


# ---------------------------------------------------------------------------
# Authenticated metrics endpoint
# ---------------------------------------------------------------------------


class MetricsServer:
    """Serves this process's telemetry over the authenticated RPC plane:
    request ``("metrics",)`` -> Prometheus text, ``("snapshot",)`` ->
    the raw telemetry snapshot dict. Same HMAC challenge + hardened
    accept loop as the host agent."""

    def __init__(self, port: int = 0, bind: str = "127.0.0.1",
                 authkey: Optional[bytes] = None) -> None:
        from multiprocessing.connection import Listener

        from fiber_tpu.auth import cluster_key

        if (bind not in ("127.0.0.1", "localhost")
                and authkey is None
                and "FIBER_CLUSTER_KEY" not in os.environ):
            raise RuntimeError(
                "metrics server: refusing to bind non-loopback interface "
                f"{bind!r} with the default cluster key; set "
                "FIBER_CLUSTER_KEY or bind 127.0.0.1")
        self._authkey = authkey or cluster_key()
        self._listener = Listener((bind, port))
        self.port = self._listener.address[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="fiber-metrics-serve", daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        from fiber_tpu.utils.serve import serve_request_reply

        serve_request_reply(self._listener, self._authkey, self._stop,
                            self._answer, "fiber-metrics-conn")

    def _answer(self, request):
        from fiber_tpu import telemetry

        op = request[0] if isinstance(request, tuple) else request
        if op == "metrics":
            return prometheus_text()
        if op == "snapshot":
            return telemetry.snapshot()
        raise ValueError(f"unknown metrics op {op!r}")

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass

"""Postmortem capture: black-box bundles for dead or dying processes
(docs/observability.md).

A **bundle** is one JSON file answering "what was that process doing
when it died": the process's flight-recorder events
(:mod:`fiber_tpu.telemetry.flightrec`), a ``faulthandler``-style
all-thread stack dump, and identity/reason metadata. Three producers
write them, all under ``<staging root>/postmortem/`` — the same
agent-servable root the object store and code staging use, so the host
agent can ship bundles to the operator without widening its file-op
confinement:

* **workers** install :func:`install_crash_handler` (pool worker
  bootstrap): SIGTERM/SIGABRT flush a bundle before the process dies,
  and the chaos harness's hard-kill (``os._exit``) calls
  :func:`crash_flush` first — modeling a real flight recorder's
  survive-the-crash property;
* **the health plane** (``ResilientPool._on_peer_suspect``): when the
  failure detector declares a worker dead, the master writes a bundle
  with its own view of the dead ident and best-effort pulls the peer
  host's ``postmortem`` agent op into it;
* **operators**: ``fiber-tpu postmortem`` lists/prints bundles locally
  or pulls them from agents.

Bundles are bounded: the newest :data:`MAX_BUNDLES` are kept per
directory, oldest pruned at write time.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import traceback
from typing import Any, Dict, List, Optional

from fiber_tpu.telemetry.flightrec import FLIGHT

#: Bundle files kept per postmortem directory before the oldest are
#: pruned (each is a few KB; a crash-looping worker must not fill the
#: staging disk).
MAX_BUNDLES = 64

SCHEMA = "fiber-postmortem-v1"

_BUNDLE_PREFIX = "pm-"


def bundle_dir(root: Optional[str] = None) -> str:
    """Where bundles land: ``<staging root>/postmortem`` (the staging
    root is FIBER_AGENT_STAGING or ~/.fiber_tpu/staging — the directory
    the host agent already serves and polices)."""
    if root is None:
        from fiber_tpu.host_agent import default_staging_root

        root = default_staging_root()
    return os.path.join(root, "postmortem")


def stack_dump() -> str:
    """All-thread stack dump. Prefers ``faulthandler`` (the
    async-signal-safe canonical form); falls back to a pure-Python walk
    of ``sys._current_frames`` when faulthandler can't take a file
    (some embedders)."""
    try:
        import faulthandler

        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            return fh.read()
    except Exception:  # noqa: BLE001 - the dump must never fail capture
        try:
            import threading

            names = {t.ident: t.name for t in threading.enumerate()}
            lines: List[str] = []
            for tid, frame in sys._current_frames().items():
                lines.append(f"Thread {names.get(tid, tid)}:")
                lines.extend(
                    ln.rstrip() for ln in traceback.format_stack(frame))
            return "\n".join(lines)
        except Exception:  # noqa: BLE001
            return "<stack dump unavailable>"


def _log_tail(n: int = 200) -> List[str]:
    """The process log ring's tail (utils/logging.LogRing): the third
    observability pillar riding the bundle beside flight events and
    stacks — what the process was LOGGING when it died. Never fails
    capture."""
    try:
        from fiber_tpu.utils.logging import LOG_RING

        return LOG_RING.tail(n)
    except Exception:  # noqa: BLE001 - the dump must never fail capture
        return []


def capture(reason: str, ident: Optional[str] = None,
            **extra: Any) -> Dict[str, Any]:
    """Build one bundle dict from this process's state (no I/O)."""
    from fiber_tpu.telemetry import tracing

    bundle: Dict[str, Any] = {
        "schema": SCHEMA,
        "reason": str(reason),
        "host": tracing.host_id(),
        "pid": os.getpid(),
        "ts": time.time(),
        "flight": FLIGHT.snapshot(),
        "flight_dropped": FLIGHT.dropped,
        "stacks": stack_dump(),
        "logs": _log_tail(),
    }
    if ident is not None:
        bundle["ident"] = ident
    if extra:
        bundle.update(extra)
    return bundle


def _prune(directory: str) -> None:
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(_BUNDLE_PREFIX))
    except OSError:
        return
    for name in names[:-MAX_BUNDLES]:
        try:
            os.unlink(os.path.join(directory, name))
        except OSError:
            pass


def write_bundle(bundle: Dict[str, Any],
                 directory: Optional[str] = None) -> str:
    """Write one bundle as JSON under ``directory`` (default:
    :func:`bundle_dir`); returns the path. Atomic rename so a reader
    (the agent's postmortem op) never sees a torn file."""
    directory = directory or bundle_dir()
    os.makedirs(directory, exist_ok=True)
    name = (f"{_BUNDLE_PREFIX}{bundle.get('host', 'host')}-"
            f"{bundle.get('pid', 0)}-{int(bundle.get('ts', 0) * 1000)}"
            ".json")
    path = os.path.join(directory, name)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as fh:
            json.dump(bundle, fh, default=str)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _prune(directory)
    return path


def capture_and_write(reason: str, ident: Optional[str] = None,
                      directory: Optional[str] = None,
                      **extra: Any) -> str:
    return write_bundle(capture(reason, ident=ident, **extra), directory)


def list_bundles(directory: Optional[str] = None) -> List[str]:
    """Bundle paths under ``directory``, oldest first."""
    directory = directory or bundle_dir()
    try:
        names = sorted(n for n in os.listdir(directory)
                       if n.startswith(_BUNDLE_PREFIX)
                       and n.endswith(".json"))
    except OSError:
        return []
    return [os.path.join(directory, n) for n in names]


def read_bundle(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        return json.load(fh)


# ---------------------------------------------------------------------------
# Crash handler (worker side)
# ---------------------------------------------------------------------------

_installed = False
_flushed = False


def crash_flush(reason: str) -> Optional[str]:
    """Flush this process's bundle if (and only if) the crash handler
    is installed — the seam the chaos harness's hard-kill calls before
    ``os._exit``, since no signal ever fires there. Idempotent: the
    first flush wins (a SIGTERM racing an explicit flush must not write
    two bundles for one death)."""
    global _flushed
    if not _installed or _flushed:
        return None
    _flushed = True
    try:
        return capture_and_write(reason)
    except Exception:  # noqa: BLE001 - dying anyway; never mask the exit
        return None


def install_crash_handler() -> bool:
    """Arm SIGTERM/SIGABRT bundle flushing for this process (pool
    worker bootstrap calls this when the flight recorder is on). The
    handler writes the bundle, restores the previous disposition and
    re-raises the signal so the observable death semantics — exit code,
    core dumps, parent reaping — are untouched. Main-thread only (the
    signal module's rule); returns False when it can't install."""
    global _installed
    if _installed:
        return True
    import signal
    import threading

    if threading.current_thread() is not threading.main_thread():
        return False

    def make_handler(signum, prev):
        def handler(_sig, _frame):
            crash_flush(f"signal-{signal.Signals(signum).name}")
            try:
                signal.signal(signum, prev if callable(prev)
                              or prev in (signal.SIG_IGN, signal.SIG_DFL)
                              else signal.SIG_DFL)
            except (OSError, ValueError):
                pass
            os.kill(os.getpid(), signum)
        return handler

    try:
        for signum in (signal.SIGTERM, signal.SIGABRT):
            prev = signal.getsignal(signum)
            signal.signal(signum, make_handler(signum, prev))
    except (OSError, ValueError):
        return False
    _installed = True
    return True

"""Metrics registry: thread-safe Counter / Gauge / Histogram.

Design constraints (docs/observability.md):

* **Near-zero-cost disabled path** — every instrument method starts with
  one attribute read + branch on the registry's ``enabled`` flag, so hot
  paths (transport frame loop, pool task loop) can stay instrumented
  unconditionally.
* **Bounded label sets** — at most :data:`MAX_LABEL_SETS` distinct label
  combinations per metric; further ones fold into a single
  ``other="overflow"`` series instead of growing without bound (a
  misbehaving label like a per-task id cannot OOM the registry).
* **Fixed histogram buckets** — bucket boundaries are chosen at
  registration and never change, so per-host snapshots aggregate by
  simple element-wise addition (``backends.tpu.cluster_metrics``).

Instruments are process-global singletons obtained from a registry via
``registry.counter(name, help)`` — re-registration returns the existing
instrument (modules can declare their instruments at import time without
coordinating).
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, Optional, Sequence, Tuple

#: Distinct label combinations kept per metric before folding into the
#: overflow series.
MAX_LABEL_SETS = 64

#: Default histogram boundaries, seconds — spans worker-spawn latencies
#: (~1 s) down to sub-millisecond serialize/dispatch sections.
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

_OVERFLOW_KEY = (("other", "overflow"),)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def key_to_str(key: Tuple[Tuple[str, str], ...]) -> str:
    """Stable text form of a label key (snapshot dict keys must survive
    pickling across the agent RPC plane and JSON dumps)."""
    return ",".join(f"{k}={v}" for k, v in key)


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 registry: "MetricsRegistry",
                 max_label_sets: Optional[int] = None) -> None:
        self.name = name
        self.help = help
        self._reg = registry
        self._series: Dict[Tuple, object] = {}
        #: Per-metric label-set bound (default MAX_LABEL_SETS). Metrics
        #: with per-job/tenant labels register a higher bound AND retire
        #: completed-job series, so a long job sequence never folds
        #: LIVE jobs into the overflow series.
        self._max = int(max_label_sets) if max_label_sets \
            else MAX_LABEL_SETS
        #: Retired (completed-job) keys in retirement order — the LRU
        #: eviction pool a full metric drains before overflowing.
        self._retired: Dict[Tuple, None] = {}

    def _slot(self, labels: Dict[str, str]) -> Tuple:
        """Label key for this observation, bounded (caller holds the
        registry lock). A full metric first evicts its oldest RETIRED
        series (their jobs completed; the slot is reclaimable) and only
        folds into overflow when every live series is still live."""
        key = _label_key(labels)
        if key in self._series:
            self._retired.pop(key, None)  # re-observed: live again
            return key
        if len(self._series) >= self._max:
            if not self._retired:
                return _OVERFLOW_KEY
            oldest = next(iter(self._retired))
            del self._retired[oldest]
            self._series.pop(oldest, None)
        return key

    def _retire(self, match: Tuple[Tuple[str, str], ...]) -> int:
        """Mark every series whose labels contain all of ``match`` as
        retired (caller holds the registry lock); returns the count."""
        n = 0
        for key in self._series:
            if key is _OVERFLOW_KEY:
                continue
            if all(pair in key for pair in match):
                self._retired[key] = None
                n += 1
        return n

    def _snapshot_series(self) -> Dict[str, object]:
        return {key_to_str(k): v for k, v in self._series.items()}


class Counter(_Instrument):
    """Monotonic float counter."""

    kind = "counter"

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            key = self._slot(labels)
            self._series[key] = self._series.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        with self._reg._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Instrument):
    """Point-in-time value (queue depth, breaker state)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            self._series[self._slot(labels)] = float(value)

    def inc(self, n: float = 1.0, **labels: str) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            key = self._slot(labels)
            self._series[key] = self._series.get(key, 0.0) + n

    def dec(self, n: float = 1.0, **labels: str) -> None:
        self.inc(-n, **labels)

    def value(self, **labels: str) -> float:
        with self._reg._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Histogram(_Instrument):
    """Fixed-boundary histogram. A series is the list
    ``[count_per_bucket..., count_above_last, sum, count]``."""

    kind = "histogram"

    def __init__(self, name: str, help: str, registry: "MetricsRegistry",
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 max_label_sets: Optional[int] = None) -> None:
        super().__init__(name, help, registry,
                         max_label_sets=max_label_sets)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")

    def observe(self, value: float, **labels: str) -> None:
        if not self._reg.enabled:
            return
        with self._reg._lock:
            key = self._slot(labels)
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = (
                    [0] * (len(self.buckets) + 1) + [0.0, 0]
                )
            series[bisect.bisect_left(self.buckets, value)] += 1
            series[-2] += value
            series[-1] += 1

    def count(self, **labels: str) -> int:
        with self._reg._lock:
            series = self._series.get(_label_key(labels))
            return int(series[-1]) if series else 0

    def sum(self, **labels: str) -> float:
        with self._reg._lock:
            series = self._series.get(_label_key(labels))
            return float(series[-2]) if series else 0.0


class MetricsRegistry:
    """Process-wide instrument table. One global instance lives in
    ``fiber_tpu.telemetry``; separate registries exist only for tests."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        with self._lock:
            inst = self._metrics.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                return inst
            inst = cls(name, help, self, **kwargs)
            self._metrics[name] = inst
            return inst

    def counter(self, name: str, help: str = "",
                max_label_sets: Optional[int] = None) -> Counter:
        return self._get_or_create(Counter, name, help,
                                   max_label_sets=max_label_sets)

    def gauge(self, name: str, help: str = "",
              max_label_sets: Optional[int] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help,
                                   max_label_sets=max_label_sets)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  max_label_sets: Optional[int] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets,
                                   max_label_sets=max_label_sets)

    def retire_series(self, **labels: str) -> int:
        """Mark every series (any metric) whose labels contain all of
        ``labels`` as retired — completed-job series become the LRU
        eviction pool their metric drains before folding new jobs into
        overflow. Returns the number of series marked."""
        match = _label_key(labels)
        if not match:
            return 0
        with self._lock:
            return sum(inst._retire(match)
                       for inst in self._metrics.values())

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, dict]:
        """Picklable dump: {name: {type, help, [buckets,] series}}.
        Histogram series are copied lists; scalars are plain floats."""
        with self._lock:
            out: Dict[str, dict] = {}
            for name, inst in self._metrics.items():
                entry: dict = {
                    "type": inst.kind,
                    "help": inst.help,
                    "series": {
                        k: (list(v) if isinstance(v, list) else v)
                        for k, v in inst._snapshot_series().items()
                    },
                }
                if isinstance(inst, Histogram):
                    entry["buckets"] = list(inst.buckets)
                out[name] = entry
            return out

    def reset(self) -> None:
        """Clear every series (instruments stay registered) — tests."""
        with self._lock:
            for inst in self._metrics.values():
                inst._series.clear()
                inst._retired.clear()


def merge_snapshots(snapshots: Dict[str, Dict[str, dict]]) -> Dict[str, dict]:
    """Aggregate per-host ``registry.snapshot()`` dicts into one, adding
    a ``host=<key>`` label to every series so per-host structure
    survives the merge (the shape ``cluster_metrics`` renders)."""
    merged: Dict[str, dict] = {}
    for host, snap in snapshots.items():
        if not isinstance(snap, dict):
            continue
        for name, entry in snap.items():
            slot = merged.setdefault(name, {
                "type": entry.get("type", "untyped"),
                "help": entry.get("help", ""),
                "series": {},
            })
            if "buckets" in entry and "buckets" not in slot:
                slot["buckets"] = entry["buckets"]
            for key, value in entry.get("series", {}).items():
                label = f"host={host}" + (f",{key}" if key else "")
                slot["series"][label] = value
    return merged

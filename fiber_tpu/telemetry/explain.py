"""``fiber-tpu explain``: join spans + flight events and classify where
a map's time went (docs/observability.md).

Tracing (PR 3) answers *what happened*: the spans of one trace id show
serialize → dispatch → resolve-refs → execute → result across the
cluster. This module answers *why was it slow*, by joining those spans
with the flight recorder's decision/anomaly events and attributing
seconds to the five blame categories the training/inference stacks
debug daily:

==================  =====================================================
straggler           excess service time of outlier chunks — per-chunk
                    handout→result durations (``sched``/``chunk_done``
                    events, falling back to execute-span durations) above
                    ``quantile`` x the map's median; ``speculate`` events
                    are corroborating evidence
store_fetch         worker-side ref resolution (``worker.resolve_refs``
                    span durations)
locality_miss       the subset of store traffic that crossed the wire
                    (``store``/``fetch`` events with ``wire=True``) —
                    payload fetched where it did NOT already live
backpressure        submit-side waits on the in-flight cap
                    (``pool``/``backpressure`` events, ``wait_s``)
transport_stall     ingress stalls/parks observed by either I/O engine
                    (``transport``/``stall`` + ``park`` events)
==================  =====================================================

The verdict is a **ranked budget**: seconds attributed per category,
plus ``primary`` — the top category with nonzero blame (or
``"compute"`` when nothing above explains the wall clock, i.e. the map
was simply busy). All inputs are artifacts (the Chrome trace written by
``Pool.trace_dump`` / ``bench.py --cluster`` and the flight-event JSON
from ``Pool.flight_dump``), so the CLI runs offline against any
recorded run.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

#: Blame categories, ranked in the verdict (compute is context, not
#: blame — it appears in the budget but never as primary unless nothing
#: else has weight).
CATEGORIES = ("straggler", "transfer", "store_fetch", "locality_miss",
              "backpressure", "transport_stall", "fanout")


def spans_from_chrome(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Invert export.chrome_trace: complete (``ph == "X"``) events back
    into span dicts (ts/dur in seconds, args flattened)."""
    pid_to_host = {
        e.get("pid"): e.get("args", {}).get("name")
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    spans = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        sp = dict(e.get("args") or {})
        sp["name"] = e.get("name", "span")
        sp["ts"] = float(e.get("ts", 0.0)) / 1e6
        sp["dur"] = float(e.get("dur", 0.0)) / 1e6
        sp.setdefault("host", pid_to_host.get(e.get("pid"), "host"))
        sp.setdefault("pid", e.get("tid", 0))
        spans.append(sp)
    return spans


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Spans from a file: a Chrome trace-event JSON object (trace_dump
    output) or a plain JSON list of span dicts."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict) and "traceEvents" in doc:
        return spans_from_chrome(doc)
    if isinstance(doc, list):
        return doc
    raise ValueError(f"{path!r} holds neither a Chrome trace nor a "
                     "span list")


def load_events(path: str) -> List[Dict[str, Any]]:
    """Flight events from a file: a JSON list, or the ``Pool.flight_dump``
    envelope ``{"events": [...]}``. Events are merge-ordered on
    ``(wall, monotonic)`` — artifacts concatenated from several
    processes interleave correctly (flightrec.order_events)."""
    from fiber_tpu.telemetry.flightrec import order_events

    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, dict):
        doc = doc.get("events", [])
    if not isinstance(doc, list):
        raise ValueError(f"{path!r} holds no flight-event list")
    return order_events(doc)


def load_logs(path: str, last: int = 12) -> List[str]:
    """The log-ring tail a ``Pool.flight_dump`` artifact carries (the
    logs pillar beside the flight events): the last ``last`` lines, or
    ``[]`` for artifacts written before the ring existed / raw event
    lists."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    logs = doc.get("logs")
    if not isinstance(logs, list):
        return []
    return [str(line) for line in logs[-max(0, int(last)):]]


def _dominant_trace(spans: Sequence[Dict[str, Any]]) -> Optional[str]:
    counts: Dict[str, int] = {}
    for sp in spans:
        tid = sp.get("trace")
        if tid:
            counts[tid] = counts.get(tid, 0) + 1
    return max(counts, key=counts.get) if counts else None


def _median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    return ordered[len(ordered) // 2] if ordered else 0.0


def explain_trace(spans: Sequence[Dict[str, Any]],
                  events: Iterable[Dict[str, Any]] = (),
                  trace_id: Optional[str] = None,
                  quantile: float = 2.0,
                  profile: Optional[Dict[str, int]] = None
                  ) -> Dict[str, Any]:
    """Classify one trace's time. ``trace_id`` defaults to the trace
    with the most spans (the artifact usually holds exactly the traced
    map plus stragglers of earlier ones)."""
    trace_id = trace_id or _dominant_trace(spans)
    mine = [sp for sp in spans if sp.get("trace") == trace_id]
    if not mine:
        raise ValueError(f"no spans for trace {trace_id!r}")
    t0 = min(float(sp.get("ts", 0.0)) for sp in mine)
    t1 = max(float(sp.get("ts", 0.0)) + float(sp.get("dur", 0.0))
             for sp in mine)
    seqs = {sp["seq"] for sp in mine if sp.get("seq") is not None}

    def in_scope(ev: Dict[str, Any]) -> bool:
        seq = ev.get("seq")
        if seq is not None and seqs:
            return seq in seqs
        # seq-less events (transport, store wire traffic) join by time:
        # the trace window plus a little slack for clock skew.
        return t0 - 0.5 <= float(ev.get("ts", 0.0)) <= t1 + 0.5

    scoped = [ev for ev in events if in_scope(ev)]

    budget: Dict[str, float] = {c: 0.0 for c in CATEGORIES}
    evidence: Dict[str, Any] = {"trace": trace_id,
                                "seqs": sorted(seqs),
                                "events_considered": len(scoped)}

    execute = [sp for sp in mine if sp.get("name") == "worker.execute"]
    budget["compute"] = sum(float(sp.get("dur", 0.0)) for sp in execute)
    budget["store_fetch"] = sum(
        float(sp.get("dur", 0.0)) for sp in mine
        if sp.get("name") == "worker.resolve_refs")
    budget["serialize"] = sum(
        float(sp.get("dur", 0.0)) for sp in mine
        if sp.get("name") == "pool.serialize")

    # Straggler: per-chunk service times (handout -> result) from the
    # scheduler's chunk_done events; execute spans are the fallback for
    # artifacts recorded without the flight recorder. Blame is the
    # EXCESS above quantile x median — a uniformly slow map is compute,
    # not a straggler.
    durs = [float(ev.get("dur", 0.0)) for ev in scoped
            if ev.get("plane") == "sched" and ev.get("kind") == "chunk_done"]
    dur_source = "sched.chunk_done"
    if not durs:
        durs = [float(sp.get("dur", 0.0)) for sp in execute]
        dur_source = "worker.execute"
    median = _median(durs)
    threshold = max(quantile, 1.0) * median
    excess = [d - threshold for d in durs if d > threshold]
    budget["straggler"] = sum(excess)
    speculated = sum(1 for ev in scoped
                     if ev.get("plane") == "sched"
                     and ev.get("kind") == "speculate")
    evidence["straggler"] = {
        "chunks": len(durs), "median_s": round(median, 6),
        "outliers": len(excess), "speculations": speculated,
        "source": dur_source,
    }

    # Transfer: seconds spent crossing the host->device boundary
    # (device telemetry plane — ``device``/``transfer`` flight events;
    # ``device.transfer`` spans are the fallback for artifacts recorded
    # without the flight recorder). The transferred bytes are the
    # evidence: a verdict naming transfer should say HOW MUCH crossed.
    xfer_events = [ev for ev in scoped
                   if ev.get("plane") == "device"
                   and ev.get("kind") == "transfer"]
    xfer_source = "device.transfer events"
    by_site: Dict[str, Dict[str, float]] = {}

    def _site_add(site: str, secs: float, nbytes: int) -> None:
        slot = by_site.setdefault(site, {"transfers": 0, "bytes": 0,
                                         "s": 0.0})
        slot["transfers"] += 1
        slot["bytes"] += nbytes
        slot["s"] += secs

    if xfer_events:
        budget["transfer"] = sum(float(ev.get("s", 0.0))
                                 for ev in xfer_events)
        xfer_bytes = sum(int(ev.get("bytes", 0)) for ev in xfer_events)
        xfer_count = len(xfer_events)
        for ev in xfer_events:
            _site_add(str(ev.get("site", "?")), float(ev.get("s", 0.0)),
                      int(ev.get("bytes", 0)))
    else:
        xfer_spans = [sp for sp in mine
                      if sp.get("name") == "device.transfer"]
        budget["transfer"] = sum(float(sp.get("dur", 0.0))
                                 for sp in xfer_spans)
        xfer_bytes = sum(int(sp.get("bytes", 0)) for sp in xfer_spans)
        xfer_count = len(xfer_spans)
        xfer_source = "device.transfer spans"
        for sp in xfer_spans:
            _site_add(str(sp.get("site", "?")),
                      float(sp.get("dur", 0.0)), int(sp.get("bytes", 0)))
    # The ICI-vs-wire blame split (docs/objectstore.md "Device tier"):
    # `ici` transfers are mesh fan-out (device-tier placement) — bytes
    # that did NOT cross sockets; wire bytes come from the store's
    # wire-fetch events below. A verdict can now say "this map moved
    # 64MB, 60MB of it over ICI".
    evidence["transfer"] = {
        "transfers": xfer_count, "bytes": xfer_bytes,
        "source": xfer_source,
        "by_site": {site: {"transfers": int(v["transfers"]),
                           "bytes": int(v["bytes"]),
                           "s": round(v["s"], 6)}
                    for site, v in sorted(by_site.items())},
        "ici_bytes": int(by_site.get("ici", {}).get("bytes", 0)),
    }

    wire_fetches = [ev for ev in scoped
                    if ev.get("plane") == "store"
                    and ev.get("kind") == "fetch" and ev.get("wire")]
    wire_bytes = sum(int(ev.get("bytes", 0)) for ev in wire_fetches)
    budget["locality_miss"] = sum(float(ev.get("s", 0.0))
                                  for ev in wire_fetches)
    evidence["locality_miss"] = {
        "wire_fetches": len(wire_fetches),
        "bytes": wire_bytes,
    }
    evidence["transfer"]["wire_bytes"] = wire_bytes

    budget["backpressure"] = sum(
        float(ev.get("wait_s", 0.0)) for ev in scoped
        if ev.get("plane") == "pool" and ev.get("kind") == "backpressure")
    budget["transport_stall"] = sum(
        float(ev.get("stall_s", 0.0)) for ev in scoped
        if ev.get("plane") == "transport"
        and ev.get("kind") in ("stall", "park"))
    # Hierarchical dispatch: seconds a per-host sub-master spent
    # blocked feeding its local sub-workers (sched/hier.py records a
    # fanout_stall per blocked feed) — the range handout outran the
    # host's compute, so the fan-out itself is the bottleneck.
    fanout_stalls = [ev for ev in scoped
                     if ev.get("plane") == "hier"
                     and ev.get("kind") == "fanout_stall"]
    budget["fanout"] = sum(float(ev.get("wait_s", 0.0))
                           for ev in fanout_stalls)
    evidence["fanout"] = {"stalls": len(fanout_stalls)}

    ranked = sorted(((c, budget[c]) for c in CATEGORIES),
                    key=lambda kv: kv[1], reverse=True)
    primary = ranked[0][0] if ranked[0][1] > 0.0 else "compute"
    if profile:
        # A sampling profile (folded stacks — telemetry/profiler.py)
        # makes a compute verdict actionable: the evidence names WHICH
        # Python frames burned the samples instead of stopping at
        # "compute".
        from fiber_tpu.telemetry.profiler import top_frames

        evidence["compute_frames"] = [
            {"frame": frame, "samples": count}
            for frame, count in top_frames(profile, 5)
        ]
    return {
        "trace": trace_id,
        "wall_s": round(t1 - t0, 6),
        "spans": len(mine),
        "budget": {k: round(v, 6) for k, v in budget.items()},
        "ranked": [(c, round(s, 6)) for c, s in ranked],
        "primary": primary,
        "evidence": evidence,
    }


def policy_chains(events: Iterable[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """Join anomaly -> action -> outcome by event id (docs/
    observability.md "Autonomous operations"): every ``monitor``-plane
    anomaly event is a potential cause; ``policy``-plane events carry
    ``cause_id`` pointing back at it. Returns one chain per anomaly
    that drew ANY policy activity (actions, suppressions, reverts,
    outcomes), in event order."""
    events = list(events)
    anomalies: Dict[str, Dict[str, Any]] = {
        e["id"]: e for e in events
        if e.get("plane") == "monitor" and e.get("kind") != "clear"
        and e.get("id")}
    chains: Dict[str, Dict[str, Any]] = {}
    order: List[str] = []
    for e in events:
        if e.get("plane") != "policy":
            continue
        cid = e.get("cause_id")
        if not cid:
            continue
        chain = chains.get(cid)
        if chain is None:
            chain = chains[cid] = {
                "cause_id": cid,
                "anomaly": anomalies.get(cid),
                "actions": [], "outcomes": [], "notes": [],
            }
            order.append(cid)
        kind = e.get("kind")
        if kind == "outcome":
            chain["outcomes"].append(e)
        elif kind in ("suppressed", "revert"):
            chain["notes"].append(e)
        else:
            chain["actions"].append(e)
    return [chains[cid] for cid in order]


def render_chains(chains: Sequence[Dict[str, Any]]) -> str:
    """Narrate the anomaly -> action -> outcome chains (the CLI's
    ``explain --flight`` tail and ``fiber-tpu policies --events``)."""
    if not chains:
        return "autonomous operations: no policy activity recorded"
    lines = [f"autonomous operations: {len(chains)} anomaly chain(s)"]
    for chain in chains:
        anom = chain.get("anomaly")
        if anom is not None:
            rule = anom.get("kind", "?")
            detail = anom.get("detail", "")
            lines.append(f"anomaly {rule} [{chain['cause_id']}]: {detail}")
        else:
            lines.append(f"anomaly [{chain['cause_id']}] "
                         "(event outside this artifact)")
        for act in chain["actions"]:
            mode = ("dry-run" if act.get("dry_run")
                    else ("applied" if act.get("applied") else "no-op"))
            lines.append(f"  -> action {act.get('kind')} ({mode}): "
                         f"{act.get('detail', '')}")
        for note in chain["notes"]:
            lines.append(f"  .. {note.get('kind')}: "
                         f"{note.get('reason') or note.get('detail', '')}")
        for out in chain["outcomes"]:
            lines.append(f"  => outcome {out.get('outcome')}: "
                         f"{out.get('detail', '')}")
        if chain["actions"] and not chain["outcomes"]:
            lines.append("  => outcome pending (verification had not "
                         "run when the artifact was written)")
    return "\n".join(lines)


def render(verdict: Dict[str, Any]) -> str:
    """Human-readable ranked budget (the CLI's output)."""
    lines = [
        f"trace {verdict['trace']}  wall {verdict['wall_s']:.3f}s  "
        f"spans {verdict['spans']}",
        f"primary: {verdict['primary']}",
        "ranked budget (blame seconds):",
    ]
    for cat, secs in verdict["ranked"]:
        lines.append(f"  {cat:<16} {secs:.4f}")
    budget = verdict["budget"]
    lines.append(f"  {'compute':<16} {budget.get('compute', 0.0):.4f}"
                 "  (context, not blame)")
    if "serialize" in budget:
        lines.append(f"  {'serialize':<16} "
                     f"{budget['serialize']:.4f}  (context)")
    ev = verdict.get("evidence", {}).get("straggler")
    if ev:
        lines.append(
            f"straggler evidence: {ev['outliers']}/{ev['chunks']} outlier "
            f"chunk(s) vs median {ev['median_s']:.4f}s, "
            f"{ev['speculations']} speculation(s) [{ev['source']}]")
    ev = verdict.get("evidence", {}).get("transfer")
    if ev and verdict.get("primary") == "transfer":
        lines.append(
            f"transfer evidence: {ev['transfers']} host->device "
            f"transfer(s), {ev['bytes']} bytes [{ev['source']}]")
    if ev and (ev.get("ici_bytes") or ev.get("wire_bytes")):
        # The data-plane split: bytes that rode the mesh vs bytes that
        # crossed sockets (docs/objectstore.md "Device tier").
        lines.append(
            f"transfer split: ici {ev.get('ici_bytes', 0)}B over the "
            f"mesh, wire {ev.get('wire_bytes', 0)}B over sockets")
    frames = verdict.get("evidence", {}).get("compute_frames")
    if frames and verdict.get("primary") == "compute":
        lines.append("compute is the verdict — top sampled frames:")
        for entry in frames:
            lines.append(
                f"  {entry['samples']:>6}  {entry['frame']}")
    return "\n".join(lines)

"""Continuous metrics time-series: the per-process monitor sampler.

The registry (:mod:`.metrics`) holds *current* values; ``fiber-tpu
metrics`` renders them point-in-time. What an operator watching a
long-lived cluster actually needs is the **derivative**: tasks/s right
now, bytes/s over the last interval, whether the queue is growing.
This module is that layer — a sampler thread snapshots a small, fixed
set of load-bearing instruments every ``monitor_interval_s`` seconds
into bounded rings of ``(wall, monotonic, value)`` points and derives
rates from consecutive points. The anomaly watchdog
(:mod:`.monitor`) rides the same tick, ``fiber-tpu top`` renders the
per-host snapshots, and ``fiber-tpu metrics --watch`` reuses the rate
math between its polls.

Design constraints, mirrored from the rest of the plane:

* **Near-zero when off** — ``monitor_enabled=False`` means no thread,
  no rings, no per-tick work; :func:`MonitorSampler.configure` is the
  only cost (one call per ``telemetry.refresh``).
* **Bounded** — every series is a ring of ``monitor_history`` points;
  a week-long master holds the same memory as a minute-long one.
* **Dual clocks** — each point carries wall time (comparable across
  hosts, subject to NTP) and the process monotonic clock (immune to
  wall steps, meaningless across processes). Rates are derived on the
  monotonic axis; cross-host merges order on the wall axis with the
  monotonic value as a same-process tiebreak (see flightrec
  ``order_events``).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Instruments the sampler tracks: series name -> registry metric. The
#: set is deliberately small and fixed — the monitor answers "is the
#: cluster healthy", not "what is every counter doing" (that is the
#: registry snapshot's job).
TRACKED_COUNTERS = {
    "tasks_completed": "pool_tasks_completed",
    "tasks_submitted": "pool_tasks_submitted",
    "bytes_tx": "transport_bytes_tx",
    "bytes_rx": "transport_bytes_rx",
}
TRACKED_GAUGES = {
    "queue_depth": "pool_queue_depth",
    "inflight": "pool_inflight_tasks",
    "tx_queue_bytes": "transport_evloop_tx_queue_bytes",
    # Device telemetry plane (docs/observability.md "Device telemetry"):
    # gauges only ever set when a device runtime reports them (CPU and
    # agent processes leave them unset -> 0 in the series; the honest
    # null lives in device_snapshot / the hbm_fill rule's probe).
    "hbm_bytes_in_use": "device_hbm_bytes_in_use",
    "live_array_bytes": "device_live_array_bytes",
}
#: Counter series whose per-second rate rides the sample dict (the
#: ``fiber-tpu top`` columns).
RATE_SERIES = {
    "tasks_completed": "tasks_per_s",
    "bytes_tx": "bytes_tx_per_s",
    "bytes_rx": "bytes_rx_per_s",
}


class SeriesRing:
    """Bounded FIFO of ``(wall, mono, value)`` points (oldest fall out
    past capacity). Lock-free appends are fine — only the sampler
    thread writes; readers copy under the sampler's lock."""

    __slots__ = ("_points", "capacity")

    def __init__(self, capacity: int = 600) -> None:
        self.capacity = max(2, int(capacity))
        self._points: List[Tuple[float, float, float]] = []

    def add(self, wall: float, mono: float, value: float) -> None:
        self._points.append((wall, mono, float(value)))
        if len(self._points) > self.capacity:
            del self._points[: len(self._points) - self.capacity]

    def points(self) -> List[Tuple[float, float, float]]:
        return list(self._points)

    def last(self) -> Optional[Tuple[float, float, float]]:
        return self._points[-1] if self._points else None

    def rate(self) -> float:
        """Per-second delta between the two newest points (counter
        series; negative deltas — a registry reset — clamp to 0)."""
        if len(self._points) < 2:
            return 0.0
        (_, m0, v0), (_, m1, v1) = self._points[-2], self._points[-1]
        dt = m1 - m0
        if dt <= 0:
            return 0.0
        return max(0.0, (v1 - v0) / dt)

    def resize(self, capacity: int) -> None:
        self.capacity = max(2, int(capacity))
        if len(self._points) > self.capacity:
            del self._points[: len(self._points) - self.capacity]

    def __len__(self) -> int:
        return len(self._points)


def _metric_total(registry, name: str) -> Optional[float]:
    """Sum of every label set of one scalar metric, or None when the
    metric was never registered in this process."""
    inst = registry.get(name)
    if inst is None:
        return None
    with registry._lock:
        try:
            return float(sum(inst._series.values()))
        except TypeError:  # histogram series are lists; not tracked
            return None


class MonitorSampler:
    """Samples the registry into rings on a daemon thread and fans each
    sample out to observers (the anomaly watchdog). Probes run first so
    pull-style gauges (pool queue depth) are fresh at sample time."""

    def __init__(self, capacity: int = 600, interval: float = 1.0) -> None:
        self.enabled = False
        self._interval = float(interval)
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._series: Dict[str, SeriesRing] = {}
        self._probes: List[Callable[[], None]] = []
        self._observers: List[Callable[[Dict[str, Any]], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self.samples = 0          # lifetime ticks taken
        self._last_sample: Dict[str, Any] = {}

    # -- wiring --------------------------------------------------------
    def configure(self, enabled: bool, interval: float,
                  capacity: int) -> None:
        """Follow the config knobs (called from telemetry.refresh).
        Disabling stops the thread; the rings are kept so a bounce
        doesn't lose history. An interval change restarts the thread —
        the old one may be mid-wait on the old period."""
        interval = max(0.02, float(interval))
        capacity = int(capacity)
        with self._lock:
            if capacity != self._capacity:
                self._capacity = capacity
                for ring in self._series.values():
                    ring.resize(capacity)
        restart = bool(enabled) and (not self.enabled
                                     or interval != self._interval)
        self._interval = interval
        if not restart and bool(enabled) == self.enabled:
            return
        # Stop whatever thread is running (it checks `enabled` and its
        # private wake event after every wait).
        self.enabled = False
        self._wake.set()
        self._thread = None
        if bool(enabled):
            self.enabled = True
            self._wake = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(self._wake, interval),
                name="fiber-monitor-sampler", daemon=True)
            self._thread.start()

    def add_probe(self, probe: Callable[[], None]) -> None:
        """Register a callable run before every sample (pools push
        their queue-depth/inflight gauges here so the sampler never
        reads a stale value). Bound methods are held WEAKLY — the
        sampler must never pin an abandoned Pool alive past its
        ``__del__`` safety net."""
        ref = (weakref.WeakMethod(probe)
               if hasattr(probe, "__self__") else
               (lambda p=probe: p))
        with self._lock:
            if probe not in [r() for r in self._probes]:
                self._probes.append(ref)

    def remove_probe(self, probe: Callable[[], None]) -> None:
        with self._lock:
            self._probes = [r for r in self._probes
                            if r() is not None and r() != probe]

    def add_observer(self,
                     observer: Callable[[Dict[str, Any]], None]) -> None:
        with self._lock:
            if observer not in self._observers:
                self._observers.append(observer)

    # -- sampling ------------------------------------------------------
    def _ring(self, name: str) -> SeriesRing:
        ring = self._series.get(name)
        if ring is None:
            ring = self._series[name] = SeriesRing(self._capacity)
        return ring

    def sample_once(self) -> Dict[str, Any]:
        """Take one sample NOW (the thread's tick; also callable from
        tests and the agent's monitor op for an extra-fresh point)."""
        from fiber_tpu import telemetry

        with self._lock:
            self._probes = [r for r in self._probes if r() is not None]
            probes = [r() for r in self._probes]
            observers = list(self._observers)
        for probe in probes:
            if probe is None:
                continue
            try:
                probe()
            except Exception:  # noqa: BLE001 - a dying pool's probe
                pass
        wall = time.time()
        mono = time.monotonic()
        registry = telemetry.REGISTRY
        sample: Dict[str, Any] = {"wall": wall, "mono": mono}
        with self._lock:
            for name, metric in TRACKED_COUNTERS.items():
                total = _metric_total(registry, metric)
                if total is None:
                    continue
                ring = self._ring(name)
                ring.add(wall, mono, total)
                sample[name] = total
                rate_key = RATE_SERIES.get(name)
                if rate_key:
                    sample[rate_key] = round(ring.rate(), 3)
            for name, metric in TRACKED_GAUGES.items():
                total = _metric_total(registry, metric)
                if total is None:
                    total = 0.0
                self._ring(name).add(wall, mono, total)
                sample[name] = total
            # Heartbeat freshness from every live failure detector in
            # this process (health.py): the oldest peer silence.
            try:
                from fiber_tpu import health

                ages = health.heartbeat_ages()
                sample["heartbeat_age_s"] = (
                    round(max(ages.values()), 3) if ages else 0.0)
                sample["peers"] = len(ages)
            except Exception:  # noqa: BLE001 - sampling must not fail
                sample["heartbeat_age_s"] = 0.0
                sample["peers"] = 0
            self._ring("heartbeat_age_s").add(
                wall, mono, sample["heartbeat_age_s"])
            self.samples += 1
            self._last_sample = sample
        for observer in observers:
            try:
                observer(sample)
            except Exception:  # noqa: BLE001
                logger.exception("monitor: observer failed")
        return sample

    def _loop(self, wake: threading.Event, interval: float) -> None:
        # The wake event and interval are THIS thread's own (passed at
        # start): a configure() that replaces them cannot leave a
        # superseded thread waiting on the new generation's event.
        while not wake.wait(interval):
            if not self.enabled or wake is not self._wake:
                return
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - keep sampling
                logger.exception("monitor: sample failed")

    # -- read side -----------------------------------------------------
    def last_sample(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last_sample)

    def snapshot(self, last: int = 0) -> Dict[str, Any]:
        """Picklable dump: rings (optionally only the newest ``last``
        points), the latest derived sample, and sampler state — the
        payload of the host agent's ``monitor_snapshot`` op."""
        with self._lock:
            series = {}
            for name, ring in self._series.items():
                pts = ring.points()
                series[name] = pts[-last:] if last > 0 else pts
            return {
                "enabled": self.enabled,
                "interval_s": self._interval,
                "samples": self.samples,
                "series": series,
                "last": dict(self._last_sample),
            }

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._last_sample = {}
            self.samples = 0


#: Process-wide sampler (knobs follow ``monitor_*`` via
#: telemetry.refresh()).
TIMESERIES = MonitorSampler()


# ---------------------------------------------------------------------------
# Shared rate math (``fiber-tpu metrics --watch`` and ``top``)
# ---------------------------------------------------------------------------


def snapshot_deltas(prev: Dict[str, dict], cur: Dict[str, dict],
                    dt: float) -> Dict[str, Dict[str, Any]]:
    """Per-series deltas/rates between two ``registry.snapshot()``
    dicts taken ``dt`` seconds apart. Counters become
    ``{"delta", "rate"}``; gauges ``{"value", "delta"}``; histograms
    ``{"delta", "rate"}`` over their observation count. Series with no
    change are omitted — the --watch output shows what *moved*."""
    out: Dict[str, Dict[str, Any]] = {}
    if dt <= 0:
        return out
    for name, entry in cur.items():
        kind = entry.get("type")
        prev_series = (prev.get(name) or {}).get("series", {})
        for labels, value in entry.get("series", {}).items():
            before = prev_series.get(labels)
            if kind == "histogram":
                count = value[-1]
                prev_count = before[-1] if before else 0
                delta = count - prev_count
                if delta == 0:
                    continue
                key = f"{name}{{{labels}}}" if labels else name
                out[key] = {"kind": kind, "delta": delta,
                            "rate": round(delta / dt, 3)}
                continue
            before_v = float(before) if before is not None else 0.0
            delta = float(value) - before_v
            key = f"{name}{{{labels}}}" if labels else name
            if kind == "counter":
                if delta == 0:
                    continue
                out[key] = {"kind": kind, "delta": round(delta, 6),
                            "rate": round(max(0.0, delta) / dt, 3)}
            else:  # gauge / untyped: show level + movement
                if delta == 0:
                    continue
                out[key] = {"kind": "gauge", "value": float(value),
                            "delta": round(delta, 6)}
    return out

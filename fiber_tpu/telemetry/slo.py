"""Per-tenant SLO plane for the serve daemon (docs/observability.md
"SLOs and the archive").

The serve tier already measures everything an objective needs — each
job's ``submitted_at``/``started_at``/``finished_at`` stamps, terminal
state and task count — but nothing turns those into the question a
tenant actually asks: *is the service keeping its latency promise, and
if not, how fast is it spending the error budget?* This plane is that
turn:

* **SLIs**, per tenant: queue-wait and submit→done latency as
  fixed-bucket histograms (percentiles without unbounded storage),
  task throughput, and the error/preemption rate.
* **SLOs**: declarative targets from the ``serve_slo_*`` knobs — a
  latency target bounds the ``serve_slo_p`` percentile; the error
  objective's budget is ``serve_slo_error_pct``.
* **Burn-rate evaluation**, multi-window: an objective's burn rate is
  its bad-event fraction over a window divided by the budget fraction
  (the SRE-workbook construction). ``slo_burn`` raises only when BOTH
  the fast window (is it happening *now*?) and the slow window (is it
  *significant*?) burn past ``serve_slo_burn`` — a single slow job
  cannot page, and a long-finished incident cannot keep paging.

``slo_burn`` rides :meth:`AnomalyWatchdog.external_breach`, so it is
edge-triggered like every sampler rule and the policy plane maps it to
remediations (warm-pool boost, offender throttle — telemetry/policy.py)
with the same cause_id-linked anomaly → action → outcome chain.

Durability: every observation is appended to the archive
(``slo_obs`` records) the moment it is taken, and :meth:`replay`
rebuilds windows + histograms from the archive tail — so a SIGKILLed
daemon restarts with its burn state intact.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Fixed latency histogram buckets, seconds (upper bounds; the last
#: bucket is +inf). Chosen to resolve both interactive (ms) and batch
#: (minutes) serve jobs without per-tenant tuning.
BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0, 300.0)

#: Job states that spend error budget (client cancel is the tenant's
#: own choice, not a service failure).
BAD_STATES = ("failed", "preempted")

#: The aggregate pseudo-tenant every observation also lands under.
ALL = "*"


class _Hist:
    """One fixed-bucket histogram: counts per bucket + overflow."""

    __slots__ = ("counts", "n", "total")

    def __init__(self) -> None:
        self.counts = [0] * (len(BUCKETS) + 1)
        self.n = 0
        self.total = 0.0

    def add(self, value: float) -> None:
        i = 0
        for i, bound in enumerate(BUCKETS):
            if value <= bound:
                break
        else:
            i = len(BUCKETS)
        self.counts[i] += 1
        self.n += 1
        self.total += value

    def quantile(self, q: float) -> Optional[float]:
        """Upper bound of the bucket holding the q-quantile (None when
        empty; the overflow bucket reports the last finite bound — a
        floor, honest for "p95 exceeds X")."""
        if self.n <= 0:
            return None
        rank = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return BUCKETS[min(i, len(BUCKETS) - 1)]
        return BUCKETS[-1]

    def snapshot(self) -> Dict[str, Any]:
        return {"n": self.n, "mean": (self.total / self.n
                                      if self.n else None),
                "p50": self.quantile(0.50), "p90": self.quantile(0.90),
                "p95": self.quantile(0.95), "p99": self.quantile(0.99)}


class _Tenant:
    """One tenant's SLI accumulators (lifetime histograms + counters;
    the burn windows live in the tracker's shared observation ring)."""

    __slots__ = ("queue", "latency", "states", "tasks")

    def __init__(self) -> None:
        self.queue = _Hist()
        self.latency = _Hist()
        self.states: Dict[str, int] = {}
        self.tasks = 0


class SloTracker:
    """SLI accumulation + multi-window burn evaluation; owned by the
    serve daemon's tick thread, read by RPC threads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # targets (refreshed from config via configure())
        self.latency_s = 0.0
        self.queue_s = 0.0
        self.p = 0.95
        self.error_pct = 0.01
        self.window_s = 3600.0
        self.fast_window_s = 300.0
        self.burn_threshold = 2.0
        # state
        self._tenants: Dict[str, _Tenant] = {}
        self._obs: List[Dict[str, Any]] = []  # ring over window_s
        self._seen: set = set()               # observed job ids
        self._breached = False
        self.observations = 0

    def configure(self, cfg) -> None:
        """Re-read the SLO knobs (telemetry.refresh)."""
        self.latency_s = max(0.0, float(cfg.serve_slo_latency_s))
        self.queue_s = max(0.0, float(cfg.serve_slo_queue_s))
        self.p = min(0.999, max(0.5, float(cfg.serve_slo_p)))
        self.error_pct = min(1.0, max(0.0001,
                                      float(cfg.serve_slo_error_pct)))
        self.window_s = max(1.0, float(cfg.serve_slo_window_s))
        self.fast_window_s = min(
            self.window_s, max(0.5, float(cfg.serve_slo_fast_window_s)))
        self.burn_threshold = max(0.1, float(cfg.serve_slo_burn))

    # -- observation ----------------------------------------------------
    def observe(self, tenant: str, state: str,
                queue_wait: Optional[float] = None,
                latency: Optional[float] = None, tasks: int = 0,
                job_id: Optional[str] = None, ts: Optional[float] = None,
                archive: bool = True) -> None:
        """Record one finished job. Called by the daemon tick for every
        newly terminal job (and by replay with ``archive=False``)."""
        ts = time.time() if ts is None else float(ts)
        obs = {"tenant": tenant, "state": state,
               "queue_wait": queue_wait, "latency": latency,
               "tasks": int(tasks), "job_id": job_id, "ts": ts}
        with self._lock:
            if job_id is not None:
                if job_id in self._seen:
                    return
                self._seen.add(job_id)
            for name in (tenant, ALL):
                t = self._tenants.get(name)
                if t is None:
                    t = self._tenants[name] = _Tenant()
                if queue_wait is not None:
                    t.queue.add(float(queue_wait))
                if latency is not None:
                    t.latency.add(float(latency))
                t.states[state] = t.states.get(state, 0) + 1
                t.tasks += int(tasks)
            self._obs.append(obs)
            self.observations += 1
            self._trim_locked(ts)
        if archive:
            from fiber_tpu.telemetry.archive import ARCHIVE

            ARCHIVE.append("slo_obs", dict(obs))

    def observe_jobs(self, views: List[Dict[str, Any]]) -> int:
        """Fold a batch of terminal job views (JobRunner dicts) into
        the SLIs; returns how many were new."""
        n = 0
        for view in views:
            job_id = view.get("job_id")
            with self._lock:
                if job_id in self._seen:
                    continue
            sub = view.get("submitted_at")
            fin = view.get("finished_at")
            start = view.get("started_at") or fin
            latency = (float(fin) - float(sub)
                       if sub is not None and fin is not None else None)
            queue_wait = (float(start) - float(sub)
                          if sub is not None and start is not None
                          else None)
            self.observe(str(view.get("tenant") or "default"),
                         str(view.get("state") or ""),
                         queue_wait=queue_wait, latency=latency,
                         tasks=int(view.get("n_items") or 0),
                         job_id=job_id, ts=fin)
            n += 1
        return n

    def _trim_locked(self, now: float) -> None:
        horizon = now - self.window_s
        if self._obs and self._obs[0]["ts"] < horizon:
            self._obs = [o for o in self._obs if o["ts"] >= horizon]

    # -- burn evaluation ------------------------------------------------
    def _objectives(self) -> List[Tuple[str, float]]:
        """(name, budget fraction) of every armed objective."""
        out = [("error", self.error_pct)]
        if self.latency_s > 0:
            out.append(("latency", 1.0 - self.p))
        if self.queue_s > 0:
            out.append(("queue", 1.0 - self.p))
        return out

    def _bad(self, obs: Dict[str, Any], objective: str) -> bool:
        if objective == "error":
            return obs["state"] in BAD_STATES
        if objective == "latency":
            return (obs["latency"] is not None
                    and obs["latency"] > self.latency_s)
        return (obs["queue_wait"] is not None
                and obs["queue_wait"] > self.queue_s)

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[str, Dict[str, Any]]:
        """Per-tenant burn state: for each armed objective, the fast-
        and slow-window burn rates (bad fraction / budget fraction)."""
        now = time.time() if now is None else now
        with self._lock:
            obs = list(self._obs)
        slow = [o for o in obs if o["ts"] >= now - self.window_s]
        fast = [o for o in slow if o["ts"] >= now - self.fast_window_s]
        tenants = sorted({o["tenant"] for o in slow} - {ALL}) + [ALL]
        out: Dict[str, Dict[str, Any]] = {}
        for tenant in tenants:
            t_slow = (slow if tenant == ALL
                      else [o for o in slow if o["tenant"] == tenant])
            t_fast = (fast if tenant == ALL
                      else [o for o in fast if o["tenant"] == tenant])
            objs = {}
            for name, budget in self._objectives():
                objs[name] = {
                    "budget": budget,
                    "burn_fast": self._burn(t_fast, name, budget),
                    "burn_slow": self._burn(t_slow, name, budget),
                }
            out[tenant] = objs
        return out

    def _burn(self, obs: List[Dict[str, Any]], objective: str,
              budget: float) -> Optional[float]:
        if not obs:
            return None
        bad = sum(1 for o in obs if self._bad(o, objective))
        return (bad / len(obs)) / budget

    def evaluate(self, now: Optional[float] = None) -> Optional[Dict]:
        """One burn-rate sweep (daemon tick): raise / refresh / clear
        the edge-triggered ``slo_burn`` watchdog rule. Returns the
        worst offender (or None). The refresh path keeps the anomaly
        record's ``burn`` attr current, so the policy engine's outcome
        verification sees real movement."""
        from fiber_tpu.telemetry.monitor import WATCHDOG

        now = time.time() if now is None else now
        worst: Optional[Dict[str, Any]] = None
        for tenant, objs in self.burn_rates(now).items():
            if tenant == ALL:
                continue  # the offender is always a real tenant
            for name, b in objs.items():
                bf, bs = b["burn_fast"], b["burn_slow"]
                if bf is None or bs is None:
                    continue
                if bf < self.burn_threshold or bs < self.burn_threshold:
                    continue
                score = min(bf, bs)
                if worst is None or score > worst["burn"]:
                    worst = {"tenant": tenant, "sli": name,
                             "burn": round(score, 2),
                             "burn_fast": round(bf, 2),
                             "burn_slow": round(bs, 2)}
        if worst is not None:
            self._breached = True
            WATCHDOG.external_breach(
                "slo_burn",
                (f"tenant {worst['tenant']!r} {worst['sli']} SLO "
                 f"burning {worst['burn']:g}x its budget "
                 f"(fast {worst['burn_fast']:g}x / "
                 f"slow {worst['burn_slow']:g}x "
                 f">= {self.burn_threshold:g}x)"),
                **worst)
        elif self._breached:
            self._breached = False
            WATCHDOG.external_clear("slo_burn")
        return worst

    # -- restart replay -------------------------------------------------
    def replay(self, now: Optional[float] = None) -> int:
        """Rebuild windows/histograms/seen-set from the archive tail
        (daemon startup, after a crash or SIGKILL). Returns how many
        observations were restored."""
        from fiber_tpu.telemetry.archive import ARCHIVE

        now = time.time() if now is None else now
        restored = 0
        for rec in ARCHIVE.query("slo_obs", since=now - self.window_s):
            try:
                self.observe(str(rec.get("tenant") or "default"),
                             str(rec.get("state") or ""),
                             queue_wait=rec.get("queue_wait"),
                             latency=rec.get("latency"),
                             tasks=int(rec.get("tasks") or 0),
                             job_id=rec.get("job_id"),
                             ts=rec.get("ts"), archive=False)
                restored += 1
            except (TypeError, ValueError):
                continue
        if restored:
            logger.info("slo: replayed %d observation(s) from the "
                        "archive tail", restored)
        return restored

    # -- read side ------------------------------------------------------
    def snapshot(self, tenant: Optional[str] = None) -> Dict[str, Any]:
        """The ``fiber-tpu slo`` payload: per-tenant SLIs + burn state
        + the targets they are judged against."""
        burns = self.burn_rates()
        with self._lock:
            names = sorted(self._tenants)
            if tenant is not None:
                names = [n for n in names if n == tenant]
            tenants = {}
            for name in names:
                t = self._tenants[name]
                bad = sum(t.states.get(s, 0) for s in BAD_STATES)
                total = sum(t.states.values())
                tenants[name] = {
                    "jobs": dict(t.states),
                    "tasks": t.tasks,
                    "error_rate": (bad / total) if total else 0.0,
                    "queue_wait": t.queue.snapshot(),
                    "latency": t.latency.snapshot(),
                    "burn": burns.get(name, {}),
                }
            return {
                "targets": {
                    "latency_s": self.latency_s or None,
                    "queue_s": self.queue_s or None,
                    "p": self.p,
                    "error_pct": self.error_pct,
                    "window_s": self.window_s,
                    "fast_window_s": self.fast_window_s,
                    "burn_threshold": self.burn_threshold,
                },
                "tenants": tenants,
                "breached": self._breached,
                "observations": self.observations,
                "window_jobs": len(self._obs),
            }

    def clear(self) -> None:
        """Test isolation."""
        with self._lock:
            self._tenants.clear()
            self._obs = []
            self._seen.clear()
            self._breached = False
            self.observations = 0


#: Process-wide tracker; configured by telemetry.refresh(), driven by
#: the serve daemon's tick thread.
SLO = SloTracker()

"""Accounting plane: per-map / per-tenant resource cost attribution
(docs/observability.md "Resource accounting").

Every counter the other planes export is process- or host-global — good
for "is the cluster healthy", useless for "what did THIS job cost".
ROADMAP item 3 (a multi-tenant ``fiber-tpu serve`` tier with quotas,
admission control and preemption) cannot enforce limits it cannot
measure, so this module attributes the raw signals that already exist
(chunk timers, transport byte counters, store stats, device transfer /
compile accounting, FLOPs) to a **billing key**::

    (tenant, job_id, map_id)

* ``tenant`` — the ``tenant`` config knob (one per client process;
  ``serve`` will stamp it per connection);
* ``job_id`` — ``Pool.map(..., job_id=...)``'s durable id when given,
  else a synthetic ``map-<n>`` id;
* ``map_id`` — unique per submitted map in this master process.

The **billing key rides the task envelope's optional-field tail** (the
same back-compat posture as the trace context), so workers know which
map caused each chunk: their execute seconds, store fetches and device
transfers bill to it, and the frames they send back (result / spans /
prof / dev / cost) are billed by the master to the same key. Traffic
no key can be attributed to — heartbeats, credit-less control frames,
late frames of completed maps — lands in the explicit
:data:`OVERHEAD_KEY` bucket, never silently dropped: the per-key wire
bytes plus overhead always sum to the ledger's total.

**Exactly-once billing semantics** (chaos-tested):

* a *task* is billed to its map when its result slot fills for the
  FIRST time (``ResultStore.fill`` dedup is the billing gate) — a
  speculation duplicate or death/storemiss/partition resubmit re-runs
  the chunk but never re-bills its tasks;
* duplicate *traffic* (the resent chunk's wire bytes, the loser's
  result frame) IS billed to the map — it was caused by the map, and
  the wire reconciliation would not balance otherwise;
* ``fiber-tpu resume`` bills restored chunks as ``tasks_restored``
  (restore cost), never as executed tasks — restored + executed ==
  total, the ledger plane's exactly-once contract.

Collection mirrors the established plane pattern: workers ship
cumulative ``("cost", …)`` frames on the result stream, the host agent
serves a ``cost_snapshot`` op, ``TpuBackend.cluster_costs()`` sweeps it
(LocalBackend twin), ``Pool.cost()`` merges master + workers into
:func:`combine`-d reports, and a completed ``job_id`` map persists its
report beside the PR-7 ledger so ``fiber-tpu cost <job_id>`` can show
historical cost.

**Soft budgets**: :class:`CostBudget` caps registered per key raise the
``budget_exceeded`` watchdog anomaly (flight event + counter + log
warning, edge-triggered once per map) when a running map crosses them.
Enforcement lives in the serving tier (docs/serving.md): the policy
plane's ``throttle_tenant`` cuts the offender's WDRR weight on the
breach edge, and the serve daemon's admission controller
(:meth:`fiber_tpu.serve.admission.AdmissionController.tick`) escalates
a breach that outlives ``serve_preempt_grace_s`` to real preemption —
``Pool.preempt_billing_key`` parks the job resumable with its ledger
intact — while :meth:`~fiber_tpu.serve.admission.AdmissionController.check`
refuses new admissions against per-tenant quotas over these vectors.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from fiber_tpu import telemetry

#: Where untaggable traffic bills. Explicit, never silently dropped:
#: per-key wire bytes + overhead == the ledger total.
OVERHEAD_KEY: Tuple[str, str, str] = ("-", "-", "overhead")

#: Canonical numeric fields of a cost vector. Anything else passed to
#: charge() raises — a typo'd field must not silently open a new axis.
FIELDS = (
    # master-side seconds
    "serialize_s", "dispatch_s", "wall_s", "restore_s",
    # worker-side seconds (chunk resolve+execute+encode wall)
    "cpu_s",
    # exactly-once task counts (master: first-fill; worker: executions
    # INCLUDING duplicates — the difference is the duplicate count)
    "tasks", "tasks_restored", "tasks_executed",
    # wire bytes at the framing boundary (payload + 9-byte frame
    # overhead), master-observed for the total, worker-observed kept
    # as a per-source breakdown
    "wire_tx", "wire_rx",
    # object-store plane
    "store_put_bytes", "store_fetch_bytes",
    # durable-map ledger disk bytes
    "ledger_bytes",
    # device plane
    "device_transfer_bytes", "device_transfer_s",
    "compile_s", "flops", "device_s",
    # subset of device_transfer_bytes that rode the mesh (device-tier
    # placement + fan-out, site="ici") — the ICI-vs-wire blame split
    "ici_bytes",
)

_FIELD_SET = frozenset(FIELDS)

#: Wire size of one transport data frame carrying ``n`` payload bytes.
#: Re-exported from framing.FRAME_OVERHEAD (the single authority every
#: I/O engine bills through) so per-key sums reconcile with the
#: Endpoint byte counters under threads, selector and shm alike.
from fiber_tpu.framing import FRAME_OVERHEAD  # noqa: E402


def wire_size(payload_len: int) -> int:
    return int(payload_len) + FRAME_OVERHEAD


def key_str(key: Tuple[str, str, str]) -> str:
    """Stable text form of a billing key (snapshot dict keys must
    survive pickling across the agent RPC plane and JSON dumps)."""
    return "/".join(str(p) for p in key)


def parse_key(text: str) -> Tuple[str, str, str]:
    parts = str(text).split("/")
    while len(parts) < 3:
        parts.append("-")
    return (parts[0], parts[1], "/".join(parts[2:]))


# Per-job registry twins (docs/observability.md): bounded tenant/job
# labels with completed-job series retired so a long-lived master's
# 1000th job cannot fold live jobs into the overflow series
# (metrics.py per-metric bound override + LRU retire).
_JOB_LABEL_BOUND = 256
_m_job_tasks = telemetry.REGISTRY.counter(
    "cost_tasks_total", "Tasks billed per job (exactly-once)",
    max_label_sets=_JOB_LABEL_BOUND)
_m_job_cpu = telemetry.REGISTRY.counter(
    "cost_cpu_seconds", "Worker busy-seconds billed per job",
    max_label_sets=_JOB_LABEL_BOUND)
_m_job_wire = telemetry.REGISTRY.counter(
    "cost_wire_bytes", "Wire bytes billed per job (tx+rx)",
    max_label_sets=_JOB_LABEL_BOUND)
_m_budget_breaches = telemetry.counter(
    "cost_budget_breaches", "CostBudget limits crossed, by field")

#: Fields mirrored into the per-job registry counters at charge time.
_JOB_METRIC_FIELDS = {
    "tasks": _m_job_tasks,
    "cpu_s": _m_job_cpu,
    "wire_tx": _m_job_wire,
    "wire_rx": _m_job_wire,
}

#: Completed-map vectors kept for late Pool.cost() reads before the
#: oldest are dropped (a serve-tier master must not grow forever).
MAX_RETIRED_KEYS = 512


class CostBudget:
    """Soft per-map resource caps (``Pool.map(..., budget=...)``).

    Every limit is optional; a running map whose combined cost vector
    crosses ANY set limit raises the ``budget_exceeded`` watchdog
    anomaly (+ flight event) exactly once. This is the measurement
    hook; enforcement landed in the serve tier (docs/serving.md):
    WDRR throttling on the breach edge (telemetry/policy.py), then
    preemption after ``serve_preempt_grace_s`` via
    ``fiber_tpu.serve.admission`` + ``Pool.preempt_billing_key`` —
    the job parks ``preempted`` with its ledger intact, resumable."""

    __slots__ = ("cpu_s", "wire_mb", "device_s", "wall_s", "tasks")

    def __init__(self, cpu_s: Optional[float] = None,
                 wire_mb: Optional[float] = None,
                 device_s: Optional[float] = None,
                 wall_s: Optional[float] = None,
                 tasks: Optional[int] = None) -> None:
        self.cpu_s = None if cpu_s is None else float(cpu_s)
        self.wire_mb = None if wire_mb is None else float(wire_mb)
        self.device_s = None if device_s is None else float(device_s)
        self.wall_s = None if wall_s is None else float(wall_s)
        self.tasks = None if tasks is None else int(tasks)

    def violations(self, vec: Dict[str, float]) -> List[Tuple[str, float, float]]:
        """``[(limit_name, limit, observed), ...]`` for every crossed cap."""
        out: List[Tuple[str, float, float]] = []
        if self.cpu_s is not None and vec.get("cpu_s", 0.0) > self.cpu_s:
            out.append(("cpu_s", self.cpu_s, vec["cpu_s"]))
        if self.wire_mb is not None:
            wire = (vec.get("wire_tx", 0.0) + vec.get("wire_rx", 0.0)) \
                / float(1 << 20)
            if wire > self.wire_mb:
                out.append(("wire_mb", self.wire_mb, wire))
        if self.device_s is not None \
                and vec.get("device_s", 0.0) > self.device_s:
            out.append(("device_s", self.device_s, vec["device_s"]))
        if self.wall_s is not None \
                and vec.get("wall_s", 0.0) > self.wall_s:
            out.append(("wall_s", self.wall_s, vec["wall_s"]))
        if self.tasks is not None and vec.get("tasks", 0.0) > self.tasks:
            out.append(("tasks", float(self.tasks), vec["tasks"]))
        return out

    def as_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self.__slots__
                if getattr(self, k) is not None}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"CostBudget({self.as_dict()!r})"


_ambient = threading.local()


class CostLedger:
    """Per-process cost attribution table: billing key -> cost vector.

    One instance (:data:`COSTS`) serves masters AND workers — a worker's
    table holds the keys of the chunks it executed and ships as the
    cumulative ``("cost", …)`` frame; a master's table holds its own
    observation points (serialize / dispatch / wire / fill) and merges
    the workers' on top in :meth:`report`. Near-zero when disabled
    (``accounting_enabled`` x the telemetry master switch): every hook
    is one attribute check."""

    def __init__(self) -> None:
        self.enabled = True
        self.tenant = "default"
        self._lock = threading.Lock()
        self._costs: Dict[Tuple[str, str, str], Dict[str, float]] = {}
        self._retired: List[Tuple[str, str, str]] = []
        #: Keys already released (map completed): late charges — the
        #: final chunk's task bill, a trailing worker frame — still
        #: land in the vector, but their metric series must stay
        #: retired or every completed job would leak one label slot.
        self._released: set = set()
        #: Bumped on every charge — workers ship a fresh cost frame on
        #: the result stream only when this moved (the device-plane
        #: revision posture).
        self.revision = 0
        # soft budgets: key -> (CostBudget, on_breach callable or None)
        self._budgets: Dict[Tuple[str, str, str], CostBudget] = {}
        self._breached: Dict[Tuple[str, str, str], List[str]] = {}

    # -- configuration --------------------------------------------------
    def configure(self, cfg) -> None:
        """Follow the config knobs (telemetry.refresh)."""
        self.enabled = bool(cfg.telemetry_enabled) \
            and bool(cfg.accounting_enabled)
        self.tenant = str(cfg.tenant or "default")

    # -- ambient billing context ---------------------------------------
    def context(self, key: Optional[Tuple[str, str, str]]):
        """Set the ambient billing key for this thread — store fetches
        and device transfers inside the block bill to ``key`` instead of
        overhead (the worker wraps each chunk's processing in the
        chunk's envelope key)."""
        return _AmbientContext(key)

    @staticmethod
    def ambient_key() -> Optional[Tuple[str, str, str]]:
        return getattr(_ambient, "key", None)

    def bill_ambient(self, **fields: float) -> None:
        """Charge the thread's ambient key, or overhead when none is
        set — the hook store/device planes call without knowing about
        maps."""
        if not self.enabled:
            return
        self.charge(self.ambient_key() or OVERHEAD_KEY, **fields)

    # -- write side -----------------------------------------------------
    def charge(self, key: Optional[Tuple[str, str, str]],
               **fields: float) -> None:
        """Accumulate ``fields`` into ``key``'s vector (None key bills
        overhead). Unknown fields raise — the vector axes are closed."""
        if not self.enabled:
            return
        key = tuple(key) if key else OVERHEAD_KEY
        bad = set(fields) - _FIELD_SET
        if bad:
            raise ValueError(f"unknown cost field(s): {sorted(bad)}")
        with self._lock:
            vec = self._costs.get(key)
            if vec is None:
                vec = self._costs[key] = {}
            for field, n in fields.items():
                vec[field] = vec.get(field, 0.0) + float(n)
            self.revision += 1
            budget = self._budgets.get(key)
            released = key in self._released
        if key is not OVERHEAD_KEY and key[2] != "overhead":
            for field, n in fields.items():
                metric = _JOB_METRIC_FIELDS.get(field)
                if metric is not None:
                    metric.inc(float(n), tenant=key[0], job=key[1])
            if released:
                # A late charge re-lives the series; re-retire so the
                # completed job's label slots stay reclaimable.
                telemetry.REGISTRY.retire_series(tenant=key[0],
                                                 job=key[1])
        if budget is not None:
            self.check_budget(key)

    # -- soft budgets ---------------------------------------------------
    def set_budget(self, key: Tuple[str, str, str],
                   budget: CostBudget) -> None:
        with self._lock:
            self._budgets[tuple(key)] = budget

    def check_budget(self, key: Tuple[str, str, str],
                     extra: Optional[Dict[str, float]] = None) -> bool:
        """Evaluate ``key``'s budget against its vector (plus ``extra``
        — e.g. the worker-merged view the master computes). A newly
        crossed limit raises the edge-triggered ``budget_exceeded``
        anomaly; returns True when any limit is (or was) crossed."""
        key = tuple(key)
        with self._lock:
            budget = self._budgets.get(key)
            if budget is None:
                return bool(self._breached.get(key))
            vec = dict(self._costs.get(key) or {})
            already = self._breached.setdefault(key, [])
        if extra:
            for field, n in extra.items():
                vec[field] = vec.get(field, 0.0) + float(n)
        new = [v for v in budget.violations(vec) if v[0] not in already]
        for limit_name, limit, observed in new:
            already.append(limit_name)
            _m_budget_breaches.inc(field=limit_name)
            self._raise_budget_anomaly(key, limit_name, limit, observed)
        return bool(already)

    def _raise_budget_anomaly(self, key, limit_name: str,
                              limit: float, observed: float) -> None:
        # Lazy import keeps the module graph acyclic (monitor registers
        # instruments against telemetry, which imports this module).
        from fiber_tpu.telemetry.monitor import WATCHDOG

        WATCHDOG.external_breach(
            "budget_exceeded",
            detail=(f"map {key_str(key)} crossed its {limit_name} "
                    f"budget: {observed:.4g} > {limit:.4g}"),
            key=key_str(key), limit=limit_name,
            budget=round(float(limit), 6),
            observed=round(float(observed), 6))

    def release_key(self, key: Tuple[str, str, str]) -> None:
        """Map completed: drop its budget state, clear a standing
        ``budget_exceeded`` anomaly when no other budgeted map is in
        breach, retire its per-job metric series (freeing label slots
        for future jobs), and schedule the vector for LRU eviction.
        The vector itself stays readable until MAX_RETIRED_KEYS more
        maps retire — Pool.cost() after join() must still answer."""
        key = tuple(key)
        with self._lock:
            self._budgets.pop(key, None)
            was_breached = bool(self._breached.pop(key, None))
            any_breached = any(self._breached.values())
            self._released.add(key)
            self._retired.append(key)
            evict = []
            while len(self._retired) > MAX_RETIRED_KEYS:
                evict.append(self._retired.pop(0))
            for old in evict:
                self._costs.pop(old, None)
                self._released.discard(old)
        if was_breached and not any_breached:
            from fiber_tpu.telemetry.monitor import WATCHDOG

            WATCHDOG.external_clear("budget_exceeded")
        telemetry.REGISTRY.retire_series(tenant=key[0], job=key[1])

    # -- read side ------------------------------------------------------
    def vector(self, key: Tuple[str, str, str]) -> Dict[str, float]:
        with self._lock:
            return dict(self._costs.get(tuple(key)) or {})

    def snapshot(self) -> Dict[str, Any]:
        """Picklable per-process surface: the payload of the worker's
        ``("cost", …)`` frames, the agent's ``cost_snapshot`` op and
        ``cluster_costs()``."""
        from fiber_tpu.telemetry import tracing

        with self._lock:
            costs = {key_str(k): dict(v) for k, v in self._costs.items()}
            breached = {key_str(k): list(v)
                        for k, v in self._breached.items() if v}
        return {
            "host": tracing.host_id(),
            "pid": os.getpid(),
            "enabled": self.enabled,
            "tenant": self.tenant,
            "revision": self.revision,
            "costs": costs,
            "breached": breached,
        }

    def totals(self) -> Dict[str, float]:
        """Sum over every key (overhead included) — the internal
        reconciliation surface: per-key + overhead == this, always."""
        out: Dict[str, float] = {}
        with self._lock:
            for vec in self._costs.values():
                for field, n in vec.items():
                    out[field] = out.get(field, 0.0) + n
        return out

    def clear(self) -> None:
        with self._lock:
            self._costs.clear()
            self._budgets.clear()
            self._breached.clear()
            self._retired.clear()
            self._released.clear()
            self.revision = 0


class _AmbientContext:
    __slots__ = ("_key", "_prev")

    def __init__(self, key) -> None:
        self._key = tuple(key) if key else None
        self._prev = None

    def __enter__(self) -> "_AmbientContext":
        self._prev = getattr(_ambient, "key", None)
        _ambient.key = self._key
        return self

    def __exit__(self, *exc: Any) -> None:
        _ambient.key = self._prev


#: Process-wide cost ledger (knobs follow ``accounting_enabled`` /
#: ``tenant`` via telemetry.refresh()).
COSTS = CostLedger()


# ---------------------------------------------------------------------------
# Report assembly (master + worker frames -> one CostReport)
# ---------------------------------------------------------------------------

#: Fields whose authoritative observation point is the MASTER (every
#: pool frame passes its endpoints; worker wire counts would double-bill
#: the same traffic and are kept as a per-source breakdown only).
_MASTER_FIELDS = frozenset((
    "serialize_s", "dispatch_s", "wall_s", "restore_s",
    "tasks", "tasks_restored", "wire_tx", "wire_rx",
    "store_put_bytes", "ledger_bytes", "device_s", "flops",
))

#: Fields whose authoritative observation point is the WORKERS.
_WORKER_FIELDS = frozenset((
    "cpu_s", "tasks_executed", "store_fetch_bytes",
    "device_transfer_bytes", "device_transfer_s", "compile_s",
    "ici_bytes",
))


def combine(master: Dict[str, float],
            workers: Dict[str, float]) -> Dict[str, float]:
    """One total vector from the two observation points, each field
    taken from its authoritative side (module comment above) so shared
    traffic is never double-billed."""
    out: Dict[str, float] = {}
    for field, n in master.items():
        if field in _MASTER_FIELDS:
            out[field] = out.get(field, 0.0) + n
    for field, n in workers.items():
        if field in _WORKER_FIELDS:
            out[field] = out.get(field, 0.0) + n
    return out


def merge_worker_costs(frames: Dict[str, Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Sum the latest cumulative snapshot of every worker (label ->
    snapshot dict) into one key_str -> vector table."""
    merged: Dict[str, Dict[str, float]] = {}
    for snap in frames.values():
        for kstr, vec in (snap.get("costs") or {}).items():
            slot = merged.setdefault(kstr, {})
            for field, n in vec.items():
                slot[field] = slot.get(field, 0.0) + float(n)
    return merged


def build_report(key: Tuple[str, str, str],
                 master_vec: Dict[str, float],
                 worker_vecs: Dict[str, float],
                 budget: Optional[CostBudget] = None) -> Dict[str, Any]:
    """One map's CostReport: the combined total plus the per-source
    breakdown (the shape ``fiber-tpu cost`` renders and the per-job
    record persists)."""
    total = combine(master_vec, worker_vecs)
    report: Dict[str, Any] = {
        "schema": "fiber-cost-v1",
        "tenant": key[0],
        "job_id": key[1],
        "map_id": key[2],
        "key": key_str(key),
        "total": {k: (round(v, 6) if isinstance(v, float) else v)
                  for k, v in sorted(total.items())},
        "master": {k: round(v, 6) for k, v in sorted(master_vec.items())},
        "workers": {k: round(v, 6) for k, v in sorted(worker_vecs.items())},
    }
    if budget is not None:
        report["budget"] = budget.as_dict()
        report["budget_violations"] = [
            {"limit": n, "budget": b, "observed": round(o, 6)}
            for n, b, o in budget.violations(total)]
    return report


def render_report(report: Dict[str, Any]) -> str:
    """Human-readable cost report (the ``fiber-tpu cost`` output)."""
    total = report.get("total", {})
    lines = [
        f"job {report.get('job_id')}  tenant {report.get('tenant')}  "
        f"map {report.get('map_id')}",
        f"  tasks          {int(total.get('tasks', 0))} billed"
        f" + {int(total.get('tasks_restored', 0))} restored"
        f" ({int(total.get('tasks_executed', 0))} executions incl."
        " duplicates)",
        f"  wall           {total.get('wall_s', 0.0):.3f}s"
        f"  (serialize {total.get('serialize_s', 0.0):.3f}s,"
        f" dispatch {total.get('dispatch_s', 0.0):.3f}s,"
        f" restore {total.get('restore_s', 0.0):.3f}s)",
        f"  worker cpu     {total.get('cpu_s', 0.0):.3f}s",
        f"  wire           tx {int(total.get('wire_tx', 0))}B"
        f"  rx {int(total.get('wire_rx', 0))}B",
        f"  store          put {int(total.get('store_put_bytes', 0))}B"
        f"  fetched {int(total.get('store_fetch_bytes', 0))}B",
        f"  ledger disk    {int(total.get('ledger_bytes', 0))}B",
        f"  device         transfer "
        f"{int(total.get('device_transfer_bytes', 0))}B"
        f"/{total.get('device_transfer_s', 0.0):.3f}s"
        f"  (ici {int(total.get('ici_bytes', 0))}B)"
        f"  compile {total.get('compile_s', 0.0):.3f}s"
        f"  device_s {total.get('device_s', 0.0):.3f}"
        f"  flops {total.get('flops', 0.0):.3g}",
    ]
    violations = report.get("budget_violations") or []
    for v in violations:
        lines.append(f"  BUDGET EXCEEDED  {v['limit']}: "
                     f"{v['observed']:.4g} > {v['budget']:.4g}")
    if report.get("budget") and not violations:
        lines.append(f"  budget         {report['budget']} (within)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Persisted per-job cost records (beside the PR-7 ledger)
# ---------------------------------------------------------------------------


def cost_dir(root: Optional[str] = None) -> str:
    """Where per-job cost records land: the ``cost_dir`` config knob,
    or ``<staging root>/costs`` — beside ``ledger/`` so ``fiber-tpu
    jobs`` can join them."""
    if root is None:
        from fiber_tpu import config

        configured = str(config.get().cost_dir or "")
        if configured:
            return os.path.realpath(configured)
        from fiber_tpu.host_agent import default_staging_root

        root = default_staging_root()
    return os.path.join(root, "costs")


def _record_path(job_id: str, directory: Optional[str] = None) -> str:
    from fiber_tpu.store.ledger import check_job_id

    return os.path.join(directory or cost_dir(),
                        f"{check_job_id(job_id)}.json")


def write_job_record(job_id: str, report: Dict[str, Any],
                     directory: Optional[str] = None) -> Optional[str]:
    """Persist one job's CostReport (atomic rename; best-effort — cost
    history must never fail a map)."""
    import tempfile

    try:
        directory = directory or cost_dir()
        os.makedirs(directory, exist_ok=True)
        path = _record_path(job_id, directory)
        record = dict(report)
        record["ts"] = time.time()
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(record, fh, default=str)
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 - accounting must never fail maps
        return None


def read_job_record(job_id: str,
                    directory: Optional[str] = None) -> Optional[Dict[str, Any]]:
    try:
        with open(_record_path(job_id, directory)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None

"""Cluster flight recorder: a per-process ring buffer of structured
events from every infrastructure plane (docs/observability.md).

The metrics registry answers *how much* and the span store answers *what
happened to this map* — but neither answers "what was this process doing
in its last seconds" when a worker dies or "why did the scheduler make
that call" when a map runs slow. The flight recorder is that layer: each
plane emits one small dict per *decision or anomaly* (pool submit /
dispatch / resubmit / backpressure, scheduler locality / speculation /
park with the reason, store put / fetch / spill / miss, transport
connect / retry / stall / park, health suspect / revive / breaker
transitions) into a bounded deque — the black box an aircraft carries.

Design constraints, mirrored from the span store:

* **Near-zero when disabled** — every hook starts with one attribute
  read + branch on :attr:`FlightRecorder.enabled`; fully off, the hot
  paths pay a single load.
* **Lock-cheap when enabled** — one lock around a ``deque.append``; no
  I/O, no serialization, no per-event syscalls. The ``bench.py
  --telemetry`` flightrec arm gates the fully-on cost at <= 5%.
* **Bounded** — capacity follows ``flightrec_buffer_size``; the oldest
  events fall out and are counted in :attr:`FlightRecorder.dropped`.

Events are plain dicts (picklable, JSON-able)::

    {"ts": <epoch s>, "plane": "sched", "kind": "speculate",
     "seq": 3, "base": 64, "reason": "age 1.2s > 4.0x median 0.1s"}

They leave the process only on demand: ``Pool.flight_dump`` writes the
master's buffer as a JSON artifact, the host agent's ``postmortem`` op
ships an agent's buffer to the operator, and the crash handler
(:mod:`fiber_tpu.telemetry.postmortem`) flushes a dying worker's buffer
into a black-box bundle under the staging root.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: Planes the hooks report under (documentation + explain.py grouping;
#: record() does not enforce membership — a new plane must not need a
#: central registry edit to start reporting).
PLANES = ("pool", "sched", "store", "transport", "health", "agent",
          "policy")


class FlightRecorder:
    """Bounded FIFO of flight events (oldest fall out past capacity)."""

    def __init__(self, capacity: int = 2048, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._events: "collections.deque" = collections.deque(
            maxlen=max(1, int(capacity)))
        self.dropped = 0    # lifetime events evicted by the ring bound
        self.recorded = 0   # lifetime events accepted

    def record(self, plane: str, kind: str, **attrs: Any) -> Optional[str]:
        """Append one event and return its id (None when disabled).
        Call sites on hot paths should guard with ``if FLIGHT.enabled:``
        so the kwargs dict is never built when the recorder is off.

        The id is ``"<pid>-<n>"`` with ``n`` this recorder's lifetime
        accept count: stable, per-process monotonic, and unique across
        the processes whose buffers a postmortem merge concatenates —
        so a ``cause_id`` link (the policy plane's anomaly -> action ->
        outcome chain) survives ``order_events`` re-sorting."""
        if not self.enabled:
            return None
        # Dual clocks on every event: "ts" (wall) is comparable across
        # hosts but subject to NTP steps; "mono" orders events from ONE
        # process exactly. Cross-process merges (explain --flight, the
        # monitor plane) sort on (ts, mono) — wall first, monotonic as
        # the same-process tiebreak (see order_events).
        event: Dict[str, Any] = {"ts": time.time(),
                                 "mono": time.monotonic(),
                                 "plane": plane, "kind": kind}
        if attrs:
            event.update(attrs)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self.recorded += 1
            eid = f"{os.getpid()}-{self.recorded}"
            event["id"] = eid
            self._events.append(event)
        return eid

    def snapshot(self, last: int = 0) -> List[Dict[str, Any]]:
        """Copy of the buffered events, oldest first (``last`` > 0
        limits to the newest N — the postmortem pull)."""
        with self._lock:
            events = list(self._events)
        return events[-last:] if last > 0 else events

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
            return out

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._events = collections.deque(
                self._events, maxlen=max(1, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


#: Process-wide flight recorder (capacity/enablement follow the
#: ``flightrec_*`` config knobs via telemetry.refresh()).
FLIGHT = FlightRecorder()


def record(plane: str, kind: str, **attrs: Any) -> Optional[str]:
    """Module-level convenience for cold call sites."""
    return FLIGHT.record(plane, kind, **attrs)


def order_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Merge-order flight events from one or many processes: wall
    clock first (the only axis comparable across hosts), monotonic
    clock as the tiebreak (exact within a process, where wall-clock
    resolution or an NTP step can produce equal/backwards ``ts``).
    Events recorded before the dual-clock stamp sort by wall alone."""
    return sorted(events, key=lambda e: (float(e.get("ts", 0.0)),
                                         float(e.get("mono", 0.0))))

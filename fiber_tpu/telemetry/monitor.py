"""Anomaly watchdog: automatic detection of the failure modes the
chaos harness injects (docs/observability.md "Anomaly rules").

The watchdog rides the monitor sampler (:mod:`.timeseries`): every
``monitor_interval_s`` tick it receives the derived sample and checks a
small fixed rule set. A breach is an **edge event** — it fires once
when the rule newly trips (flight-recorder event on the new
``monitor`` plane, a log warning, and the ``monitor_anomalies``
counter) and clears when the signal recovers, so a long incident
doesn't spam one warning per tick. ``fiber-tpu top`` renders the
active set per host; ``snapshot()`` ships it through the agent's
``monitor_snapshot`` op.

Rules (knobs in config.py, docs/observability.md):

==================  ====================================================
throughput_drop     tasks/s fell more than ``anomaly_drop_pct`` below
                    the trailing-window mean while work is in flight —
                    the signature of a stuck/slowed worker
                    (chaos ``slow_worker_*``)
queue_growth        dispatch queue depth grew monotonically for
                    ``anomaly_queue_intervals`` consecutive samples —
                    submission outrunning the fleet
heartbeat_age       a peer has been silent longer than
                    ``suspect_timeout / 2`` — trouble brewing *before*
                    the failure detector declares (chaos
                    ``partition_*``)
store_disk_fill     the object store's disk tier is past
                    ``anomaly_disk_fill_pct`` of its bound — spill is
                    about to start failing
tx_queue_high       egress bytes queued in the transport exceed
                    ``anomaly_tx_queue_mb`` — a peer is not draining
budget_exceeded     a running map crossed its ``CostBudget`` caps
                    (accounting plane; raised via
                    :meth:`AnomalyWatchdog.external_breach` at charge
                    time, not on the sampler tick)
slo_burn            a serve-tier tenant's SLO is burning its error
                    budget past ``serve_slo_burn`` in BOTH burn
                    windows (SLO plane, telemetry/slo.py; raised via
                    :meth:`AnomalyWatchdog.external_breach` from the
                    daemon tick)
==================  ====================================================
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from fiber_tpu import telemetry
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.telemetry.policy import POLICY
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

_m_anomalies = telemetry.counter(
    "monitor_anomalies", "Watchdog rule breaches, by rule")

#: Trailing-window length (samples) for the throughput baseline.
TREND_WINDOW = 5

#: Recent anomaly records kept for the operator surface.
MAX_RECENT = 256


class AnomalyWatchdog:
    """Rule evaluation over monitor samples; see module docstring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # knobs (refreshed from config via configure())
        self.drop_pct = 0.5
        self.queue_intervals = 5
        self.tx_queue_bytes = 16 << 20
        self.disk_fill_pct = 0.9
        self.suspect_timeout = 10.0
        self.hbm_fill_pct = 0.92
        # state
        self._rates: Deque[float] = collections.deque(
            maxlen=TREND_WINDOW + 1)
        self._queue_depths: Deque[float] = collections.deque(maxlen=64)
        self._active: Dict[str, Dict[str, Any]] = {}
        self._recent: Deque[Dict[str, Any]] = collections.deque(
            maxlen=MAX_RECENT)
        self.total = 0  # lifetime breaches

    def configure(self, cfg) -> None:
        """Re-read the anomaly knobs (telemetry.refresh)."""
        self.drop_pct = min(0.99, max(0.01, float(cfg.anomaly_drop_pct)))
        self.queue_intervals = max(2, int(cfg.anomaly_queue_intervals))
        self.tx_queue_bytes = int(float(cfg.anomaly_tx_queue_mb) * (1 << 20))
        self.disk_fill_pct = min(1.0, max(0.05,
                                          float(cfg.anomaly_disk_fill_pct)))
        self.suspect_timeout = float(cfg.suspect_timeout or 0.0)
        self.hbm_fill_pct = min(1.0, max(0.05,
                                         float(cfg.anomaly_hbm_fill_pct)))

    # -- breach bookkeeping --------------------------------------------
    def _raise_anomaly(self, rule: str, detail: str,
                       **attrs: Any) -> None:
        record = {
            "rule": rule, "detail": detail,
            "wall": time.time(), "mono": time.monotonic(),
        }
        record.update(attrs)
        # The anomaly's flight-event id is the cause_id every linked
        # policy/outcome event carries (the explain chain's join key).
        record["id"] = FLIGHT.record("monitor", rule, detail=detail,
                                     **attrs)
        self._active[rule] = record
        self._recent.append(record)
        self.total += 1
        _m_anomalies.inc(rule=rule)
        logger.warning("monitor: anomaly %s — %s", rule, detail)
        # Policy plane (telemetry/policy.py): the breach edge is the
        # remediation trigger. Called under self._lock — same posture
        # as the old hardwired device-tier arm; the engine must never
        # call back into this watchdog.
        try:
            POLICY.on_anomaly(self, rule, record)
        except Exception:  # noqa: BLE001 - policy must not break detection
            logger.exception("monitor: policy hook failed for %s", rule)

    def _clear_anomaly(self, rule: str) -> None:
        record = self._active.pop(rule, None)
        if record is not None:
            FLIGHT.record("monitor", "clear", rule=rule,
                          cause_id=record.get("id"))
            logger.info("monitor: anomaly %s cleared", rule)
            # Clear edge reverts the rule's applied remediation
            # (promote the tier, restore weights/high-water/...).
            try:
                POLICY.on_clear(self, rule, record)
            except Exception:  # noqa: BLE001 - policy must not break
                # detection
                logger.exception(
                    "monitor: policy clear hook failed for %s", rule)

    def _edge(self, rule: str, breached: bool, detail: str = "",
              **attrs: Any) -> None:
        if breached and rule not in self._active:
            self._raise_anomaly(rule, detail, **attrs)
        elif breached:
            # Still breached: refresh the standing record's severity
            # attrs in place (no new event — breaches stay edges). The
            # policy engine's outcome verification compares these
            # against their action-time values (resolved / persisted /
            # worsened).
            self._active[rule].update(attrs, detail=detail)
        elif not breached and rule in self._active:
            self._clear_anomaly(rule)

    # -- the sampler callback ------------------------------------------
    def observe(self, sample: Dict[str, Any]) -> None:
        with self._lock:
            self._observe_locked(sample)
        # Outcome verification rides the same tick, AFTER the lock
        # drops: the engine re-samples rule state through this
        # watchdog's lock (telemetry/policy.py).
        try:
            POLICY.poll()
        except Exception:  # noqa: BLE001 - policy must not break detection
            logger.exception("monitor: policy verification failed")

    def _observe_locked(self, sample: Dict[str, Any]) -> None:
        # 1. throughput collapse vs the trailing window
        rate = float(sample.get("tasks_per_s") or 0.0)
        inflight = float(sample.get("inflight") or 0.0)
        trailing = list(self._rates)
        self._rates.append(rate)
        baseline = (sum(trailing) / len(trailing)) if trailing else 0.0
        breached = (
            len(trailing) >= TREND_WINDOW
            and baseline > 0.0
            and inflight > 0.0
            and rate < (1.0 - self.drop_pct) * baseline
        )
        self._edge(
            "throughput_drop", breached,
            detail=(f"tasks/s {rate:.1f} < "
                    f"{(1.0 - self.drop_pct):.2f}x trailing "
                    f"{baseline:.1f} with {inflight:.0f} in flight"),
            rate=round(rate, 3), baseline=round(baseline, 3))
        if breached:
            # A collapsed rate must not drag the baseline down to the
            # collapse level (which would self-clear the anomaly while
            # the worker is still stuck): freeze the window.
            self._rates.pop()

        # 2. queue depth monotonically growing
        depth = float(sample.get("queue_depth") or 0.0)
        self._queue_depths.append(depth)
        n = self.queue_intervals
        window = list(self._queue_depths)[-(n + 1):]
        growing = (
            len(window) >= n + 1
            and all(b > a for a, b in zip(window, window[1:]))
        )
        self._edge(
            "queue_growth", growing,
            detail=(f"dispatch queue grew {window[0]:.0f} -> "
                    f"{window[-1]:.0f} over {n} intervals"),
            depth=depth)

        # 3. heartbeat age past half the suspect deadline
        age = float(sample.get("heartbeat_age_s") or 0.0)
        threshold = self.suspect_timeout / 2.0
        self._edge(
            "heartbeat_age",
            self.suspect_timeout > 0 and age > threshold,
            detail=(f"oldest peer silence {age:.2f}s > "
                    f"suspect_timeout/2 ({threshold:.2f}s)"),
            age_s=round(age, 3))

        # 4. store disk-tier fill (only when a store exists — probing
        # must not instantiate one)
        usage, bound = _store_disk_usage()
        self._edge(
            "store_disk_fill",
            bound > 0 and usage > self.disk_fill_pct * bound,
            detail=(f"store disk tier {usage >> 20}MB > "
                    f"{self.disk_fill_pct:.0%} of {bound >> 20}MB"),
            bytes=usage)

        # 5. transport egress queue high water
        txq = float(sample.get("tx_queue_bytes") or 0.0)
        self._edge(
            "tx_queue_high", txq > self.tx_queue_bytes,
            detail=(f"tx queue {int(txq) >> 20}MB > "
                    f"{self.tx_queue_bytes >> 20}MB — a peer is not "
                    "draining"),
            bytes=int(txq))

        # 6. HBM fill (device telemetry plane; both fields None on CPU
        # or when no device runtime exists — honest null, no breach).
        # This rule REMEDIATES, not just observes: the policy engine's
        # hbm_fill policy (telemetry/policy.py, the refactored PR-13
        # arm) demotes the device store tier on the breach edge and
        # re-promotes on the clear edge — closed loop, flight-evented
        # by the tier itself plus the engine's policy/outcome chain.
        used, limit = _hbm_usage()
        self._edge(
            "hbm_fill", limit > 0 and used > self.hbm_fill_pct * limit,
            detail=(f"HBM {used >> 20}MB > "
                    f"{self.hbm_fill_pct:.0%} of {limit >> 20}MB"),
            bytes=used, limit=limit)

        # 7. recompile storm: one fingerprint compiling repeatedly
        # inside the device plane's window — shape churn, not progress
        storm = _recompile_state()
        self._edge(
            "recompile_storm", bool(storm.get("storm")),
            detail=(f"{storm.get('count', 0)} recompiles of "
                    f"{str(storm.get('fingerprint'))[:48]!r} within "
                    f"{storm.get('window_s', 0)}s"),
            fingerprint=str(storm.get("fingerprint"))[:48],
            count=int(storm.get("count", 0)))

    # -- external rules -------------------------------------------------
    def external_breach(self, rule: str, detail: str,
                        **attrs: Any) -> None:
        """Raise a rule owned by another plane (edge-triggered like the
        sampler rules; re-raising an active rule only refreshes its
        record). The accounting plane's ``budget_exceeded`` rides this:
        budgets are checked at charge time, not on the sampler tick
        (docs/observability.md "Resource accounting")."""
        with self._lock:
            if rule in self._active:
                self._active[rule].update(attrs, detail=detail)
                return
            self._raise_anomaly(rule, detail, **attrs)

    def external_clear(self, rule: str) -> None:
        with self._lock:
            self._clear_anomaly(rule)

    # -- read side -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": {r: dict(rec)
                           for r, rec in self._active.items()},
                "recent": [dict(r) for r in self._recent],
                "total": self.total,
            }

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._recent.clear()
            self._rates.clear()
            self._queue_depths.clear()
            self.total = 0


def _hbm_usage() -> "tuple[int, int]":
    """(bytes in use, limit) of the first local device's HBM; (0, 0)
    when unavailable (CPU, no device runtime) — the rule can't breach
    without a real limit."""
    try:
        from fiber_tpu.telemetry.device import DEVICE

        with DEVICE._lock:
            hbm = dict(DEVICE._hbm)
        return int(hbm.get("bytes_in_use") or 0), \
            int(hbm.get("bytes_limit") or 0)
    except Exception:  # noqa: BLE001 - monitoring must not fail
        return 0, 0


def _recompile_state() -> Dict[str, Any]:
    """The device plane's recompile-storm probe (monkeypatchable in
    tests, like _store_disk_usage)."""
    try:
        from fiber_tpu.telemetry.device import DEVICE

        return DEVICE.recompile_state()
    except Exception:  # noqa: BLE001 - monitoring must not fail
        return {"storm": False}


def _store_disk_usage() -> "tuple[int, int]":
    """(bytes used, bound) of the process store's disk tier; (0, 0)
    when no store has been built or it has no disk root."""
    try:
        from fiber_tpu import store as storemod

        st = storemod._store  # peek, never instantiate
        if st is None or st.root is None:
            return 0, 0
        return st.disk_usage(), int(st.max_disk_bytes)
    except Exception:  # noqa: BLE001 - monitoring must not fail
        return 0, 0


#: Process-wide watchdog; registered as a TIMESERIES observer by
#: telemetry.refresh().
WATCHDOG = AnomalyWatchdog()


def monitor_payload(history: int = 120) -> Dict[str, Any]:
    """The per-host monitor surface: latest derived sample + bounded
    ring history + the watchdog state + per-peer heartbeat ages. One
    shape shared by the host agent's ``monitor_snapshot`` op, the
    local backend's ``cluster_timeseries`` and ``Pool.timeseries()``
    so `fiber-tpu top` renders any source identically."""
    import os as _os

    from fiber_tpu import health
    from fiber_tpu.telemetry import tracing
    from fiber_tpu.telemetry.timeseries import TIMESERIES

    try:
        ages = {str(k): round(v, 3)
                for k, v in health.heartbeat_ages().items()}
    except Exception:  # noqa: BLE001
        ages = {}
    try:
        actions = POLICY.recent_actions(8)
    except Exception:  # noqa: BLE001
        actions = []
    return {
        "host": tracing.host_id(),
        "pid": _os.getpid(),
        "timeseries": TIMESERIES.snapshot(last=int(history)),
        "anomalies": WATCHDOG.snapshot(),
        "heartbeat_ages": ages,
        "device": _device_summary(),
        # Autonomous operations: what this host's policy engine DID
        # about the anomalies above (`fiber-tpu top` action feed).
        "policy": actions,
    }


def _device_summary() -> Dict[str, Any]:
    """Compact device-plane row for `fiber-tpu top` (HBM + MFU
    columns): None fields are honest nulls, never zeros — the table
    renders them as '-' (docs/observability.md "Device telemetry")."""
    try:
        from fiber_tpu.telemetry.device import DEVICE

        snap = DEVICE.snapshot()
        out = {
            "hbm_bytes_in_use": snap["hbm"].get("bytes_in_use"),
            "hbm_bytes_limit": snap["hbm"].get("bytes_limit"),
            "mfu": snap["mfu"].get("mfu"),
            "compiles": snap.get("compiles", 0),
            "transfer_bytes": snap.get("transfer_bytes", 0),
            # device store tier occupancy (None = no tier built here —
            # a host-plane process; 'top' renders it '-')
            "dev_store_bytes": None,
            "dev_store_demoted": None,
        }
        from fiber_tpu import store as storemod

        tier = storemod._dtier  # peek, never instantiate
        if tier is not None:
            tstats = tier.stats()
            out["dev_store_bytes"] = int(tstats.get("bytes", 0))
            out["dev_store_demoted"] = bool(tstats.get("demoted"))
        return out
    except Exception:  # noqa: BLE001 - monitoring must not fail
        return {}

"""Task-lifecycle tracing: spans, trace context, and the per-process
ring-buffer span store.

The model is Dapper's (Sigelman et al., 2010): a **trace id** names one
logical operation end to end (here: one ``Pool.map``); every timed
region inside it is a **span** carrying the trace id and its parent span
id. The master samples a trace per map (``trace_sample_rate``), stamps
``(trace_id, parent_span_id)`` into each task envelope, and workers
adopt that context so their spans — ref-resolve, user fn, result-pickle
— join the same trace. Finished spans land in :data:`SPANS`, a bounded
ring buffer; pool workers drain it and ship the spans back on the result
stream (pool.py), so the master's store ends up holding the whole
cluster's view of its traces.

Spans are plain dicts (picklable, JSON-able)::

    {"name": "worker.execute", "trace": "6fa1…", "span": "03bc…",
     "parent": "9d2e…" | None, "ts": <epoch s>, "dur": <s>,
     "host": "<hostname>", "pid": <os pid>, ...attrs}
"""

from __future__ import annotations

import collections
import contextlib
import os
import socket
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Tuple

_tls = threading.local()

_host_cache: Optional[str] = None


def host_id() -> str:
    """Stable host label for spans and log context: FIBER_HOST_ID env
    override, else the hostname."""
    global _host_cache
    if _host_cache is None:
        _host_cache = (os.environ.get("FIBER_HOST_ID")
                       or socket.gethostname() or "host")
    return _host_cache


def new_id() -> str:
    return uuid.uuid4().hex[:16]


class SpanStore:
    """Bounded FIFO of finished spans (oldest fall out past capacity)."""

    def __init__(self, capacity: int = 4096) -> None:
        self._lock = threading.Lock()
        self._spans: "collections.deque" = collections.deque(
            maxlen=max(1, int(capacity)))
        self.dropped = 0  # lifetime spans evicted by the ring bound

    def add(self, span: Dict) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(span)

    def add_all(self, spans: List[Dict]) -> None:
        with self._lock:
            for span in spans:
                if len(self._spans) == self._spans.maxlen:
                    self.dropped += 1
                self._spans.append(span)

    def drain(self) -> List[Dict]:
        """Pop every stored span (worker-side shipping)."""
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
            return out

    def snapshot(self) -> List[Dict]:
        with self._lock:
            return list(self._spans)

    def resize(self, capacity: int) -> None:
        with self._lock:
            self._spans = collections.deque(
                self._spans, maxlen=max(1, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)


#: Process-wide finished-span buffer (capacity follows
#: ``span_buffer_size`` via telemetry.refresh()).
SPANS = SpanStore()


def current() -> Optional[Tuple[str, Optional[str]]]:
    """Ambient ``(trace_id, span_id)`` of this thread, or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> Optional[str]:
    ctx = current()
    return ctx[0] if ctx else None


@contextlib.contextmanager
def trace_context(trace_id: str,
                  span_id: Optional[str] = None) -> Iterator[None]:
    """Adopt a propagated trace context (worker side: the envelope's
    ``(trace, parent_span)``) for the enclosed region, so nested
    :func:`span` calls join that trace."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((trace_id, span_id))
    try:
        yield
    finally:
        stack.pop()


@contextlib.contextmanager
def span(name: str, trace: Optional[str] = None,
         parent: Optional[str] = None, store: Optional[SpanStore] = None,
         **attrs) -> Iterator[Optional[Dict]]:
    """Record one timed span into the process span store (no-op when
    telemetry is disabled — yields None). Trace/parent default to the
    ambient context; with neither, the span roots a fresh trace.
    Yields the span dict so callers can read ``span["span"]`` to use as
    the parent id for propagated work."""
    from fiber_tpu import telemetry

    if not telemetry.tracing_active():
        yield None
        return
    if trace is None:
        ctx = current()
        if ctx is not None:
            trace = ctx[0]
            if parent is None:
                parent = ctx[1]
        else:
            trace = new_id()
    sp: Dict = {
        "name": name,
        "trace": trace,
        "span": new_id(),
        "parent": parent,
        "ts": time.time(),
        "dur": 0.0,
        "host": host_id(),
        "pid": os.getpid(),
    }
    if attrs:
        sp.update(attrs)
    t0 = time.perf_counter()
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((trace, sp["span"]))
    try:
        yield sp
    finally:
        stack.pop()
        sp["dur"] = time.perf_counter() - t0
        (store or SPANS).add(sp)

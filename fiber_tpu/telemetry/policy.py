"""Policy plane: autonomous remediation riding the anomaly watchdog
(docs/observability.md "Autonomous operations").

Every plane so far *reports*; this one *acts*. The watchdog's
edge-triggered anomalies (telemetry/monitor.py) are the triggers: on a
breach edge the engine looks up the rule's registered policy, runs its
remediation, and records the act as a ``policy`` flight event whose
``cause_id`` links back to the anomaly's own event id — so ``fiber-tpu
explain --flight`` narrates the full *anomaly → action → outcome*
chain instead of leaving the operator to correlate timestamps.

The remediation set (ROADMAP item 5, one registered policy per rule):

====================  =================================================
hbm_fill              demote the device store tier to the host tiers
                      (the PR-13 arm, now the engine's first policy);
                      re-promote on the clear edge
recompile_storm       pin the offending fingerprint's compile-cache
                      entries so LRU churn stops re-evicting the storm's
                      own program; unpin on clear
heartbeat_age /       pre-emptively replicate precious digests (the
throughput_drop       suspect-time path, run EARLY) and boost straggler
                      speculation on live schedulers; restore on clear
store_disk_fill       LRU eviction pressure: trim the disk tier below
                      the fill threshold
budget_exceeded       throttle the offending (tenant, job): cut the WDRR
                      weight of its in-flight maps (the PR-10 hook);
                      restore on clear
tx_queue_high         tighten the transport TX high-water so senders
                      feel backpressure earlier; restore on clear
queue_growth          shrink the admission window of active streaming
                      maps (docs/streaming.md) so a runaway producer
                      parks instead of filling master RAM; restore the
                      original windows on clear
slo_burn              a tenant's serve-tier SLO is burning its error
                      budget (telemetry/slo.py): boost every registered
                      warm pool to its ceiling (capacity is the lever
                      for queue/latency burn) and, for an error burn,
                      WDRR-throttle the offending tenant's in-flight
                      maps; restore both on clear
====================  =================================================

Verification closes the loop: ``policy_verify_s`` after an action the
engine re-samples the rule through the raising watchdog and classifies
the **outcome** — ``resolved`` (the rule cleared), ``persisted`` (still
breached, severity flat) or ``worsened`` (severity degraded ≥5%) — as
both an ``outcome`` flight event and the ``policy_actions`` counter
(labels rule/action/outcome). ``policy_dry_run`` records what *would*
have been done without acting; per-rule cooldowns stop a flapping rule
from re-firing its action every edge (the hbm_fill demote/promote pair
is exempt — its hysteresis lives in the watchdog edge itself, and the
PR-13 drills require every breach edge to demote).

Concurrency contract: ``on_anomaly``/``on_clear`` run UNDER the raising
watchdog's lock (the same posture as the old hardwired arm), so actions
must never call back into a watchdog. Verification (``poll``) runs
outside it — after ``observe`` releases, or from any caller.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from fiber_tpu import telemetry
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.utils.logging import get_logger

logger = get_logger()

_m_actions = telemetry.counter(
    "policy_actions",
    "Policy-plane remediations, by rule/action/verified outcome")

#: Recent action records kept for the operator surface (`fiber-tpu
#: policies`, the `top` feed, monitor_payload).
MAX_RECENT = 64

#: Severity attr per rule for outcome classification: (key, direction)
#: — direction +1 means a larger value is worse, -1 means smaller is
#: worse. Compared between the action-time anomaly record and the
#: re-sampled record after policy_verify_s (the watchdog refreshes a
#: standing anomaly's attrs each tick).
RULE_SEVERITY: Dict[str, Tuple[str, int]] = {
    "throughput_drop": ("rate", -1),
    "queue_growth": ("depth", +1),
    "heartbeat_age": ("age_s", +1),
    "store_disk_fill": ("bytes", +1),
    "tx_queue_high": ("bytes", +1),
    "hbm_fill": ("bytes", +1),
    "recompile_storm": ("count", +1),
    "budget_exceeded": ("observed", +1),
    "slo_burn": ("burn", +1),
}

#: Fractional severity degradation that upgrades "persisted" to
#: "worsened".
WORSE_PCT = 0.05

#: Pools registered for billing-key resolution (budget_exceeded
#: throttling) — weak so a closed pool drops out without bookkeeping.
_POOLS: "weakref.WeakSet" = weakref.WeakSet()


def register_pool(pool) -> None:
    """Called by Pool.__init__: the budget_exceeded policy resolves a
    billing key to in-flight maps through every registered pool's
    ``throttle_billing_key`` hook."""
    _POOLS.add(pool)


#: Warm pools registered for the slo_burn boost — weak, like _POOLS
#: (a stopped daemon's warm pool drops out without bookkeeping).
_WARM: "weakref.WeakSet" = weakref.WeakSet()


def register_warm_pool(warm) -> None:
    """Called by the serve daemon: the slo_burn policy scales every
    registered warm pool to its ceiling through its ``boost`` hook."""
    _WARM.add(warm)


# ---------------------------------------------------------------------------
# remediation actions
#
# Each is ``fn(record, dry_run) -> (applied, detail, revert)``: the
# anomaly record supplies the offender (fingerprint, billing key, ...),
# ``detail`` narrates what was (or would be) done, ``revert`` (optional)
# runs on the rule's clear edge. All targets are PEEKED, never
# instantiated — a process without the subsystem has nothing to remediate
# (the watchdog's `_store_disk_usage` convention).
# ---------------------------------------------------------------------------


def _act_hbm_fill(record: Dict[str, Any], dry_run: bool):
    """The PR-13 arm, refactored from monitor._device_tier_remediate:
    demote the device store tier on the breach edge (its HBM is the one
    allocation the runtime can safely shed — the host store still holds
    every byte), re-promote on the clear edge via the revert."""
    from fiber_tpu import store as storemod

    tier = storemod._dtier  # peek, never instantiate
    if tier is None:
        return False, "no device store tier in this process", None
    if dry_run:
        return False, "would demote the device store tier to host RAM", None
    freed = tier.demote("hbm_fill")

    def revert() -> None:
        t = storemod._dtier
        if t is not None:
            t.promote()

    return True, (f"demoted device store tier "
                  f"({freed} bytes shed to the host tiers)"), revert


def _act_recompile_storm(record: Dict[str, Any], dry_run: bool):
    fp = str(record.get("fingerprint") or "")
    if not fp or fp == "None":
        return False, "storm fingerprint unknown; nothing to pin", None
    from fiber_tpu.parallel import dmap

    if dry_run:
        return False, f"would pin compile-cache entries for {fp!r}", None
    n = dmap.pin_fingerprint(fp)

    def revert() -> None:
        dmap.unpin_fingerprint(fp)

    return True, (f"pinned {n} compile-cache entr"
                  f"{'y' if n == 1 else 'ies'} for {fp!r} — LRU "
                  "eviction skips them while the storm lasts"), revert


def _act_straggler(record: Dict[str, Any], dry_run: bool):
    """heartbeat_age / throughput_drop: run the suspect-time precious
    replication EARLY (while 'trouble brewing' is still cheap to hedge)
    and tighten straggler speculation so duplicates fire sooner.
    Speculation is only boosted where it is already enabled — duplicates
    are only safe for idempotent functions, and the policy plane must
    not widen that contract."""
    from fiber_tpu.sched.core import _LIVE
    from fiber_tpu.store.replicate import REPLICATOR

    scheds = [s for s in list(_LIVE) if not s.closed and s.speculation]
    if dry_run:
        driver = ("registered" if REPLICATOR.has_driver()
                  else "not registered")
        return False, (f"would replicate precious digests (driver "
                       f"{driver}) and boost speculation on "
                       f"{len(scheds)} scheduler(s)"), None
    boosted = [s for s in scheds if s.boost_speculation()]
    drove = REPLICATOR.drive(reason=str(record.get("rule") or "policy"))
    parts = []
    if drove:
        parts.append("kicked pre-emptive precious replication")
    else:
        parts.append("replication skipped (no driver or nothing "
                     "precious)")
    if boosted:
        parts.append(f"boosted speculation on {len(boosted)} "
                     "scheduler(s)")
    else:
        parts.append("no speculation-enabled scheduler to boost")
    applied = bool(boosted) or drove

    def revert() -> None:
        for s in boosted:
            try:
                s.restore_speculation()
            except Exception:  # noqa: BLE001 - best-effort restore
                pass

    return applied, "; ".join(parts), (revert if boosted else None)


def _act_store_disk_fill(record: Dict[str, Any], dry_run: bool):
    from fiber_tpu import store as storemod

    st = storemod._store  # peek, never instantiate
    if st is None or st.root is None:
        return False, "no store disk tier in this process", None
    if dry_run:
        return False, ("would trim the disk tier to 70% of "
                       "max_disk_bytes"), None
    freed = st.shed_disk(0.7)
    return True, (f"LRU eviction pressure: trimmed the disk tier by "
                  f"{freed} bytes (target 70% of its bound)"), None


def _act_budget(record: Dict[str, Any], dry_run: bool):
    from fiber_tpu.telemetry.accounting import key_str, parse_key

    key = parse_key(str(record.get("key") or ""))
    if dry_run:
        return False, (f"would cut the WDRR weight of maps billed to "
                       f"{key_str(key)} by 4x"), None
    hit: List[Tuple["weakref.ref", Tuple[str, str, str]]] = []
    n = 0
    for pool in list(_POOLS):
        try:
            throttled = pool.throttle_billing_key(key, factor=4.0)
        except Exception:  # noqa: BLE001 - one pool must not stop the rest
            logger.exception("policy: budget throttle failed")
            continue
        if throttled:
            n += throttled
            hit.append((weakref.ref(pool), key))
    if not n:
        return False, (f"no in-flight map billed to {key_str(key)} "
                       "in this process"), None

    def revert() -> None:
        for pref, k in hit:
            p = pref()
            if p is not None:
                try:
                    p.unthrottle_billing_key(k)
                except Exception:  # noqa: BLE001 - best-effort restore
                    pass

    return True, (f"throttled {n} in-flight map(s) billed to "
                  f"{key_str(key)}: WDRR weight cut 4x"), revert


def _act_queue_growth(record: Dict[str, Any], dry_run: bool):
    """queue_growth: a monotonically growing task queue means the
    producer outruns the cluster — for streaming maps the source is
    throttleable, so halve every active stream's admission window
    (docs/streaming.md): admission parks sooner, the queue drains, and
    the producer feels backpressure instead of filling master RAM.
    Restores the original windows on the clear edge."""
    pools = [p for p in list(_POOLS)
             if getattr(p, "_stream_windows", None)]
    if dry_run:
        streams = sum(len(p._stream_windows) for p in pools)
        return False, (f"would halve the admission window of {streams} "
                       f"active stream(s) across {len(pools)} "
                       "pool(s)"), None
    hit: List["weakref.ref"] = []
    n = 0
    for pool in pools:
        try:
            shrunk = pool.shrink_stream_window(factor=0.5)
        except Exception:  # noqa: BLE001 - one pool must not stop the rest
            logger.exception("policy: stream-window shrink failed")
            continue
        if shrunk:
            n += shrunk
            hit.append(weakref.ref(pool))
    if not n:
        return False, ("no active streaming map in this process; "
                       "queue growth is not admission-driven"), None

    def revert() -> None:
        for pref in hit:
            p = pref()
            if p is not None:
                try:
                    p.restore_stream_window()
                except Exception:  # noqa: BLE001 - best-effort restore
                    pass

    return True, (f"halved the admission window of {n} active "
                  "stream(s) — producer parks sooner, queue "
                  "drains"), revert


def _act_tx_queue_high(record: Dict[str, Any], dry_run: bool):
    from fiber_tpu.transport import evloop

    old = int(evloop.TX_HIGH_WATER)
    new = max(4 << 20, old // 2)
    if new >= old:
        return False, (f"TX high-water already at its "
                       f"{old >> 20}MB floor"), None
    if dry_run:
        return False, (f"would tighten TX high-water "
                       f"{old >> 20}MB -> {new >> 20}MB"), None
    evloop.set_tx_high_water(new)

    def revert() -> None:
        evloop.set_tx_high_water(old)

    return True, (f"tightened TX high-water {old >> 20}MB -> "
                  f"{new >> 20}MB — senders feel backpressure "
                  "earlier"), revert


def _act_slo_burn(record: Dict[str, Any], dry_run: bool):
    """slo_burn: a tenant's serve-tier objective is burning budget
    (telemetry/slo.py). Two existing levers, both reverted on clear:

    * **warm-pool boost** — queue-wait and latency burn are usually
      capacity-shaped, so pin every registered warm pool at its
      ceiling (the floor is raised; the idle scale-down stops) until
      the burn clears;
    * **offender throttle** — an *error* burn is usually one tenant's
      own failing workload crowding the pool, so cut the WDRR weight
      of every in-flight map billed to the offending tenant (the
      budget_exceeded lever, tenant-wide instead of per-key)."""
    tenant = str(record.get("tenant") or "")
    sli = str(record.get("sli") or "")
    warms = [w for w in list(_WARM)]
    if dry_run:
        return False, (f"would boost {len(warms)} warm pool(s) to "
                       f"ceiling"
                       + (f" and throttle tenant {tenant!r}"
                          if sli == "error" and tenant else "")), None
    boosted = []
    for warm in warms:
        try:
            if warm.boost():
                boosted.append(weakref.ref(warm))
        except Exception:  # noqa: BLE001 - one pool must not stop the rest
            logger.exception("policy: warm-pool boost failed")
    throttled: List[Tuple["weakref.ref", tuple]] = []
    n_throttled = 0
    if sli == "error" and tenant:
        for pool in list(_POOLS):
            try:
                keys = {tuple(bk) for bk in
                        list(pool._seq_bill.values())
                        if bk and bk[0] == tenant}
                for key in keys:
                    hit = pool.throttle_billing_key(key, factor=4.0)
                    if hit:
                        n_throttled += hit
                        throttled.append((weakref.ref(pool), key))
            except Exception:  # noqa: BLE001
                logger.exception("policy: slo_burn throttle failed")
    parts = []
    if boosted:
        parts.append(f"boosted {len(boosted)} warm pool(s) to ceiling")
    else:
        parts.append("no warm pool to boost")
    if n_throttled:
        parts.append(f"throttled {n_throttled} in-flight map(s) of "
                     f"tenant {tenant!r}: WDRR weight cut 4x")
    elif sli == "error" and tenant:
        parts.append(f"no in-flight map billed to tenant {tenant!r}")
    applied = bool(boosted) or bool(n_throttled)
    if not applied:
        return False, "; ".join(parts), None

    def revert() -> None:
        for wref in boosted:
            w = wref()
            if w is not None:
                try:
                    w.unboost()
                except Exception:  # noqa: BLE001 - best-effort restore
                    pass
        for pref, key in throttled:
            p = pref()
            if p is not None:
                try:
                    p.unthrottle_billing_key(key)
                except Exception:  # noqa: BLE001 - best-effort restore
                    pass

    return True, "; ".join(parts), revert


class Policy:
    """One rule -> action binding (declarative row of the engine)."""

    __slots__ = ("rule", "action", "func", "knob", "cooldown_s")

    def __init__(self, rule: str, action: str, func: Callable,
                 knob: str = "", cooldown_s: Optional[float] = None) -> None:
        self.rule = rule
        self.action = action
        self.func = func
        self.knob = knob            # the config knob that tunes the rule
        self.cooldown_s = cooldown_s  # None = engine default


#: The shipped policy table (docs/observability.md "Autonomous
#: operations"). hbm_fill's cooldown is 0: the demote/promote pair must
#: track every watchdog edge exactly (the PR-13 behavior contract).
_DEFAULT_POLICIES: Tuple[Policy, ...] = (
    Policy("hbm_fill", "demote_device_tier", _act_hbm_fill,
           knob="anomaly_hbm_fill_pct", cooldown_s=0.0),
    Policy("recompile_storm", "pin_compile_cache", _act_recompile_storm,
           knob="anomaly_recompile_count"),
    Policy("heartbeat_age", "replicate_and_boost", _act_straggler,
           knob="suspect_timeout"),
    Policy("throughput_drop", "replicate_and_boost", _act_straggler,
           knob="anomaly_drop_pct"),
    Policy("store_disk_fill", "shed_store_disk", _act_store_disk_fill,
           knob="anomaly_disk_fill_pct"),
    Policy("budget_exceeded", "throttle_tenant", _act_budget,
           knob="CostBudget caps"),
    Policy("tx_queue_high", "tighten_tx_highwater", _act_tx_queue_high,
           knob="anomaly_tx_queue_mb"),
    Policy("queue_growth", "shrink_stream_window", _act_queue_growth,
           knob="stream_window"),
    Policy("slo_burn", "boost_and_throttle", _act_slo_burn,
           knob="serve_slo_burn"),
)


class PolicyEngine:
    """Anomaly -> remediation dispatch + outcome verification."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # knobs (refreshed from config via configure())
        self.enabled = True
        self.dry_run = False
        self.cooldown_s = 30.0
        self.verify_s = 3.0
        self._rules_filter: Optional[set] = None  # None = all rules
        # the policy table
        self._policies: Dict[str, Policy] = {
            p.rule: p for p in _DEFAULT_POLICIES}
        # state
        self._last_action: Dict[str, float] = {}   # rule -> mono stamp
        self._applied: Dict[str, Dict[str, Any]] = {}  # rule -> revert
        self._pending: List[Dict[str, Any]] = []   # verification queue
        self._recent: Deque[Dict[str, Any]] = collections.deque(
            maxlen=MAX_RECENT)
        self.actions_total = 0
        self.suppressed_total = 0

    def configure(self, cfg) -> None:
        """Re-read the policy knobs (telemetry.refresh)."""
        self.enabled = bool(cfg.telemetry_enabled) \
            and bool(cfg.policy_enabled)
        self.dry_run = bool(cfg.policy_dry_run)
        self.cooldown_s = max(0.0, float(cfg.policy_cooldown_s))
        self.verify_s = max(0.05, float(cfg.policy_verify_s))
        rules = str(cfg.policy_rules).strip().lower()
        if rules in ("", "all", "*"):
            self._rules_filter = None
        else:
            self._rules_filter = {r.strip() for r in rules.split(",")
                                  if r.strip()}

    # -- watchdog hooks (called UNDER the raising watchdog's lock) ------
    def on_anomaly(self, dog, rule: str,
                   record: Dict[str, Any]) -> None:
        """Breach edge: run the rule's policy (if any). ``record`` is
        the watchdog's anomaly record — its ``id`` (the anomaly's
        flight-event id) becomes every linked event's ``cause_id``."""
        if not self.enabled:
            return
        pol = self._policies.get(rule)
        if pol is None:
            return
        if self._rules_filter is not None \
                and rule not in self._rules_filter:
            return
        now = time.monotonic()
        cause_id = record.get("id")
        with self._lock:
            cd = (self.cooldown_s if pol.cooldown_s is None
                  else pol.cooldown_s)
            last = self._last_action.get(rule)
            if last is not None and cd > 0 and (now - last) < cd:
                self.suppressed_total += 1
                FLIGHT.record(
                    "policy", "suppressed", rule=rule,
                    action=pol.action, cause_id=cause_id,
                    reason=(f"cooldown: last action "
                            f"{now - last:.1f}s ago < {cd:g}s"))
                return
            self._last_action[rule] = now
        try:
            applied, detail, revert = pol.func(record, self.dry_run)
        except Exception:  # noqa: BLE001 - a policy must never take
            # the watchdog (and the sampler thread) down with it
            logger.exception("policy: %s action %s failed",
                             rule, pol.action)
            applied, detail, revert = False, "action raised; see log", None
        act: Dict[str, Any] = {
            "rule": rule, "action": pol.action,
            "wall": time.time(), "mono": now,
            "cause_id": cause_id, "applied": bool(applied),
            "dry_run": bool(self.dry_run), "detail": detail,
            "outcome": None,
        }
        act["id"] = FLIGHT.record(
            "policy", pol.action, rule=rule, cause_id=cause_id,
            applied=bool(applied), dry_run=bool(self.dry_run) or None,
            detail=detail)
        with self._lock:
            self._recent.append(act)
            self.actions_total += 1
            if applied and revert is not None:
                self._applied[rule] = {"revert": revert,
                                       "dog": weakref.ref(dog)}
            sev = RULE_SEVERITY.get(rule)
            baseline = (record.get(sev[0]) if sev else None)
            self._pending.append({
                "due": now + self.verify_s, "rule": rule, "act": act,
                "dog": weakref.ref(dog), "baseline": baseline,
            })
        logger.warning("policy: %s -> %s%s — %s", rule, pol.action,
                       " [dry-run]" if self.dry_run else "", detail)

    def on_clear(self, dog, rule: str,
                 record: Optional[Dict[str, Any]] = None) -> None:
        """Clear edge: run the applied action's revert (promote the
        tier, unpin the fingerprint, restore speculation/weights/
        high-water). Only the watchdog that triggered the action (or a
        dead one) reverts — a second watchdog instance clearing the
        same rule name must not undo another's remediation."""
        entry = None
        with self._lock:
            e = self._applied.get(rule)
            if e is not None:
                d = e["dog"]()
                if d is None or d is dog:
                    entry = self._applied.pop(rule)
        if entry is None:
            return
        try:
            entry["revert"]()
        except Exception:  # noqa: BLE001 - revert must not take the
            # watchdog down
            logger.exception("policy: %s revert failed", rule)
            return
        cause_id = (record or {}).get("id")
        FLIGHT.record("policy", "revert", rule=rule, cause_id=cause_id,
                      detail="rule cleared; remediation reverted")

    # -- outcome verification -------------------------------------------
    def poll(self, now: Optional[float] = None) -> int:
        """Classify every due verification (called after each watchdog
        tick, outside its lock; tests pass ``now`` to force due).
        Returns how many outcomes were emitted."""
        if now is None:
            now = time.monotonic()
        with self._lock:
            due = [e for e in self._pending if e["due"] <= now]
            if due:
                self._pending = [e for e in self._pending
                                 if e["due"] > now]
        for entry in due:
            self._verify(entry)
        return len(due)

    def _verify(self, entry: Dict[str, Any]) -> None:
        rule = entry["rule"]
        act = entry["act"]
        dog = entry["dog"]()
        current = None
        if dog is not None:
            with dog._lock:
                rec = dog._active.get(rule)
                current = dict(rec) if rec is not None else None
        if current is None:
            outcome = "resolved"
        else:
            outcome = "persisted"
            sev = RULE_SEVERITY.get(rule)
            base = entry.get("baseline")
            if sev is not None and base is not None:
                cur = current.get(sev[0])
                try:
                    base_f, cur_f = float(base), float(cur)
                    worse = (cur_f - base_f) * sev[1]
                    if abs(base_f) > 0 \
                            and worse > abs(base_f) * WORSE_PCT:
                        outcome = "worsened"
                except (TypeError, ValueError):
                    pass
        act["outcome"] = outcome
        _m_actions.inc(rule=rule, action=act["action"], outcome=outcome)
        FLIGHT.record(
            "policy", "outcome", rule=rule, action=act["action"],
            outcome=outcome, cause_id=act.get("cause_id"),
            action_id=act.get("id"),
            detail=(f"re-sampled {self.verify_s:g}s after the action: "
                    f"rule {outcome}"))
        logger.info("policy: %s %s -> outcome %s", rule, act["action"],
                    outcome)

    # -- read side -------------------------------------------------------
    def recent_actions(self, last: int = 8) -> List[Dict[str, Any]]:
        """Newest-last action records (the `fiber-tpu top` feed rides
        this through monitor_payload)."""
        with self._lock:
            out = [dict(a) for a in self._recent]
        return out[-max(0, int(last)):]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "dry_run": self.dry_run,
                "cooldown_s": self.cooldown_s,
                "verify_s": self.verify_s,
                "rules": (sorted(self._rules_filter)
                          if self._rules_filter is not None else "all"),
                "policies": [
                    {"rule": p.rule, "action": p.action, "knob": p.knob,
                     "cooldown_s": (self.cooldown_s
                                    if p.cooldown_s is None
                                    else p.cooldown_s)}
                    for p in self._policies.values()],
                "recent": [dict(a) for a in self._recent],
                "actions_total": self.actions_total,
                "suppressed_total": self.suppressed_total,
                "pending_verifications": len(self._pending),
            }

    def reset(self) -> None:
        """Revert every applied remediation and drop engine state (test
        isolation: a leaked TX high-water or throttled weight must not
        outlive the test that provoked it). ``WATCHDOG.clear()``
        bypasses the clear-edge hooks, so this is the safety net."""
        with self._lock:
            applied = list(self._applied.values())
            self._applied.clear()
            self._pending.clear()
            self._recent.clear()
            self._last_action.clear()
            self.actions_total = 0
            self.suppressed_total = 0
        for entry in applied:
            try:
                entry["revert"]()
            except Exception:  # noqa: BLE001 - best-effort restore
                logger.exception("policy: reset revert failed")


#: Process-wide engine; configured by telemetry.refresh(), triggered by
#: every AnomalyWatchdog instance's raise/clear edges.
POLICY = PolicyEngine()

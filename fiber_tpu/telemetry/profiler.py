"""Wall-clock sampling profiler (py-spy style, in-process).

A daemon thread wakes ``profiler_hz`` times per second, walks every
thread's current Python frame stack via ``sys._current_frames()``, and
aggregates **collapsed stacks**: ``root;caller;…;leaf -> sample
count``, the flamegraph folded format (Gregg's ``flamegraph.pl``,
speedscope, and Perfetto's flamegraph view all ingest it). Because
sampling reads frames without tracing, the profiled code pays nothing
between samples — at the default-off setting it pays nothing at all,
and `make bench-telemetry`'s profiler arm gates the armed cost ≤ 5%.

Cluster story (docs/observability.md):

* every process runs its own profiler, armed by the ``profiler_hz``
  config knob (shipped to workers in the spawn preparation);
* pool workers drain their folded samples after each chunk and ship
  them on the existing result stream (``("prof", …)`` frames beside
  heartbeats and spans); the master folds them into
  :data:`AGGREGATE`, so ``Pool.profile_dump`` writes a cluster-wide
  profile;
* the host agent's ``profile_dump`` op samples the agent process on
  demand (``TpuBackend.collect_profiles``), and ``fiber-tpu profile
  script.py --out prof.folded`` runs a whole program under the
  profiler.

``fiber-tpu explain`` consumes the folded output: a ``primary=compute``
verdict names the top frames instead of stopping at "compute".
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Stack depth kept per sample (deeper frames are folded into the
#: root-most entry) — bounds folded-key size on pathological recursion.
MAX_STACK_DEPTH = 64

#: Hard cap on distinct collapsed stacks kept per process; beyond it,
#: new stacks fold into one overflow key (same posture as the metrics
#: registry's label bound).
MAX_STACKS = 4096

_OVERFLOW_STACK = "(other stacks)"


def _frame_label(frame) -> str:
    code = frame.f_code
    return (f"{code.co_name} "
            f"({os.path.basename(code.co_filename)}:{code.co_firstlineno})")


def _collapse(frame) -> str:
    """One thread's current stack as ``root;…;leaf``."""
    parts: List[str] = []
    while frame is not None and len(parts) < MAX_STACK_DEPTH:
        parts.append(_frame_label(frame))
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


class SamplingProfiler:
    """Aggregating wall-clock sampler for THIS process's threads."""

    def __init__(self, hz: float = 0.0) -> None:
        self.hz = float(hz)
        self._lock = threading.Lock()
        self._folded: Dict[str, int] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.samples = 0        # lifetime samples taken
        self._skip_threads = {-1}

    @property
    def active(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def set_hz(self, hz: float) -> None:
        """Follow the ``profiler_hz`` knob (telemetry.refresh): > 0
        starts the sampler at that rate, <= 0 stops it. The aggregate
        survives a stop so the operator can still dump it."""
        hz = max(0.0, float(hz))
        if hz == self.hz and (self.active == (hz > 0)):
            return
        self.hz = hz
        if self.active:
            self._stop.set()
            self._thread = None
        if hz > 0:
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name="fiber-profiler", daemon=True)
            self._thread.start()

    def _loop(self) -> None:
        # The sampler must never profile itself: its own thread id is
        # excluded from every frame walk.
        self._skip_threads = {threading.get_ident()}
        period = 1.0 / self.hz if self.hz > 0 else 0.01
        while not self._stop.wait(period):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 - keep sampling
                logger.exception("profiler: sample failed")

    def sample(self) -> None:
        """Take one sample of every thread now."""
        frames = sys._current_frames()
        skip = self._skip_threads
        with self._lock:
            for tid, frame in frames.items():
                if tid in skip:
                    continue
                stack = _collapse(frame)
                if stack not in self._folded \
                        and len(self._folded) >= MAX_STACKS:
                    stack = _OVERFLOW_STACK
                self._folded[stack] = self._folded.get(stack, 0) + 1
            self.samples += 1

    # -- read side -----------------------------------------------------
    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._folded)

    def drain(self) -> Dict[str, int]:
        """Pop the aggregate (worker-side shipping: each ``("prof",…)``
        frame carries only samples the master hasn't seen)."""
        with self._lock:
            out = self._folded
            self._folded = {}
            return out

    def clear(self) -> None:
        with self._lock:
            self._folded.clear()
            self.samples = 0

    def sample_for(self, seconds: float, hz: float = 97.0) -> Dict[str, int]:
        """Blocking bounded burst: sample this process for ``seconds``
        at ``hz`` into a PRIVATE aggregate (the agent's on-demand
        ``profile_dump`` op — it must not disturb the knob-armed
        aggregate)."""
        seconds = min(max(0.0, float(seconds)), 30.0)
        hz = min(max(1.0, float(hz)), 1000.0)
        burst = SamplingProfiler()
        burst._skip_threads = {threading.get_ident()}
        deadline = time.monotonic() + seconds
        period = 1.0 / hz
        while time.monotonic() < deadline:
            burst.sample()
            time.sleep(period)
        return burst.snapshot()


#: Process-wide profiler (armed by ``profiler_hz`` via
#: telemetry.refresh()).
PROFILER = SamplingProfiler()


class ProfileAggregate:
    """Master-side merge of worker-shipped folded profiles, keyed by a
    ``host:pid`` source label so `fiber-tpu top`-style tooling can
    still attribute samples per worker."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: Dict[str, Dict[str, int]] = {}

    def merge(self, source: str, folded: Dict[str, int]) -> None:
        with self._lock:
            slot = self._sources.setdefault(str(source), {})
            for stack, count in folded.items():
                if stack not in slot and len(slot) >= MAX_STACKS:
                    stack = _OVERFLOW_STACK
                slot[stack] = slot.get(stack, 0) + int(count)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {src: dict(folded)
                    for src, folded in self._sources.items()}

    def merged(self) -> Dict[str, int]:
        with self._lock:
            return merge_folded(*self._sources.values())

    def clear(self) -> None:
        with self._lock:
            self._sources.clear()


#: Cluster profile aggregate in the master process (fed by the pool's
#: result loop).
AGGREGATE = ProfileAggregate()


# ---------------------------------------------------------------------------
# Folded-format helpers
# ---------------------------------------------------------------------------


def merge_folded(*folded_dicts: Dict[str, int]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for folded in folded_dicts:
        for stack, count in (folded or {}).items():
            out[stack] = out.get(stack, 0) + int(count)
    return out


def folded_text(folded: Dict[str, int]) -> str:
    """Render ``stack -> count`` as flamegraph folded lines, highest
    count first (``flamegraph.pl prof.folded > prof.svg``)."""
    lines = [f"{stack} {count}" for stack, count in
             sorted(folded.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def parse_folded(text: str) -> Dict[str, int]:
    """Inverse of :func:`folded_text` (tolerates blank lines)."""
    out: Dict[str, int] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        stack, _, count_s = line.rpartition(" ")
        if not stack or not count_s.lstrip("-").isdigit():
            raise ValueError(f"malformed folded line: {line!r}")
        out[stack] = out.get(stack, 0) + int(count_s)
    return out


#: Leaf-frame prefixes that mean "off-CPU, parked in a blocking
#: primitive" (a wall-clock sampler sees every thread, and a process
#: full of heartbeat/transport threads is MOSTLY parked threads). The
#: py-spy posture: idle samples are excluded from hot-frame rankings
#: unless nothing else exists.
IDLE_LEAF_PREFIXES = (
    "wait (threading", "wait (", "select (selectors", "select (",
    "accept (socket", "poll (", "recv (", "recv_into (", "readinto (",
    "sleep (", "channel_recv (", "_recv (", "epoll (",
)


def is_idle_stack(stack: str) -> bool:
    leaf = stack.rsplit(";", 1)[-1]
    return leaf.startswith(IDLE_LEAF_PREFIXES)


def top_frames(folded: Dict[str, int], n: int = 5,
               self_time: bool = True,
               exclude_idle: bool = True) -> List[Tuple[str, int]]:
    """The ``n`` hottest frames. ``self_time=True`` attributes each
    sample to its LEAF frame (where the CPU actually was); False
    attributes to every frame on the stack (inclusive time). Stacks
    parked in blocking primitives are excluded by default (falling
    back to everything when the whole profile is idle) so a compute
    verdict names code, not ``wait (threading.py)``."""
    stacks = dict(folded or {})
    if exclude_idle:
        busy = {s: c for s, c in stacks.items() if not is_idle_stack(s)}
        if busy:
            stacks = busy
    totals: Dict[str, int] = {}
    for stack, count in stacks.items():
        frames = stack.split(";")
        chosen = frames[-1:] if self_time else set(frames)
        for frame in chosen:
            totals[frame] = totals.get(frame, 0) + int(count)
    return sorted(totals.items(), key=lambda kv: (-kv[1], kv[0]))[:n]


def profile_chrome_trace(folded: Dict[str, int],
                         hz: float = 97.0) -> Dict[str, Any]:
    """Folded profile -> a Chrome trace-event flamegraph: the sample
    tree laid out as nested complete events on one synthetic timeline
    where 1 sample = 1/hz seconds (load in Perfetto / chrome://tracing
    next to the span trace)."""
    period_us = 1e6 / max(1.0, float(hz))
    # Build the prefix tree: node = {child_label: [count, children]}.
    root: Dict[str, list] = {}
    for stack, count in (folded or {}).items():
        node = root
        for label in stack.split(";"):
            slot = node.setdefault(label, [0, {}])
            slot[0] += int(count)
            node = slot[1]
    events: List[Dict[str, Any]] = []

    def emit(node: Dict[str, list], ts: float) -> None:
        cursor = ts
        for label in sorted(node):
            count, children = node[label]
            dur = count * period_us
            events.append({
                "name": label, "ph": "X", "ts": cursor, "dur": dur,
                "pid": 1, "tid": 1, "cat": "profile",
                "args": {"samples": count},
            })
            emit(children, cursor)
            cursor += dur

    emit(root, 0.0)
    meta = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
             "args": {"name": "sampling profile (1 sample = "
                               f"{1.0 / max(1.0, float(hz)):.4f}s)"}}]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome_profile(path: str, folded: Dict[str, int],
                         hz: float = 97.0) -> str:
    with open(path, "w") as fh:
        json.dump(profile_chrome_trace(folded, hz), fh)
    return path


def load_folded(path: str) -> Dict[str, int]:
    """Folded profile from a file (the ``explain --profile`` input)."""
    with open(path) as fh:
        return parse_folded(fh.read())

"""Device telemetry plane: visibility into the JAX device boundary
(docs/observability.md "Device telemetry").

The five CPU planes are deeply observable, but the thing this framework
exists to drive — the device plane — was a black box: every
``jax.device_put`` untimed, HBM usage invisible, a recompile storm
indistinguishable from slow compute, MFU only computed inside
``make bench-cluster``. This module is the missing instrument panel:

* **Transfer accounting** — :func:`transfer` wraps the host→device
  boundary (store resolution, serialization deserialize, the device_map
  plan, checkpoint restore) and records per-site
  ``device_transfer_seconds`` / ``device_transfer_bytes`` histograms,
  a tracing span when a trace context is ambient, and a flight event —
  so ``fiber-tpu explain`` can grow a ``transfer`` blame category.
* **Compile observability** — ``jax.monitoring`` event/duration
  listeners (null-safe shim in :mod:`fiber_tpu.utils.jaxcompat` for
  jax versions without it) count compiles and compile seconds, and a
  fingerprint-keyed recompile detector feeds the watchdog's
  ``recompile_storm`` rule: the SAME logical function compiling over
  and over is shape churn, not progress.
* **Device gauges** — per-process HBM ``memory_stats()``
  (bytes_in_use / limit; honestly ``None`` on CPU and older jaxlib),
  live-array count/bytes, pushed into the registry each monitor tick
  so the PR-8 time-series and the ``hbm_fill`` anomaly rule see them.
* **Live MFU** — whenever a device peak resolves
  (:mod:`fiber_tpu.utils.flops`), per-map achieved FLOP/s divide into
  the ``pool_map_mfu`` gauge; CPU runs record ``None`` honestly.

Design constraints, mirrored from the rest of the plane:

* **Near-zero when off** — ``device_telemetry_enabled=False`` (or the
  telemetry master switch) reduces every hook to one attribute check;
  the fully-on cost is gated ≤ 5% by ``make bench-telemetry``'s
  ``device`` arm.
* **Null-safe everywhere** — no probe may *initialize* a jax backend
  (``jax`` absent from ``sys.modules`` means every device field is
  ``None``), and a CPU ``memory_stats()`` returning None/empty records
  ``None`` honestly instead of raising — the bench-cluster MFU
  posture.
* **Picklable snapshots** — :func:`snapshot` is the payload of the
  host agent's ``device_snapshot`` op, ``cluster_devices()`` on both
  backends, the worker's ``("dev", …)`` result-stream frames, and
  ``Pool.device_stats()``.
"""

from __future__ import annotations

import collections
import contextlib
import os
import sys
import threading
import time
from typing import Any, Dict, Iterator, Optional, Tuple

from fiber_tpu import telemetry
from fiber_tpu.telemetry import tracing
from fiber_tpu.telemetry.flightrec import FLIGHT

# Registry twins (docs/observability.md metric catalog). Histograms for
# both axes: the bucket shape answers "are transfers many-small or
# few-huge" and sum/count give the totals the snapshots expose.
_m_transfer_seconds = telemetry.histogram(
    "device_transfer_seconds",
    "Host->device transfer boundary seconds, by site",
    buckets=(0.0001, 0.001, 0.01, 0.1, 1.0, 10.0))
_m_transfer_bytes = telemetry.histogram(
    "device_transfer_bytes",
    "Host->device transfer boundary payload bytes, by site",
    buckets=(1 << 10, 1 << 16, 1 << 20, 1 << 24, 1 << 28))
_m_compiles = telemetry.counter(
    "device_compiles", "XLA compilations observed in this process")
_m_compile_seconds = telemetry.counter(
    "device_compile_seconds", "XLA compilation seconds in this process")
_g_hbm_in_use = telemetry.gauge(
    "device_hbm_bytes_in_use", "HBM bytes in use on the first local device")
_g_hbm_limit = telemetry.gauge(
    "device_hbm_bytes_limit", "HBM byte capacity of the first local device")
_g_live_arrays = telemetry.gauge(
    "device_live_arrays", "Live jax.Array count in this process")
_g_live_array_bytes = telemetry.gauge(
    "device_live_array_bytes", "Live jax.Array bytes in this process")
_g_map_mfu = telemetry.gauge(
    "pool_map_mfu",
    "MFU of the last device map whose device peak resolved")


class DeviceTelemetry:
    """Per-process device-plane aggregate; see module docstring."""

    def __init__(self) -> None:
        self.enabled = True
        self._lock = threading.Lock()
        # site -> [count, seconds, bytes]
        self._transfers: Dict[str, list] = {}
        #: Bumped on every recorded transfer/compile — workers ship a
        #: fresh snapshot on the result stream only when this moved.
        self.revision = 0
        # compile observability
        self._compiles = 0
        self._compile_seconds = 0.0
        self._fingerprints: Dict[str, int] = {}
        self._recompiles: "collections.deque" = collections.deque(
            maxlen=256)  # (mono, fingerprint)
        self.storm_count = 4
        self.storm_window_s = 30.0
        self._listeners_installed = False
        self._monitoring_available: Optional[bool] = None
        # last live-MFU observation (None values are honest nulls)
        self._mfu: Dict[str, Any] = {
            "mfu": None, "flops_per_sec": None, "peak_row": None,
            "items": None, "wall_s": None,
        }
        # last gauge probe (kept so snapshots are cheap + honest)
        self._hbm: Dict[str, Optional[int]] = {
            "bytes_in_use": None, "bytes_limit": None}
        self._live: Dict[str, Optional[int]] = {
            "count": None, "bytes": None}
        # last XLA profiler capture (utils/profiling.trace notes it so
        # trace_dump can merge the device timeline without being told)
        self._xla_trace: Optional[Tuple[str, float, float]] = None

    # -- transfer accounting -------------------------------------------
    @contextlib.contextmanager
    def transfer(self, site: str, nbytes: int = 0) -> Iterator[None]:
        """Time one host→device boundary crossing. Off, the cost is one
        attribute check; on, the observation lands in the registry
        histograms, the flight recorder, and (when a trace context is
        ambient — i.e. inside a traced chunk) a ``device.transfer``
        span so the transfer shows up in the map's timeline."""
        if not self.enabled:
            yield
            return
        span_ctx = (tracing.span("device.transfer", site=site,
                                 bytes=int(nbytes))
                    if tracing.current() is not None
                    else contextlib.nullcontext())
        t0 = time.perf_counter()
        try:
            with span_ctx:
                yield
        finally:
            self.add_transfer(site, time.perf_counter() - t0, nbytes)

    def add_transfer(self, site: str, seconds: float,
                     nbytes: int = 0) -> None:
        """Record one completed transfer (the non-context form)."""
        if not self.enabled:
            return
        nbytes = int(nbytes)
        with self._lock:
            agg = self._transfers.get(site)
            if agg is None:
                agg = self._transfers[site] = [0, 0.0, 0]
            agg[0] += 1
            agg[1] += seconds
            agg[2] += nbytes
            self.revision += 1
        _m_transfer_seconds.observe(seconds, site=site)
        _m_transfer_bytes.observe(float(nbytes), site=site)
        # Accounting plane: the transfer bills the map whose chunk is
        # ambient (the worker's store_resolve path), else overhead. The
        # `ici` site (device-tier placement/fan-out) bills its own field
        # too, so Pool.cost()/explain split blame: bytes that rode the
        # mesh vs bytes that crossed sockets.
        from fiber_tpu.telemetry.accounting import COSTS

        if site == "ici":
            COSTS.bill_ambient(device_transfer_bytes=nbytes,
                               device_transfer_s=seconds,
                               ici_bytes=nbytes)
        else:
            COSTS.bill_ambient(device_transfer_bytes=nbytes,
                               device_transfer_s=seconds)
        if FLIGHT.enabled:
            FLIGHT.record("device", "transfer", site=site,
                          bytes=nbytes, s=round(seconds, 6))

    # -- compile observability -----------------------------------------
    def install_listeners(self) -> bool:
        """Register the jax.monitoring compile listeners (idempotent;
        null-safe: False when the installed jax has no monitoring
        surface — every other signal still works). NEVER imports jax:
        a process that hasn't loaded it (lite pool workers, host
        agents) must not pay a multi-second interpreter tax for
        telemetry — installation is retried from the gauge probe and
        compile notes once jax shows up."""
        if self._listeners_installed:
            return True
        if "jax" not in sys.modules:
            return False  # deferred, not unavailable: retried later
        if self._monitoring_available is False:
            return False
        from fiber_tpu.utils.jaxcompat import register_monitoring_listeners

        ok = register_monitoring_listeners(self._on_jax_event,
                                           self._on_jax_duration)
        self._monitoring_available = ok
        self._listeners_installed = ok
        return ok

    def _on_jax_event(self, event: str, **kwargs: Any) -> None:
        # jax emits many event kinds; only compilation concerns us —
        # and a compilation-CACHE hit/request is precisely not a
        # compilation (counting it would make the healthy cached path
        # look like a storm).
        if "compil" not in event:
            return
        if "cache" in event and "miss" not in event:
            return
        self.note_compile(event)

    def _on_jax_duration(self, event: str, duration: float,
                         **kwargs: Any) -> None:
        if "compil" not in event:
            return
        if not self.enabled:
            return
        with self._lock:
            self._compile_seconds += float(duration)
            self.revision += 1
        _m_compile_seconds.inc(float(duration))
        from fiber_tpu.telemetry.accounting import COSTS

        COSTS.bill_ambient(compile_s=float(duration))

    def note_compile(self, fingerprint: str) -> None:
        """One compilation (or compile-cache miss) of the logical
        program named by ``fingerprint``. The device_map plan calls this
        on every compile-cache miss; the jax.monitoring listener calls
        it with the event key. The same fingerprint recurring inside
        ``storm_window_s`` is the recompile-storm signal."""
        if not self.enabled:
            return
        self.install_listeners()  # a compile implies jax is loaded
        now = time.monotonic()
        with self._lock:
            self._compiles += 1
            self._fingerprints[fingerprint] = \
                self._fingerprints.get(fingerprint, 0) + 1
            if len(self._fingerprints) > 128:
                # Bound the table; a storm is about repeats, not breadth.
                self._fingerprints.pop(next(iter(self._fingerprints)))
            self._recompiles.append((now, fingerprint))
            self.revision += 1
        _m_compiles.inc()
        if FLIGHT.enabled:
            FLIGHT.record("device", "compile",
                          fingerprint=str(fingerprint)[:48],
                          count=self._fingerprints.get(fingerprint, 1))

    def recompile_state(self) -> Dict[str, Any]:
        """The watchdog's per-tick probe: is any single fingerprint
        compiling repeatedly inside the storm window?"""
        cutoff = time.monotonic() - float(self.storm_window_s)
        with self._lock:
            recent: Dict[str, int] = {}
            for mono, fp in self._recompiles:
                if mono >= cutoff:
                    recent[fp] = recent.get(fp, 0) + 1
        if not recent:
            return {"storm": False, "fingerprint": None, "count": 0}
        fp = max(recent, key=recent.get)
        return {"storm": recent[fp] >= int(self.storm_count),
                "fingerprint": fp, "count": recent[fp],
                "window_s": float(self.storm_window_s)}

    # -- device gauges --------------------------------------------------
    def update_gauges(self) -> None:
        """Refresh HBM / live-array gauges (the monitor sampler's
        per-tick probe). Never initializes a jax backend: with jax not
        yet imported every field stays None — honest, not zero."""
        if not self.enabled:
            return
        self.install_listeners()  # retry once jax appears (no-op else)
        hbm = _hbm_stats()
        live = _live_array_stats()
        with self._lock:
            self._hbm = hbm
            self._live = live
        if hbm["bytes_in_use"] is not None:
            _g_hbm_in_use.set(float(hbm["bytes_in_use"]))
        if hbm["bytes_limit"] is not None:
            _g_hbm_limit.set(float(hbm["bytes_limit"]))
        if live["count"] is not None:
            _g_live_arrays.set(float(live["count"]))
            _g_live_array_bytes.set(float(live["bytes"] or 0))

    # -- live MFU -------------------------------------------------------
    def note_map_flops(self, flops: float, wall_s: float,
                       items: int) -> Optional[float]:
        """One device map finished having executed ``flops`` analytic
        FLOPs in ``wall_s``. When the device peak resolves
        (utils/flops.py — real TPU kind, or FIBER_PEAK_FLOPS), the MFU
        lands in the ``pool_map_mfu`` gauge; otherwise the observation
        records ``mfu: None`` honestly (CPU posture). Returns the MFU
        or None."""
        if not self.enabled or wall_s <= 0:
            return None
        from fiber_tpu.utils import flops as flopsmod

        value = None
        fps = float(flops) / wall_s
        peak = {"peak_row": None}
        try:
            devices = _devices()
            if devices:
                value = flopsmod.mfu(fps, devices)
                peak = flopsmod.peak_report(devices)
        except Exception:  # noqa: BLE001 - accounting must not fail maps
            pass
        with self._lock:
            self._mfu = {"mfu": value, "flops_per_sec": fps,
                         "peak_row": peak.get("peak_row"),
                         "items": int(items), "wall_s": round(wall_s, 6)}
            self.revision += 1
        if value is not None:
            _g_map_mfu.set(float(value))
        if FLIGHT.enabled:
            FLIGHT.record("device", "mfu", mfu=value,
                          flops_per_sec=round(fps, 3),
                          peak_row=peak.get("peak_row"))
        return value

    # -- unified timeline ----------------------------------------------
    def note_xla_trace(self, log_dir: str, wall_start: float,
                       mono_start: float) -> None:
        """utils/profiling.trace records where the XLA profiler wrote
        its capture (and the wall clock at trace start), so
        ``Pool.trace_dump`` can merge the device timeline beside the
        host spans without being told the directory."""
        with self._lock:
            self._xla_trace = (str(log_dir), float(wall_start),
                               float(mono_start))

    def last_xla_trace(self) -> Optional[Tuple[str, float, float]]:
        with self._lock:
            return self._xla_trace

    # -- read side ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Picklable per-process device-plane surface (agent
        ``device_snapshot`` op / ``Pool.device_stats()`` / worker
        ``("dev", …)`` frames). Null fields are honest: this process
        has no device runtime, not 'zero bytes of HBM'."""
        with self._lock:
            transfers = {site: {"count": agg[0],
                                "seconds": round(agg[1], 6),
                                "bytes": agg[2]}
                         for site, agg in self._transfers.items()}
            out = {
                "host": tracing.host_id(),
                "pid": os.getpid(),
                "enabled": self.enabled,
                "revision": self.revision,
                "transfers": transfers,
                "transfer_bytes": sum(a[2]
                                      for a in self._transfers.values()),
                "transfer_seconds": round(
                    sum(a[1] for a in self._transfers.values()), 6),
                "compiles": self._compiles,
                "compile_seconds": round(self._compile_seconds, 6),
                "compile_fingerprints": dict(self._fingerprints),
                "hbm": dict(self._hbm),
                "live_arrays": dict(self._live),
                "mfu": dict(self._mfu),
            }
        out["recompile"] = self.recompile_state()
        out["platform"] = _platform()
        out["jax_monitoring"] = bool(self._listeners_installed)
        return out

    def configure(self, cfg) -> None:
        """Follow the config knobs (telemetry.refresh)."""
        self.enabled = bool(cfg.telemetry_enabled) \
            and bool(cfg.device_telemetry_enabled)
        self.storm_count = max(2, int(cfg.anomaly_recompile_count))
        self.storm_window_s = max(1.0,
                                  float(cfg.anomaly_recompile_window_s))
        if self.enabled:
            self.install_listeners()

    def clear(self) -> None:
        with self._lock:
            self._transfers.clear()
            self._compiles = 0
            self._compile_seconds = 0.0
            self._fingerprints.clear()
            self._recompiles.clear()
            self.revision = 0
            self._mfu = {"mfu": None, "flops_per_sec": None,
                         "peak_row": None, "items": None, "wall_s": None}
            self._hbm = {"bytes_in_use": None, "bytes_limit": None}
            self._live = {"count": None, "bytes": None}
            self._xla_trace = None


# ---------------------------------------------------------------------------
# Null-safe device probes (never initialize a backend, never raise)
# ---------------------------------------------------------------------------


def _devices():
    """Local jax devices, or None when jax was never imported here —
    probing must not pay (or trigger) a backend initialization in a
    process that does no device work (host agents, lite workers)."""
    if "jax" not in sys.modules:
        return None
    try:
        import jax

        return jax.local_devices()
    except Exception:  # noqa: BLE001 - no backend is a valid state
        return None


def _platform() -> Optional[str]:
    devices = _devices()
    if not devices:
        return None
    return getattr(devices[0], "platform", None)


def _hbm_stats() -> Dict[str, Optional[int]]:
    """First-local-device memory stats: ``{"bytes_in_use", "bytes_limit"}``,
    both None when unavailable (CPU backends return None or an empty
    dict from ``memory_stats()``; older jaxlib lacks the method)."""
    devices = _devices()
    if not devices:
        return {"bytes_in_use": None, "bytes_limit": None}
    try:
        stats = getattr(devices[0], "memory_stats", lambda: None)()
    except Exception:  # noqa: BLE001 - platform-dependent surface
        stats = None
    if not stats:
        return {"bytes_in_use": None, "bytes_limit": None}
    return {
        "bytes_in_use": _maybe_int(stats.get("bytes_in_use")),
        "bytes_limit": _maybe_int(stats.get("bytes_limit")
                                  or stats.get("bytes_reservable_limit")),
    }


def _maybe_int(value) -> Optional[int]:
    try:
        return int(value) if value is not None else None
    except (TypeError, ValueError):
        return None


def _live_array_stats() -> Dict[str, Optional[int]]:
    if "jax" not in sys.modules:
        return {"count": None, "bytes": None}
    try:
        import jax

        arrays = jax.live_arrays()
        total = 0
        for arr in arrays:
            try:
                total += int(arr.nbytes)
            except Exception:  # noqa: BLE001 - deleted/donated buffers
                continue
        return {"count": len(arrays), "bytes": total}
    except Exception:  # noqa: BLE001
        return {"count": None, "bytes": None}


#: Process-wide device telemetry (knobs follow ``device_telemetry_*``
#: via telemetry.refresh()).
DEVICE = DeviceTelemetry()


def transfer(site: str, nbytes: int = 0):
    """Module-level convenience: ``with device.transfer("dmap", n): …``"""
    return DEVICE.transfer(site, nbytes)


def snapshot() -> Dict[str, Any]:
    return DEVICE.snapshot()

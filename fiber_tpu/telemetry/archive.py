"""Persistent observability archive (docs/observability.md "SLOs and
the archive").

Every observability plane built so far — metrics registry, timeseries
rings, flight recorder, anomaly/policy chains, cost vectors — is
per-process and in-memory: a daemon restart erases all history, and
``fiber-tpu top`` can only show the live instant. The archive is the
durable layer under them: an append-only, time-partitioned store of
JSON-line records under ``<staging>/archive/``, flushed on the monitor
sampler tick (daemon-side) and queryable by time range + label.

On-disk layout, one file per ``archive_segment_s`` window::

    <archive_dir>/seg-<t0>-<pid>.jsonl
        {"kind": "header", "v": 1, "t0": ..., "pid": ...}
        {"kind": "sample", "ts": ..., "tasks_per_s": ..., ...}
        {"kind": "event",  "ts": ..., "plane": "monitor", ...}
        {"kind": "slo_obs", "ts": ..., "tenant": ..., ...}
        {"kind": "cost",   "ts": ..., "job_id": ..., ...}

Design posture, all inherited from the PR-7 ledger:

* **Torn-tail tolerant** — a SIGKILL mid-write leaves at most one
  partial final line per segment; readers skip unparseable lines (and
  count them) instead of dying, so a query never returns a torn
  record.
* **Refuse-newer** — a segment whose header carries a larger
  ``ARCHIVE_VERSION`` is skipped with a warning, never misparsed.
* **Batched durability** — appends are buffered writes; fsync runs at
  most every ``archive_fsync_s`` (bounded loss window, no per-record
  syscall).
* **Bounded** — on every segment roll, segments past
  ``archive_retention_s`` are pruned, then oldest-first until the
  archive fits ``archive_max_mb``.

The writer is process-local and OFF by default: the serve daemon arms
it on startup (:meth:`MetricsArchive.enable`), so the pool workers the
daemon spawns never inherit an archive writer through config adoption.
Segment filenames carry the writer's pid, so a restarted daemon (new
pid) appends beside — never into — its predecessor's segments, and
queries merge both.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from fiber_tpu.utils.logging import get_logger

logger = get_logger()

#: Bumped on any incompatible record-shape change; readers refuse
#: (skip + warn) segments written by a NEWER version — same posture as
#: the ledger's LEDGER_VERSION.
ARCHIVE_VERSION = 1

_SEG_RE = re.compile(r"^seg-(\d+)-(\d+)\.jsonl$")

#: Hard cap on records one query returns (a runaway range must not
#: build an unbounded reply for the serve protocol to pickle).
QUERY_LIMIT = 10000


def default_archive_dir() -> str:
    """``archive_dir`` knob; "" puts it at ``<staging root>/archive``,
    beside ``ledger/``, ``costs/`` and ``serve/``."""
    from fiber_tpu import config as _config
    from fiber_tpu.host_agent import default_staging_root

    cfg_dir = str(_config.get().archive_dir or "")
    return cfg_dir or os.path.join(default_staging_root(), "archive")


class MetricsArchive:
    """Append-only segment writer + time-range reader; see module
    docstring. Thread-safe: appends come from the sampler tick and the
    daemon tick thread, queries from per-connection RPC threads."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.enabled = False
        self._dir: Optional[str] = None
        self.segment_s = 300.0
        self.fsync_s = 0.2
        self.retention_s = 604800.0
        self.max_bytes = 256 << 20
        # live segment state (under _lock)
        self._fh = None
        self._seg_t0 = 0.0
        self._last_fsync = 0.0
        # flight-recorder drain watermark (lifetime accept count)
        self._flight_mark = 0
        # lifetime stats
        self.records_written = 0
        self.segments_rolled = 0
        self.segments_pruned = 0
        self.torn_lines = 0      # unparseable lines skipped by readers
        self.refused_segments = 0  # newer-version segments skipped
        # enable() came from code (the serve daemon), not the knob —
        # configure() must not disarm it on the next refresh.
        self._armed_locally = False

    # -- configuration --------------------------------------------------
    def configure(self, cfg) -> None:
        """Re-read the archive knobs (telemetry.refresh). Arms the
        writer only when the ``archive_enabled`` knob says so; the
        serve daemon arms process-locally via :meth:`enable` instead."""
        self.segment_s = max(1.0, float(cfg.archive_segment_s))
        self.fsync_s = max(0.0, float(cfg.archive_fsync_s))
        self.retention_s = max(1.0, float(cfg.archive_retention_s))
        self.max_bytes = max(1, int(cfg.archive_max_mb)) << 20
        want = bool(cfg.telemetry_enabled) and bool(cfg.archive_enabled)
        if want and not self.enabled:
            self.enable()
        elif not want and self.enabled and not self._armed_locally:
            self.disable()

    def enable(self, directory: Optional[str] = None,
               local: bool = False) -> None:
        """Arm the writer for THIS process (the serve daemon's startup
        call passes ``local=True``; the configure() path rides the
        archive_enabled knob)."""
        with self._lock:
            self._dir = directory or default_archive_dir()
            os.makedirs(self._dir, exist_ok=True)
            self.enabled = True
            if local:
                self._armed_locally = True

    def disable(self) -> None:
        with self._lock:
            self.enabled = False
            self._armed_locally = False
            self._close_segment_locked()

    def directory(self) -> str:
        return self._dir or default_archive_dir()

    # -- write side -----------------------------------------------------
    def append(self, kind: str, rec: Dict[str, Any]) -> bool:
        """Append one record (stamped ``kind`` + ``ts`` when absent).
        Near-zero when disabled: one attribute read + branch."""
        if not self.enabled:
            return False
        rec = dict(rec)
        rec["kind"] = kind
        rec.setdefault("ts", time.time())
        try:
            line = json.dumps(rec, default=str)
        except (TypeError, ValueError):
            return False
        now = time.time()
        with self._lock:
            if not self.enabled:  # disabled while we serialized
                return False
            try:
                fh = self._segment_locked(now)
                fh.write(line + "\n")
                self.records_written += 1
                if now - self._last_fsync >= self.fsync_s:
                    fh.flush()
                    os.fsync(fh.fileno())
                    self._last_fsync = now
            except OSError:
                logger.warning("archive: append failed", exc_info=True)
                return False
        return True

    def on_sample(self, sample: Dict[str, Any]) -> None:
        """Monitor-sampler observer (registered by telemetry.refresh):
        persist the derived sample as one ``sample`` record, then drain
        every flight event recorded since the last tick — anomaly
        raise/clear, policy action/outcome, scheduler decisions — as
        ``event`` records. One tick, one batch, one fsync window."""
        if not self.enabled:
            return
        try:
            numeric = {k: v for k, v in sample.items()
                       if isinstance(v, (int, float))}
            self.append("sample", numeric)
            for ev in self._drain_flight():
                self.append("event", ev)
        except Exception:  # noqa: BLE001 - archiving must not take the
            # sampler thread down
            logger.warning("archive: sample flush failed", exc_info=True)

    def _drain_flight(self) -> List[Dict[str, Any]]:
        """New flight events since the last drain, identified by the
        recorder's lifetime accept count (each event id is
        ``"<pid>-<n>"``). Events evicted by the ring bound before a
        tick are lost to the archive too — the recorder is the bound."""
        from fiber_tpu.telemetry.flightrec import FLIGHT

        mark = self._flight_mark
        self._flight_mark = FLIGHT.recorded
        if FLIGHT.recorded == mark:
            return []
        out = []
        for ev in FLIGHT.snapshot():
            try:
                n = int(str(ev.get("id", "0-0")).rsplit("-", 1)[1])
            except (ValueError, IndexError):
                continue
            if n <= mark:
                continue
            rec = {k: v for k, v in ev.items() if k != "kind"}
            rec["event"] = ev.get("kind")
            out.append(rec)
        return out

    def flush(self) -> None:
        """Force the current segment durable (queries + tests +
        daemon shutdown)."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    os.fsync(self._fh.fileno())
                    self._last_fsync = time.time()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            self._close_segment_locked()

    # -- segment lifecycle (under _lock) --------------------------------
    def _segment_locked(self, now: float):
        if self._fh is not None and now - self._seg_t0 < self.segment_s:
            return self._fh
        self._close_segment_locked()
        self._seg_t0 = now
        # Filenames carry whole-second t0; two rolls inside one second
        # (sub-second segment_s in tests) must not merge into one file,
        # so bump until unused.
        base = int(now)
        path = os.path.join(self.directory(),
                            f"seg-{base}-{os.getpid()}.jsonl")
        while os.path.exists(path):
            base += 1
            path = os.path.join(self.directory(),
                                f"seg-{base}-{os.getpid()}.jsonl")
        self._fh = open(path, "a")
        self._fh.write(json.dumps(
            {"kind": "header", "v": ARCHIVE_VERSION,
             "t0": now, "pid": os.getpid()}) + "\n")
        self.segments_rolled += 1
        self._prune_locked(now)
        return self._fh

    def _close_segment_locked(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _segments(self) -> List[Dict[str, Any]]:
        """Every segment on disk, oldest first: ``{path, t0, pid,
        bytes}``. Shared by pruning and queries; tolerant of foreign
        files in the directory."""
        out = []
        try:
            names = os.listdir(self.directory())
        except OSError:
            return []
        for name in names:
            m = _SEG_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.directory(), name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"path": path, "t0": float(m.group(1)),
                        "pid": int(m.group(2)), "bytes": st.st_size,
                        "mtime": st.st_mtime})
        out.sort(key=lambda s: s["t0"])
        return out

    def _prune_locked(self, now: float) -> None:
        """Retention on roll: drop segments whose window ended past the
        horizon, then oldest-first until under the size cap. The live
        segment is never pruned."""
        live = self._fh.name if self._fh is not None else None
        segs = [s for s in self._segments() if s["path"] != live]
        # Age by mtime (the newest record's append time): filename t0
        # is whole-second and pins only the start of the window.
        doomed = [s for s in segs
                  if s["mtime"] < now - self.retention_s]
        expired = {s["path"] for s in doomed}
        keep = [s for s in segs if s["path"] not in expired]
        total = sum(s["bytes"] for s in keep)
        while keep and total > self.max_bytes:
            victim = keep.pop(0)
            doomed.append(victim)
            total -= victim["bytes"]
        for s in doomed:
            try:
                os.remove(s["path"])
                self.segments_pruned += 1
            except OSError:
                pass

    # -- read side ------------------------------------------------------
    def query(self, metric: str, since: Optional[float] = None,
              until: Optional[float] = None,
              labels: Optional[Dict[str, Any]] = None,
              limit: int = QUERY_LIMIT) -> List[Dict[str, Any]]:
        """Records in ``[since, until]`` (epoch seconds; None = open)
        matching ``metric``, oldest first.

        ``metric`` is either a record kind (``"event"``, ``"slo_obs"``,
        ``"cost"``, ``"sample"`` — full records returned) or a sample
        field (``"tasks_per_s"`` — ``{"ts", "value"}`` points
        returned). ``labels`` restricts to records whose fields equal
        every given item (e.g. ``{"tenant": "alice"}`` or
        ``{"rule": "slo_burn"}``). Torn lines are skipped and counted,
        never returned."""
        self.flush()
        limit = max(1, min(int(limit), QUERY_LIMIT))
        out: List[Dict[str, Any]] = []
        for seg in self._segments():
            # Segment-level skip is an optimization only: a record's ts
            # may trail its append time (slo_obs carries finished_at),
            # so allow one segment window of slack each way — the
            # per-record ts filter in _scan is the source of truth.
            if until is not None and seg["t0"] > until + self.segment_s:
                continue
            if since is not None and seg["mtime"] < since - self.segment_s:
                continue
            out.extend(self._scan(seg["path"], metric, since, until,
                                  labels))
            if len(out) >= limit:
                break
        out.sort(key=lambda r: float(r.get("ts") or 0.0))
        return out[:limit]

    def _scan(self, path: str, metric: str, since, until,
              labels) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            fh = open(path)
        except OSError:
            return out
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # Torn tail (SIGKILL mid-write) or corruption:
                    # skip, count, never die, never return it.
                    self.torn_lines += 1
                    continue
                if not isinstance(rec, dict):
                    self.torn_lines += 1
                    continue
                kind = rec.get("kind")
                if kind == "header":
                    if int(rec.get("v") or 0) > ARCHIVE_VERSION:
                        self.refused_segments += 1
                        logger.warning(
                            "archive: segment %s written by a newer "
                            "version (v%s > v%d); skipping it",
                            os.path.basename(path), rec.get("v"),
                            ARCHIVE_VERSION)
                        break
                    continue
                ts = float(rec.get("ts") or 0.0)
                if since is not None and ts < since:
                    continue
                if until is not None and ts > until:
                    continue
                if labels and any(rec.get(k) != v
                                  for k, v in labels.items()):
                    continue
                if kind == metric:
                    out.append(rec)
                elif kind == "sample" and metric in rec:
                    out.append({"ts": ts, "value": rec[metric]})
        return out

    def stats(self) -> Dict[str, Any]:
        segs = self._segments()
        return {
            "enabled": self.enabled,
            "dir": self.directory(),
            "segments": len(segs),
            "bytes": sum(s["bytes"] for s in segs),
            "records_written": self.records_written,
            "segments_rolled": self.segments_rolled,
            "segments_pruned": self.segments_pruned,
            "torn_lines": self.torn_lines,
            "refused_segments": self.refused_segments,
        }

    def clear(self) -> None:
        """Test isolation: close the live segment and reset counters
        (on-disk segments are the test's tmp dir to manage)."""
        with self._lock:
            self._close_segment_locked()
            self._flight_mark = 0
            self.records_written = 0
            self.segments_rolled = 0
            self.segments_pruned = 0
            self.torn_lines = 0
            self.refused_segments = 0


#: Process-wide archive; knobs follow telemetry.refresh(), the writer
#: arms via the archive_enabled knob or the serve daemon's startup.
ARCHIVE = MetricsArchive()

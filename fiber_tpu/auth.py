"""Data-plane authentication: a mutual HMAC-SHA256 challenge/response run
once per TCP connection, before any data or credit frames.

The host data plane (pool task/result streams, queue devices) carries
pickled payloads, so an unauthenticated peer reaching a bound port would
get arbitrary-code execution in the dialing master/worker (advisor,
round 1 — the reference has the same exposure through nanomsg,
fiber/socket.py, but never deploys multi-host where it bites). Every
connection therefore proves knowledge of the shared cluster key first:

1. acceptor -> dialer:  AUTH frame, 16-byte nonce ``Ns``
2. dialer -> acceptor:  AUTH frame, 16-byte nonce ``Nc``
                        + HMAC(key, "FTC0" || Ns)
3. acceptor -> dialer:  AUTH frame, HMAC(key, "FTS0" || Nc)

Both sides verify with a constant-time compare and close on mismatch.
The same protocol is spoken by the Python endpoints here and the native
C pump/client (_native/pump.cpp). ``FIBER_DATA_AUTH=0`` disables the
handshake (both sides must agree — e.g. fully trusted localhost runs).

The key is the cluster key: FIBER_CLUSTER_KEY, or a well-known default
that is only acceptable on loopback (the host agent refuses non-loopback
binds with the default key).
"""

from __future__ import annotations

import hmac
import hashlib
import os
import socket
from typing import Optional

from fiber_tpu.framing import recv_frame, send_frame

#: Frame-type tag for handshake frames (data = 0x00, credit = 0x01).
T_AUTH = b"\x02"

_NONCE = 16
_DIGEST = 32
_CLIENT_TAG = b"FTC0"
_SERVER_TAG = b"FTS0"
_HANDSHAKE_TIMEOUT = 20.0

DEFAULT_KEY = "fiber-tpu-cluster"


class AuthenticationError(OSError):
    """Peer failed the data-plane handshake."""


def cluster_key() -> bytes:
    """Shared secret for every authenticated plane (agents, managers, and
    the data plane): FIBER_CLUSTER_KEY or the development default. An
    empty value counts as unset — a zero-length key would silently mean
    "auth enabled" to the Python plane but "auth disabled" to the native
    plane (key_len == 0), and would dodge the default-key bind refusals."""
    return (os.environ.get("FIBER_CLUSTER_KEY") or DEFAULT_KEY).encode()


def auth_enabled() -> bool:
    return os.environ.get("FIBER_DATA_AUTH", "1") not in ("0", "false")


def _mac(key: bytes, tag: bytes, nonce: bytes) -> bytes:
    return hmac.new(key, tag + nonce, hashlib.sha256).digest()


def _recv_auth(sock: socket.socket) -> bytes:
    frame = recv_frame(sock)
    if not frame or frame[:1] != T_AUTH:
        raise AuthenticationError("expected auth frame")
    return frame[1:]


def server_handshake(sock: socket.socket, key: Optional[bytes] = None) -> None:
    """Acceptor role. Raises AuthenticationError / OSError on failure; the
    caller closes the socket."""
    key = cluster_key() if key is None else key
    old_timeout = sock.gettimeout()
    sock.settimeout(_HANDSHAKE_TIMEOUT)
    try:
        ns = os.urandom(_NONCE)
        send_frame(sock, ns, prefix=T_AUTH)
        reply = _recv_auth(sock)
        if len(reply) != _NONCE + _DIGEST:
            raise AuthenticationError("malformed auth response")
        nc, digest = reply[:_NONCE], reply[_NONCE:]
        if not hmac.compare_digest(digest, _mac(key, _CLIENT_TAG, ns)):
            raise AuthenticationError("peer failed data-plane auth")
        send_frame(sock, _mac(key, _SERVER_TAG, nc), prefix=T_AUTH)
    finally:
        sock.settimeout(old_timeout)


def client_handshake(sock: socket.socket, key: Optional[bytes] = None) -> None:
    """Dialer role. Raises AuthenticationError / OSError on failure."""
    key = cluster_key() if key is None else key
    old_timeout = sock.gettimeout()
    sock.settimeout(_HANDSHAKE_TIMEOUT)
    try:
        ns = _recv_auth(sock)
        if len(ns) != _NONCE:
            raise AuthenticationError("malformed auth challenge")
        nc = os.urandom(_NONCE)
        send_frame(sock, nc + _mac(key, _CLIENT_TAG, ns), prefix=T_AUTH)
        answer = _recv_auth(sock)
        if not hmac.compare_digest(answer, _mac(key, _SERVER_TAG, nc)):
            raise AuthenticationError("server failed data-plane auth")
    finally:
        sock.settimeout(old_timeout)

"""Distributed pools: ``Pool`` and ``ResilientPool``.

Reference parity: fiber/pool.py (ZPool / ResilientZPool — the reference's
default). Architecture:

* The master binds two transport endpoints: a **task stream** (push
  round-robin for ``Pool``; REQ/REP handout for ``ResilientPool``) and a
  **result stream** (pull, fair-merged).
* Worker processes are fiber_tpu Processes started lazily on first use
  (reference: fiber/pool.py:1118-1137) and maintained by a handler thread
  that joins exited workers and repopulates (fiber/pool.py:975-1082).
* Tasks are chunked (default 32 items — the reference's load-bearing
  constant, fiber/pool.py:1169-1170); in-flight items are capped at 20,000
  (explicit backpressure, fiber/pool.py:904) because the transport won't
  block the way a full nanomsg socket would.
* ``ResilientPool`` additionally keeps a per-worker pending table and
  resubmits a dead worker's outstanding chunks (fiber/pool.py:1490-1659);
  retry is only safe for idempotent task functions.

TPU-native extension: a function marked ``@meta(device=True)`` short-cuts
``map`` onto the on-device ``shard_map`` path (fiber_tpu/parallel) instead
of the host worker path.
"""

from __future__ import annotations

import contextlib
import hashlib
import itertools
from collections import deque
import os
import queue as pyqueue
import sys
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from fiber_tpu import serialization, telemetry
from fiber_tpu.meta import get_meta
from fiber_tpu.sched import Scheduler, local_host_key
from fiber_tpu.store.core import ObjectRef
from fiber_tpu.store.plane import StoreFetchError
from fiber_tpu.telemetry import accounting, tracing
from fiber_tpu.telemetry.accounting import COSTS, CostBudget  # noqa: F401
from fiber_tpu.telemetry.flightrec import FLIGHT
from fiber_tpu.testing import chaos
from fiber_tpu.transport import Endpoint, TransportClosed
from fiber_tpu.utils.logging import get_logger
from fiber_tpu.utils.profiling import global_timer

logger = get_logger()

# Pool task-loop metrics (docs/observability.md). Registry instruments
# are process-global; per-Pool exact counts live on the Pool instance
# (Pool.stats()) so tests and operators can attribute them.
_m_tasks_submitted = telemetry.counter(
    "pool_tasks_submitted", "Task items submitted to host pools")
_m_tasks_completed = telemetry.counter(
    "pool_tasks_completed", "Task results received from workers")
_m_chunks_dispatched = telemetry.counter(
    "pool_chunks_dispatched", "Task chunks handed to workers")
_m_chunks_resubmitted = telemetry.counter(
    "pool_chunks_resubmitted",
    "Chunks requeued after worker death or suspect declaration")
_m_backpressure_waits = telemetry.counter(
    "pool_backpressure_waits",
    "Dispatches that blocked on the MAX_INFLIGHT_TASKS gate")
_m_store_fallbacks = telemetry.counter(
    "pool_store_inline_fallbacks",
    "Chunks resent inline after a worker store-fetch failure")
_g_queue_depth = telemetry.gauge(
    "pool_queue_depth", "Chunks queued for dispatch")
_g_inflight = telemetry.gauge(
    "pool_inflight_tasks", "Task items submitted but not yet completed")
_m_stream_admit_waits = telemetry.counter(
    "pool_stream_admit_waits",
    "Stream admission park episodes (consumer slower than producer)")
_g_stream_window_fill = telemetry.gauge(
    "pool_stream_window_fill",
    "Admitted-but-unyielded task items across active streams")

DEFAULT_CHUNKSIZE = 32
MAX_INFLIGHT_TASKS = 20000
# Smallest shared array the device map lifts onto the mesh as a
# broadcast arg (docs/objectstore.md "Device tier"): under this, the
# stack-and-shard path is cheaper than content-addressing.
_DEVICE_BCAST_MIN = 64 << 10

#: Process-wide map-id source for accounting billing keys: unique per
#: submitted map across every pool in this master process.
_MAP_IDS = itertools.count(1)

_UNSET = object()
#: A result slot whose value has been handed to the consumer. The slot
#: stays occupied (duplicate fills from speculation losers / death
#: resubmits still dedup against it) but the payload reference is gone —
#: the sliding-window release that keeps a streaming master O(window).
_YIELDED = object()

#: Consecutive failed worker starts (with zero live workers and pending
#: work) before the pool gives up and fails the pending maps.
_SPAWN_FAIL_LIMIT = 25


class WorkerStartError(Exception):
    """The backend persistently refused to start pool workers while work
    was pending (e.g. an unsatisfiable resource reservation). Raised so a
    map fails loudly instead of waiting forever for workers that can never
    exist; transient start failures are absorbed and retried as before
    (reference posture: fiber/pool.py:96-104 safe_start)."""


class JobPreemptedError(Exception):
    """The serve tier preempted this map mid-flight (budget enforcement,
    docs/serving.md): its journaled progress is intact in the ledger and
    the job is resumable via ``fiber-tpu resume`` / daemon replay. Raised
    into the map's waiters so a blocked ``pool.map`` call unblocks with a
    recognizable, non-fatal verdict rather than hanging."""


class RemoteError(Exception):
    """An exception raised inside a pool worker, with remote traceback."""

    def __init__(self, exc: BaseException, tb: str) -> None:
        super().__init__(str(exc))
        self.original = exc
        self.remote_traceback = tb

    def __str__(self) -> str:
        return f"{self.original!r}\n\nRemote traceback:\n{self.remote_traceback}"


# ---------------------------------------------------------------------------
# Result bookkeeping (reference: the Inventory, fiber/pool.py:644-728)
# ---------------------------------------------------------------------------


class _Entry:
    __slots__ = ("values", "remaining", "total", "callbacks", "yielded",
                 "stream", "finalized", "bits", "pending")

    def __init__(self, n: int, stream: bool = False) -> None:
        #: Classic entries hold a full slot list (the caller asked for
        #: every result at once). Stream entries instead keep a dedup
        #: BITMAP (1 bit per admitted slot) plus a dict of
        #: filled-but-unyielded values: live payloads stay
        #: O(stream_window) and per-task bookkeeping is ~0.125 bytes —
        #: a million-task stream costs the master ~128KB, not an
        #: O(n) pointer list. That IS the constant-memory claim the
        #: `make bench-stream` RSS gate enforces.
        self.values: List[Any] = [] if stream else [_UNSET] * n
        self.bits: Optional[bytearray] = bytearray() if stream else None
        self.pending: Optional[Dict[int, Any]] = {} if stream else None
        self.remaining = n
        self.total = n
        self.callbacks: List[Callable] = []
        self.yielded = 0
        #: Stream entries grow via extend() and complete only once the
        #: admission loop finalizes them — remaining == 0 alone means
        #: "caught up", not "done".
        self.stream = stream
        self.finalized = not stream

    def done_locked(self) -> bool:
        return self.remaining == 0 and self.finalized

    def filled_locked(self, idx: int) -> bool:
        """Has slot ``idx`` ever filled (yielded or still pending)?"""
        if self.stream:
            return bool((self.bits[idx >> 3] >> (idx & 7)) & 1)
        return self.values[idx] is not _UNSET


class ResultStore:
    """Sequence-keyed store of in-flight map results with ordered and
    unordered iteration.

    Two entry shapes share the bookkeeping: classic map entries are born
    with their full slot count, and *stream* entries (``add_stream``)
    start empty and grow chunk-by-chunk via ``extend`` as the admission
    loop pulls from the caller's iterator — completion requires both
    ``remaining == 0`` and ``finalize()``. Stream iteration releases
    each yielded slot's payload reference immediately (``_YIELDED``
    tombstone), so the store holds O(un-yielded window) payloads, never
    O(stream length); duplicate fills still dedup against tombstones."""

    def __init__(self) -> None:
        self._entries: Dict[int, _Entry] = {}
        self._seq = itertools.count()
        self._cond = threading.Condition()
        self._completion_log: Dict[int, deque] = {}

    def add(self, n: int) -> int:
        seq = next(self._seq)
        with self._cond:
            self._entries[seq] = _Entry(n)
            self._completion_log[seq] = deque()
        return seq

    def add_stream(self) -> int:
        """Open a growable stream entry (zero slots until ``extend``)."""
        seq = next(self._seq)
        with self._cond:
            self._entries[seq] = _Entry(0, stream=True)
            self._completion_log[seq] = deque()
        return seq

    def extend(self, seq: int, n: int) -> int:
        """Grow a stream entry by ``n`` slots; returns the base index of
        the new chunk. Raising the outstanding count needs no notify —
        only downward transitions matter to any waiter's predicate."""
        with self._cond:
            entry = self._entries[seq]
            if not entry.stream or entry.finalized:
                raise ValueError("extend() on a non-stream or finalized seq")
            base = entry.total
            entry.total += n
            entry.remaining += n
            need = (entry.total + 7) >> 3
            if len(entry.bits) < need:
                entry.bits.extend(b"\x00" * (need - len(entry.bits)))
        return base

    def finalize(self, seq: int) -> None:
        """The admission loop exhausted the source iterator: no more
        slots will be added. Completion callbacks fire once every
        admitted slot has also filled."""
        callbacks: List[Callable] = []
        with self._cond:
            entry = self._entries.get(seq)
            if entry is None or entry.finalized:
                return
            entry.finalized = True
            if entry.remaining == 0:
                callbacks = list(entry.callbacks)
            self._cond.notify_all()
        self._drain_callbacks(callbacks)

    def stream_fill_state(self, seq: int) -> Tuple[int, int, bool]:
        """(admitted_total, yielded, finalized) for window accounting."""
        with self._cond:
            entry = self._entries.get(seq)
            if entry is None:
                return (0, 0, True)
            return (entry.total, entry.yielded, entry.finalized)

    def wait_stream_capacity(self, seq: int, max_unyielded: int,
                             timeout: Optional[float] = None) -> bool:
        """Park the admission loop until the consumer has drained the
        window: un-yielded slots (admitted − yielded) <= ``max_unyielded``.
        Rides the store condition — every fill/fail/yield notifies — so
        a slow consumer parks admission with zero busy-wait, which parks
        dispatch, which lets transport credits drain (the end-to-end
        backpressure chain, docs/streaming.md). True when capacity is
        available (or the entry is gone/failed — the caller re-checks)."""
        def _have_room() -> bool:
            entry = self._entries.get(seq)
            if entry is None or entry.done_locked():
                return True
            return (entry.total - entry.yielded) <= max_unyielded
        with self._cond:
            return self._cond.wait_for(_have_room, timeout)

    def fill(self, seq: int, base: int, values: List[Any]) -> int:
        """Fill result slots; duplicates (speculation losers, death
        resubmits) are dropped here. Returns the number of NEWLY filled
        slots — the accounting plane's exactly-once billing gate: a
        task is billed when its slot first fills, so a duplicate
        execution never re-bills it."""
        newly = 0
        with self._cond:
            entry = self._entries.get(seq)
            if entry is None:
                return 0
            if base < 0 or base + len(values) > entry.total:
                raise ValueError(
                    f"result frame out of range: base={base} "
                    f"n={len(values)} total={entry.total}"
                )
            if entry.stream:
                bits = entry.bits
                for offset, value in enumerate(values):
                    idx = base + offset
                    if not (bits[idx >> 3] >> (idx & 7)) & 1:
                        bits[idx >> 3] |= 1 << (idx & 7)
                        entry.pending[idx] = value
                        entry.remaining -= 1
                        newly += 1
                        self._completion_log[seq].append(idx)
            else:
                for offset, value in enumerate(values):
                    idx = base + offset
                    if entry.values[idx] is _UNSET:
                        entry.values[idx] = value
                        entry.remaining -= 1
                        newly += 1
                        self._completion_log[seq].append(idx)
            callbacks = (list(entry.callbacks)
                         if entry.done_locked() else [])
            self._cond.notify_all()
        for cb in callbacks:
            try:
                cb()
            except Exception:
                logger.exception("pool callback failed")
        return newly

    def ready(self, seq: int) -> bool:
        with self._cond:
            entry = self._entries[seq]
            return entry.done_locked()

    def wait(self, seq: int, timeout: Optional[float] = None) -> List[Any]:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._entries[seq].done_locked(), timeout
            )
            if not ok:
                raise TimeoutError("pool result wait timed out")
            return self._pop(seq)

    def _pop(self, seq: int) -> List[Any]:
        entry = self._entries.pop(seq)
        self._completion_log.pop(seq, None)
        return entry.values

    def add_callback(self, seq: int, cb: Callable) -> None:
        with self._cond:
            entry = self._entries.get(seq)
            if entry is None or entry.done_locked():
                fire = True
            else:
                entry.callbacks.append(cb)
                fire = False
        if fire:
            cb()

    def iter_ordered(self, seq: int):
        """Yield results in submission order as they become available.
        Each yielded slot's payload reference is dropped at grab time
        (stream: popped from the pending dict; classic: ``_YIELDED``
        tombstone) and the store condition notified, which is what
        advances a stream's admission window as the ordered head moves.
        The whole contiguous ready run is grabbed under ONE lock
        acquire — per-item lock+notify is measurable at 1M tasks — and
        the local batch is bounded by the un-yielded window, so memory
        stays O(window)."""
        i = 0
        while True:
            batch: List[Any] = []
            with self._cond:
                entry = self._entries.get(seq)
                if entry is None:
                    return
                if i >= entry.total and entry.finalized:
                    self._pop(seq)
                    return

                def _head_ready() -> bool:
                    e = self._entries.get(seq)
                    if e is None:
                        return True
                    if i < e.total:
                        return e.filled_locked(i) and (
                            not e.stream or i in e.pending)
                    return e.finalized  # stream: past the admitted tail
                self._cond.wait_for(_head_ready)
                entry = self._entries.get(seq)
                if entry is None:
                    return
                if i >= entry.total:  # finalized with no more slots
                    self._pop(seq)
                    return
                if entry.stream:
                    pending = entry.pending
                    while i < entry.total and i in pending:
                        batch.append(pending.pop(i))
                        entry.yielded += 1
                        i += 1
                else:
                    vals = entry.values
                    while i < entry.total and vals[i] is not _UNSET:
                        batch.append(vals[i])
                        vals[i] = _YIELDED
                        entry.yielded += 1
                        i += 1
                if batch:
                    self._cond.notify_all()
            for value in batch:
                yield value

    def iter_unordered(self, seq: int):
        """Yield results in completion order. The completion log is a
        deque consumed by popleft, so it too stays O(un-yielded window)
        on a stream; yielded slots release their payload reference at
        grab time like iter_ordered, and the log is drained in one
        batch per lock acquire."""
        while True:
            batch: List[Any] = []
            with self._cond:
                entry = self._entries.get(seq)
                if entry is None:
                    return
                log = self._completion_log.get(seq)
                if not log and entry.yielded >= entry.total \
                        and entry.finalized:
                    self._pop(seq)
                    return

                def _have_result() -> bool:
                    e = self._entries.get(seq)
                    if e is None:
                        return True
                    lg = self._completion_log.get(seq)
                    return bool(lg) or (e.finalized
                                        and e.yielded >= e.total)
                self._cond.wait_for(_have_result)
                entry = self._entries.get(seq)
                log = self._completion_log.get(seq)
                if entry is None:
                    return
                if not log:  # finalized, everything already yielded
                    self._pop(seq)
                    return
                if entry.stream:
                    # Detach the whole log under an O(1) lock hold and
                    # pop the values OUTSIDE the lock: fill() only ever
                    # ADDS distinct keys (dedup rides the bitmap, not
                    # the dict), so per-key dict ops need no lock, and
                    # the result loop's fills never stall behind a
                    # windowful of consumer pops.
                    detached = log
                    self._completion_log[seq] = deque()
                    entry.yielded += len(log)
                    self._cond.notify_all()
                    pending = entry.pending
                else:
                    detached = None
                    vals = entry.values
                    while log:
                        idx = log.popleft()
                        batch.append(vals[idx])
                        vals[idx] = _YIELDED
                        entry.yielded += 1
                    self._cond.notify_all()
            if detached is not None:
                batch = [pending.pop(idx) for idx in detached]
            for value in batch:
                yield value

    def _fail_entry_locked(self, seq: int, entry: "_Entry",
                           exc: BaseException, reason: str,
                           direct: bool) -> List[Callable]:
        """Fail an entry's unset slots (caller holds the lock); returns
        the completion callbacks to fire outside the lock."""
        log = self._completion_log.get(seq)
        if log is None:
            log = self._completion_log[seq] = deque()
        if entry.stream:
            bits = entry.bits
            for i in range(entry.total):
                if not (bits[i >> 3] >> (i & 7)) & 1:
                    bits[i >> 3] |= 1 << (i & 7)
                    entry.pending[i] = _Failure(exc, reason,
                                                direct=direct)
                    log.append(i)  # unblock iter_unordered too
        else:
            for i, v in enumerate(entry.values):
                if v is _UNSET:
                    entry.values[i] = _Failure(exc, reason, direct=direct)
                    log.append(i)  # unblock iter_unordered consumers too
        # A failed stream admits nothing more: finalize it here so
        # iterators terminate after draining the failure markers and the
        # admission loop's capacity wait falls through.
        fresh_fail = entry.remaining > 0 or not entry.finalized
        entry.finalized = True
        if fresh_fail:
            entry.remaining = 0
            # Completion callbacks must fire on failure paths too, or
            # map_async consumers waiting on a callback (rather than
            # .get()) hang through the very failure being surfaced.
            return list(entry.callbacks)
        return []

    @staticmethod
    def _drain_callbacks(callbacks: List[Callable]) -> None:
        for cb in callbacks:
            try:
                cb()
            except Exception:
                logger.exception("pool callback failed")

    def fail(self, seq: int, exc: BaseException,
             reason: str = "dispatch failed", direct: bool = True) -> None:
        """Fail every unset slot of ONE entry (device-dispatch errors);
        fires the entry's completion callbacks."""
        with self._cond:
            entry = self._entries.get(seq)
            if entry is None:
                return
            callbacks = self._fail_entry_locked(seq, entry, exc, reason,
                                                direct)
            self._cond.notify_all()
        self._drain_callbacks(callbacks)

    def _outstanding_locked(self) -> int:
        """Unfilled slots, plus one phantom unit per open (unfinalized)
        stream — a caught-up stream between admissions must still hold
        ``join()``/drain gates open, or the pool would release workers
        mid-stream. The phantom is noise to the 20k-item inflight gate."""
        return (sum(e.remaining for e in self._entries.values())
                + sum(1 for e in self._entries.values()
                      if e.stream and not e.finalized))

    def outstanding(self) -> int:
        with self._cond:
            return self._outstanding_locked()

    def wait_outstanding_below(self, limit: int,
                               timeout: Optional[float] = None) -> bool:
        """Block until the in-flight item count is <= ``limit`` (True)
        or ``timeout`` elapses (False). Rides the store's condition —
        every fill/fail notifies it — so backpressure waits cost no
        idle CPU. Only downward transitions matter to the predicate, so
        submissions (which raise the count without notifying) can't
        strand a waiter on a stale True."""
        with self._cond:
            return self._cond.wait_for(
                lambda: self._outstanding_locked() <= limit,
                timeout,
            )

    def is_done(self, seq: int) -> bool:
        """True when ``seq`` has completed or failed — its chunks are
        dead weight and must not be handed to (or resubmitted at)
        workers. A caught-up but unfinalized stream is NOT done: more
        chunks are coming."""
        with self._cond:
            entry = self._entries.get(seq)
            return entry is None or entry.done_locked()

    def abort_all(self, exc: BaseException,
                  reason: str = "pool terminated",
                  direct: bool = False) -> None:
        """Fail every unset slot with ``exc``. ``direct=True`` raises the
        exception itself from result getters (catchable by its own type)
        instead of wrapping it in RemoteError — for local failures like
        worker-start escalation, which never happened on a remote."""
        callbacks: List[Callable] = []
        with self._cond:
            for seq, entry in self._entries.items():
                callbacks.extend(
                    self._fail_entry_locked(seq, entry, exc, reason,
                                            direct))
            self._cond.notify_all()
        self._drain_callbacks(callbacks)


class _Failure:
    """Marker wrapping a failed result slot. Remote failures re-raise as
    RemoteError (with the remote traceback); local failures
    (``direct=True``) re-raise the original exception so callers can
    catch it by type."""

    __slots__ = ("exc", "tb", "direct")

    def __init__(self, exc: BaseException, tb: str,
                 direct: bool = False) -> None:
        self.exc = exc
        self.tb = tb
        self.direct = direct

    def raise_(self) -> None:
        if self.direct:
            raise self.exc from None
        raise RemoteError(self.exc, self.tb) from None


def _resolve(value: Any) -> Any:
    if isinstance(value, _Failure):
        value.raise_()
    return value


class AsyncResult:
    """Handle returned by apply_async (reference: fiber/pool.py:731-757)."""

    def __init__(self, store: ResultStore, seq: int, single: bool) -> None:
        self._store = store
        self._seq = seq
        self._single = single
        self._value: Any = _UNSET
        # Serializes concurrent fetches (user .get() vs. callback firing):
        # the store entry can only be popped once.
        self._fetch_lock = threading.Lock()

    def _fetch(self, timeout: Optional[float]) -> None:
        with self._fetch_lock:
            if self._value is _UNSET:
                with global_timer.section("pool.result_wait"):
                    self._value = self._store.wait(self._seq, timeout)

    def get(self, timeout: Optional[float] = None) -> Any:
        self._fetch(timeout)
        if self._single:
            return _resolve(self._value[0])
        return [_resolve(v) for v in self._value]

    def wait(self, timeout: Optional[float] = None) -> None:
        try:
            self._fetch(timeout)
        except TimeoutError:
            pass

    def ready(self) -> bool:
        return self._value is not _UNSET or self._store.ready(self._seq)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")
        self._fetch(None)
        values = self._value if not self._single else [self._value[0]]
        return not any(isinstance(v, _Failure) for v in values)


MapResult = AsyncResult


def _register_async_callbacks(store: ResultStore, seq: int,
                              result: AsyncResult,
                              callback: Optional[Callable],
                              error_callback: Optional[Callable]) -> None:
    """Wire multiprocessing-style completion callbacks to a store entry:
    success values go to ``callback``, failures — RemoteError from worker
    code or direct local failures (WorkerStartError, device-dispatch
    errors) — to ``error_callback``. Fires on whichever thread completes
    the entry, never the submitting one."""
    if callback is None and error_callback is None:
        return

    def fire() -> None:
        try:
            value = result.get(0)
        except TimeoutError:
            return  # not actually complete; a later fill refires
        except Exception as err:  # noqa: BLE001
            if error_callback is not None:
                error_callback(err)
            return
        if callback is not None:
            callback(value)

    store.add_callback(seq, fire)


class _ResultIterator:
    """imap iterator: an item whose task raised re-raises RemoteError at
    consumption, and the iterator remains usable for the items after it
    (multiprocessing IMapIterator semantics)."""

    def __init__(self, inner) -> None:
        self._inner = inner

    def __iter__(self) -> "_ResultIterator":
        return self

    def __next__(self) -> Any:
        return _resolve(next(self._inner))


# ---------------------------------------------------------------------------
# By-reference payloads (fiber_tpu/store): args/results above
# store_inline_max travel as ObjectRefs; workers resolve them through the
# per-host store so a broadcast arg crosses the wire once per host, not
# once per task (docs/objectstore.md).
# ---------------------------------------------------------------------------


def _payload_size_hint(obj: Any) -> Optional[int]:
    """Cheap serialized-size estimate, or None when only a real pickle
    can tell. The point is to never pay a probe pickle for the common
    small scalars nor for the numpy/jax arrays whose size is a field
    read; unknown container types fall through to the probe."""
    if obj is None or isinstance(obj, (bool, int, float, complex)):
        return 0
    if isinstance(obj, (bytes, bytearray, memoryview, str)):
        return len(obj)
    try:
        nbytes = getattr(obj, "nbytes", None)  # numpy / jax arrays
        if nbytes is not None:
            return int(nbytes)
    except Exception:  # noqa: BLE001 - exotic objects; just probe
        pass
    return None


def _chunk_spans(n_items: int, chunksize: int) -> List[Tuple[int, int]]:
    """Balanced remainder chunking: split ``n_items`` into
    ``ceil(n/chunksize)`` near-equal spans (sizes differ by at most 1,
    none above ``chunksize``) instead of fixed-size chunks plus one
    small straggler tail. ``chunksize`` keeps its explicit-override
    meaning as the chunk-size CAP; only the remainder is rebalanced —
    an evenly divisible length produces exactly the classic chunks.
    Returns ``[(base, size), ...]``."""
    chunksize = max(1, int(chunksize))
    nchunks = max(1, -(-n_items // chunksize))
    base_size, rem = divmod(n_items, nchunks)
    spans: List[Tuple[int, int]] = []
    offset = 0
    for i in range(nchunks):
        size = base_size + (1 if i < rem else 0)
        spans.append((offset, size))
        offset += size
    return spans


def _chunk_digests(chunk: List[Any]) -> List[str]:
    """Object digests this chunk's items reference (top level or one
    tuple level deep — exactly where the encoder puts refs); the
    scheduler's locality key set."""
    digs: List[str] = []
    for item in chunk:
        if isinstance(item, ObjectRef):
            digs.append(item.digest)
        elif type(item) is tuple:
            digs.extend(e.digest for e in item
                        if isinstance(e, ObjectRef))
    return digs


def _chunk_has_refs(chunk: List[Any]) -> bool:
    for item in chunk:
        if isinstance(item, ObjectRef):
            return True
        if type(item) is tuple and any(
                isinstance(e, ObjectRef) for e in item):
            return True
    return False


def _resolve_item(item: Any, client) -> Any:
    """Replace ObjectRefs (top level, or one tuple level deep — exactly
    where the encoder puts them) with the resolved objects. Raises
    StoreFetchError when a ref cannot be resolved from any tier.
    Device-hinted refs resolve through the store's device tier, so
    co-located workers share one replicated copy per digest."""
    if isinstance(item, ObjectRef):
        return client.resolve(
            item, device=getattr(item, "device_hint", False))
    if type(item) is tuple and any(
            isinstance(e, ObjectRef) for e in item):
        return tuple(
            client.resolve(e, device=getattr(e, "device_hint", False))
            if isinstance(e, ObjectRef) else e
            for e in item)
    return item


def _encode_results(values: List[Any], get_client, store_addr: str,
                    inline_max: int) -> List[Any]:
    """Worker-side result encoding: push results above the threshold to
    the master's store and ship the ref. Every failure falls back to
    inline shipping — the store is an optimization, never a correctness
    dependency."""
    for i, v in enumerate(values):
        if isinstance(v, (_Failure, ObjectRef)):
            continue
        hint = _payload_size_hint(v)
        if hint is not None and hint <= inline_max:
            continue
        try:
            data = serialization.dumps(v)
        except Exception:  # noqa: BLE001 - let the inline path raise it
            continue
        if len(data) <= inline_max:
            continue
        try:
            values[i] = get_client().push(data, store_addr)
        except Exception:  # noqa: BLE001
            logger.warning("store: result push failed; shipping inline",
                           exc_info=True)
    return values


# ---------------------------------------------------------------------------
# Worker side
# ---------------------------------------------------------------------------

_EXIT = ("exit",)
#: Sentinel the task-fetch thread enqueues when its connection died —
#: distinct from a clean exit so the crash surfaces as reason="error".
_FETCH_FAILED = object()


class _FuncCache:
    """Unpickle each shipped function once per worker (functions travel as
    bytes keyed by digest so repeated chunks are cheap)."""

    def __init__(self) -> None:
        self._cache: Dict[bytes, Callable] = {}

    def get(self, digest: bytes, blob: Optional[bytes]) -> Callable:
        fn = self._cache.get(digest)
        if fn is None:
            if blob is None:
                raise RuntimeError("worker missing function blob")
            fn = serialization.loads(blob)
            self._cache[digest] = fn
        return fn


def _run_chunk(fn: Callable, chunk: List[Any], star: bool) -> List[Any]:
    out: List[Any] = []
    for args in chunk:
        try:
            if star:
                out.append(fn(*args))
            else:
                out.append(fn(args))
        except BaseException as exc:  # noqa: BLE001 - shipped to master
            out.append(_Failure(exc, traceback.format_exc()))
    return out


# Exit codes a packed sub-worker uses so the packing parent can tell a
# clean maxtasksperchild recycle (17) and a transport failure (19) apart
# from "the pool is shutting down" (0) and from a crash (anything else).
_SUBWORKER_RECYCLE = 17
_SUBWORKER_XPORT_ERR = 19


def _subworker_main(
    ident: bytes,
    task_addr: str,
    result_addr: str,
    resilient: bool,
    initializer: Optional[Callable],
    initargs: Tuple,
    maxtasksperchild: Optional[int],
    store_addr: Optional[str],
) -> None:
    reason = _pool_worker_core(
        task_addr, result_addr, resilient, initializer, initargs,
        maxtasksperchild, ident=ident, store_addr=store_addr,
    )
    if reason == "recycle":
        sys.exit(_SUBWORKER_RECYCLE)
    if reason == "error":
        # A dropped connection is NOT a drain: the parent must report the
        # ident (its handed-out chunk may be stranded in the pending
        # table) and respawn — exit 0 here would silently eat both.
        sys.exit(_SUBWORKER_XPORT_ERR)


def pool_worker(
    task_addr: str,
    result_addr: str,
    resilient: bool,
    initializer: Optional[Callable],
    initargs: Tuple,
    maxtasksperchild: Optional[int],
    n_local: int = 1,
    ctl_addr: Optional[str] = None,
    store_addr: Optional[str] = None,
    dispatch_mode: str = "direct",
) -> None:
    """Body of one pool worker process. With ``n_local > 1`` the process
    packs that many OS sub-workers, each dialing the master independently
    (reference: fiber/pool.py:144-173 cpu_per_job packing).

    With ``dispatch_mode="hier"`` (resilient packed jobs only) the
    process instead becomes this host's sub-master: it fetches chunk
    RANGES from the master, fans them to local sub-workers, and streams
    results back aggregated (fiber_tpu/sched/hier.py).

    Unlike the reference — where a dead sub-worker's pending chunks
    strand until the WHOLE job exits (job-level ``is_alive`` is the only
    death signal) — the packing parent here monitors each child: a crash
    is reported to the resilient master's dedicated control endpoint as
    a ``("subdead", ident)`` frame (the master resubmits exactly that
    sub-worker's pending chunks) and the child is respawned in place, so
    the job never silently loses capacity. Clean maxtasksperchild
    recycling (exit code ``_SUBWORKER_RECYCLE``) respawns the slot and
    reports ``("subgone", ident)`` so the master can retire the old
    ident's bookkeeping; exit 0 means the pool is draining — no respawn."""
    if n_local > 1:
        if dispatch_mode == "hier" and resilient:
            from fiber_tpu.sched.hier import HostDispatcher

            HostDispatcher(
                task_addr, result_addr, n_local, initializer, initargs,
                maxtasksperchild, store_addr,
            ).run()
            return
        import multiprocessing

        from fiber_tpu.transport.tcp import connect_transport

        ctx = multiprocessing.get_context("fork")

        def spawn(i: int):
            ident = uuid.uuid4().bytes
            c = ctx.Process(
                target=_subworker_main,
                args=(ident, task_addr, result_addr, resilient,
                      initializer, initargs, maxtasksperchild,
                      store_addr),
                name=f"fiber-subworker-{i}",
                daemon=True,
            )
            c.start()
            return ident, c

        def try_report(kind: str, ident: bytes) -> bool:
            # Reports ride the resilient master's DEDICATED control
            # endpoint (ctl_addr; None on the plain pool, which has no
            # pending table to repair). Not the result channel — that
            # would inflate the peer count wait_workers() reads as
            # "workers connected" — and not the REQ/REP task channel,
            # whose single-threaded loop can be parked in its
            # task-handout wait (a deadlock: resubmission needs the
            # report processed, the report waits behind the handout).
            # The credit-based send IS the delivery confirmation (it
            # only completes against a consumer-granted credit); a
            # failed send stays queued and is retried — a lost report
            # must not strand the dead sub-worker's pending chunks
            # forever, because the respawned slot keeps the job alive,
            # so the job-death backstop would never fire.
            try:
                # native=False: only the Python Endpoint honors the send
                # deadline (the C client blocks on the credit wait); a
                # report into a half-dead connection must fail (and be
                # retried) rather than freeze the monitor loop — this is
                # the parent's only thread. Reports are rare and tiny,
                # so the native fast path buys nothing here.
                # retries=0: the transport's connect backoff would turn
                # "master unreachable" into ~1s of doomed redials per
                # attempt on this single-threaded monitor; the 1s tick
                # gate is the retry policy here.
                ep = connect_transport("w", ctl_addr, native=False,
                                       retries=0)
                try:
                    ep.send(serialization.dumps((kind, ident)),
                            timeout=10.0)
                    return True
                finally:
                    ep.close()
            except Exception:
                logger.warning("subworker monitor: %s report failed "
                               "(will retry)", kind)
                return False

        children = {ident: (c, time.monotonic())
                    for ident, c in (spawn(i) for i in range(n_local))}
        draining = False
        fail_streak = 0
        pending_reports: List[Tuple[str, bytes]] = []
        last_report_attempt = 0.0
        while children:
            time.sleep(0.1)
            if pending_reports and ctl_addr \
                    and time.monotonic() - last_report_attempt >= 1.0:
                # Drain until the first failure: successful sends are
                # cheap, so a healthy master absorbs a death burst
                # immediately; with the master unreachable the first
                # attempt fails after its connect timeout and the 1s
                # tick gate keeps the monitor reaping/respawning
                # instead of starving in doomed connect() calls.
                last_report_attempt = time.monotonic()
                while pending_reports and try_report(*pending_reports[0]):
                    pending_reports.pop(0)
            for ident, (c, born) in list(children.items()):
                code = c.exitcode
                if code is None:
                    continue
                del children[ident]
                c.join()
                if code == 0:
                    draining = True  # master released this worker
                    continue
                if ctl_addr:
                    # Clean recycle ("subgone"): master drops the old
                    # ident's bookkeeping. Crash ("subdead"): master
                    # resubmits the ident's pending chunks NOW rather
                    # than when the whole job dies. Under a long master
                    # outage only disposable "subgone" entries (pure
                    # bookkeeping cleanup) are shed; "subdead" reports
                    # are NEVER dropped — a lost one would strand its
                    # ident's pending chunks forever, since the
                    # respawned slot keeps the job (and its death
                    # backstop) alive. Each entry is ~50 bytes, so the
                    # worst case is bounded by the crash count.
                    kind = ("subgone" if code == _SUBWORKER_RECYCLE
                            else "subdead")
                    pending_reports.append((kind, ident))
                    if len(pending_reports) > 512:
                        keep = [r for r in pending_reports
                                if r[0] == "subdead"]
                        pending_reports = keep
                    last_report_attempt = 0.0
                if draining:
                    continue
                if code != _SUBWORKER_RECYCLE:
                    # Exponential backoff on rapid crash loops (failing
                    # initializer, master gone hard): a child that died
                    # within 5s of spawn escalates the delay, a child
                    # that survived longer resets it.
                    if time.monotonic() - born < 5.0:
                        fail_streak += 1
                    else:
                        fail_streak = 0
                    time.sleep(min(0.1 * (2 ** fail_streak), 2.0))
                new_ident, new_c = spawn(len(children))
                children[new_ident] = (new_c, time.monotonic())
        # Final flush so a crash right at drain time still gets
        # reported; stop at the first failure — an unreachable master
        # must not hold the exiting parent for one connect timeout per
        # queued report (job death is the backstop then anyway).
        for kind, ident in pending_reports:
            if not try_report(kind, ident):
                break
        return
    _pool_worker_core(
        task_addr, result_addr, resilient, initializer, initargs,
        maxtasksperchild, store_addr=store_addr,
    )


def _pool_worker_core(
    task_addr: str,
    result_addr: str,
    resilient: bool,
    initializer: Optional[Callable],
    initargs: Tuple,
    maxtasksperchild: Optional[int],
    ident: Optional[bytes] = None,
    store_addr: Optional[str] = None,
) -> str:
    from fiber_tpu import process as fprocess

    if initializer is not None:
        initializer(*initargs)

    ident = ident or uuid.uuid4().bytes
    fiber_pid = fprocess.current_process().pid or os.getpid()
    funcs = _FuncCache()

    if FLIGHT.enabled:
        # Black-box posture (docs/observability.md): a dying worker
        # flushes its flight buffer + stack dump into a postmortem
        # bundle under the staging root — on SIGTERM/SIGABRT via the
        # handler, and on the chaos harness's hard-kill via its
        # pre-exit crash_flush hook.
        from fiber_tpu.telemetry import postmortem

        postmortem.install_crash_handler()

    from fiber_tpu.transport.tcp import connect_transport

    result_ep = connect_transport("w", result_addr)
    if resilient:
        task_ep = connect_transport("req", task_addr)
    else:
        # prefetch=2: the transport pulls the next chunk while the
        # current one computes (one parked frame at most — the plain
        # pool has no resubmission, so the bound stays tight). With
        # maxtasksperchild the window must collapse to pure demand
        # (prefetch=1): a standing window parks one granted chunk in
        # the inbox of a worker that breaks at its task budget, and
        # the plain pool has no pending table to resubmit it — the
        # chunk would be silently lost and map() would hang (advisor,
        # round 3). prefetch=1 grants credit only to a reader blocked
        # in recv(), so a recycle break strands nothing.
        task_ep = connect_transport(
            "r", task_addr,
            prefetch=1 if maxtasksperchild else 2,
        )

    completed_chunks = 0
    reason = "error"
    next_task = None
    heartbeater = None
    # Last device-telemetry revision shipped to the master (list so the
    # per-chunk _ship_device closure can update it).
    dev_shipped = [0]
    # Last accounting-ledger revision shipped (same posture: cumulative
    # ("cost", ...) frames ride the result stream only when this moved).
    cost_shipped = [0]
    # By-reference payloads: the store client is built lazily on the
    # first ref actually seen (most workers in small maps never pay the
    # import), shared across chunks so broadcast args resolve once per
    # worker process. Result-side threshold mirrors the master's config
    # (shipped in the spawn preparation).
    store_client = None
    store_inline_max = 0
    if store_addr:
        from fiber_tpu import config as _wcfg

        _c = _wcfg.get()
        if _c.store_enabled:
            store_inline_max = int(_c.store_inline_max)

    def get_store_client():
        nonlocal store_client
        if store_client is None:
            from fiber_tpu import store as storemod

            store_client = storemod.client()
        return store_client
    if resilient:
        # Health plane: beat on the result stream (the master's result
        # loop already fair-merges it; no extra sockets) so the failure
        # detector can declare this worker dead on silence — a hung
        # host stops beating long before TCP notices. Plain pools skip
        # it: with no pending table there is nothing a declaration
        # could resubmit.
        from fiber_tpu import config as fconfig
        from fiber_tpu.health import Heartbeater

        hb_interval = float(fconfig.get().heartbeat_interval or 0)
        if hb_interval > 0:
            hb_payload = serialization.dumps(("hb", ident))

            def _emit_beat() -> None:
                result_ep.send(hb_payload, timeout=hb_interval)

            heartbeater = Heartbeater(
                _emit_beat, hb_interval, gate=chaos.heartbeats_allowed,
            ).start()
        # Pipelined REQ/REP handout: a fetch thread keeps exactly one
        # chunk staged locally so the ready->task round trip overlaps
        # compute instead of serializing with it (the reference's REQ
        # loop pays the round trip per chunk on the critical path —
        # fiber/pool.py:783-790; this closed most of the measured 10ms
        # overhead gap vs multiprocessing). Strict send/recv alternation
        # is preserved — only this thread touches task_ep. The depth-1
        # queue bounds a dead worker's blast radius to three chunks —
        # computing + queued + one the fetch thread may hold while
        # blocked in put — all tracked in the pending table.
        # With maxtasksperchild the thread stops fetching at the budget,
        # so recycling can never strand a staged chunk.
        next_task = pyqueue.Queue(maxsize=1)
        # Placement identity rides every "ready" frame so the master's
        # scheduler can route ref-bearing chunks to the hosts that
        # already cache their objects (docs/scheduling.md). Backends
        # that pick the host stamp FIBER_HOST_KEY into the job env;
        # local workers share the machine's host id.
        host_key = local_host_key()

        def fetch_loop() -> None:
            fetched = 0
            try:
                while True:
                    task_ep.send(
                        serialization.dumps(
                            ("ready", ident, fiber_pid, host_key))
                    )
                    msg = serialization.loads(task_ep.recv())
                    next_task.put(msg)
                    if msg[0] == "exit":
                        return
                    fetched += 1
                    if maxtasksperchild and fetched >= maxtasksperchild:
                        return
            except BaseException:
                # NOT the clean ("exit",) sentinel: a dropped connection
                # (or any decode failure) must surface as reason="error"
                # so a packed parent reports subdead and the master
                # resubmits this ident's pending chunks — mapping it to
                # "exit" would read as pool drain and silently eat both
                # (see _subworker_main). Broad catch: a dead fetch
                # thread with no sentinel would park the main loop in
                # next_task.get() forever.
                next_task.put(_FETCH_FAILED)

        fetcher = threading.Thread(target=fetch_loop,
                                   name="fiber-task-fetch", daemon=True)
        fetcher.start()
    try:
        while True:
            if resilient:
                msg = next_task.get()
                if msg is _FETCH_FAILED:
                    break  # reason stays "error": crash, not drain
            else:
                msg = serialization.loads(task_ep.recv())
            if msg[0] == "exit":
                reason = "exit"
                break
            # 7-tuple envelopes predate the telemetry plane; the trace
            # context rides as an optional 8th field and the accounting
            # billing key as an optional 9th, so replayed/stored
            # payloads of any shape decode.
            seq, base, digest, blob, chunk, star = msg[1:7]
            tctx = msg[7] if len(msg) > 7 else None
            bkey = (tuple(msg[8]) if len(msg) > 8 and msg[8] is not None
                    else None)
            if FLIGHT.enabled:
                # One event per chunk: the dead-worker bundle must show
                # what the worker was chewing on when it died.
                FLIGHT.record("pool", "chunk", seq=seq, base=base,
                              items=len(chunk))

            def _wspan(name: str, **attrs):
                # Spans only for traced chunks (the master sampled this
                # map): an unsampled map must not fill the ring buffer
                # with spans nobody will ship.
                if tctx is None:
                    return contextlib.nullcontext()
                return tracing.span(name, seq=seq, base=base, **attrs)

            def _ship_spans() -> None:
                if tctx is None:
                    return
                finished = tracing.SPANS.drain()
                if not finished:
                    return
                try:
                    # Spans ride the existing result stream (like the
                    # health plane's heartbeats) — no extra sockets; a
                    # lost spans frame costs observability, never
                    # results.
                    result_ep.send(serialization.dumps(
                        ("spans", ident, finished, bkey)))
                except (TransportClosed, OSError):
                    pass

            def _ship_profile() -> None:
                # Sampling-profiler stacks ride the result stream too
                # (docs/observability.md "Sampling profiler"): drain so
                # each frame carries only samples the master hasn't
                # seen. Unlike spans this is NOT tied to the map's
                # trace sampling — the profiler has its own hz knob.
                from fiber_tpu.telemetry.profiler import PROFILER

                if not PROFILER.active:
                    return
                folded = PROFILER.drain()
                if not folded:
                    return
                try:
                    result_ep.send(serialization.dumps(
                        ("prof", ident,
                         f"{tracing.host_id()}:{fiber_pid}", folded,
                         bkey)))
                except (TransportClosed, OSError):
                    pass

            def _ship_device() -> None:
                # Device-plane counters (transfer accounting, compile
                # observability — docs/observability.md "Device
                # telemetry") ride the result stream like spans and
                # profiles, but as a CUMULATIVE snapshot keyed host:pid
                # (latest wins on the master) — shipped only when the
                # revision moved so idle workers cost nothing.
                from fiber_tpu.telemetry.device import DEVICE

                if not DEVICE.enabled \
                        or DEVICE.revision == dev_shipped[0]:
                    return
                snap = DEVICE.snapshot()
                dev_shipped[0] = snap["revision"]
                try:
                    result_ep.send(serialization.dumps(
                        ("dev", ident,
                         f"{tracing.host_id()}:{fiber_pid}", snap,
                         bkey)))
                except (TransportClosed, OSError):
                    pass

            def _ship_cost() -> None:
                # Accounting plane (docs/observability.md "Resource
                # accounting"): this worker's per-billing-key cost
                # vectors (chunk busy-seconds, store fetches, device
                # transfers) ride the result stream as a CUMULATIVE
                # snapshot keyed host:pid — the device-frame posture:
                # latest wins on the master, shipped only when the
                # ledger revision moved so idle workers cost nothing.
                if not COSTS.enabled \
                        or COSTS.revision == cost_shipped[0]:
                    return
                snap = COSTS.snapshot()
                cost_shipped[0] = snap["revision"]
                try:
                    result_ep.send(serialization.dumps(
                        ("cost", ident,
                         f"{tracing.host_id()}:{fiber_pid}", snap)))
                except (TransportClosed, OSError):
                    pass
            plan = chaos._plan
            if plan is not None:
                # Hang BEFORE compute (the held chunk is what the
                # detector must get resubmitted); kill AFTER a result
                # (so the death strands staged/queued chunks, the
                # resubmission case worth inducing). A slow token turns
                # this worker into a living straggler — heartbeats keep
                # flowing, the scheduler's speculation is what must
                # route around it.
                plan.maybe_hang_worker(completed_chunks)
                plan.maybe_slow_worker(completed_chunks)
            chunk_t0 = time.perf_counter()
            with contextlib.ExitStack() as tstack:
                if tctx is not None:
                    # Adopt the master's trace so every span below
                    # shares its trace id, parented on the map's
                    # serialize span.
                    tstack.enter_context(
                        tracing.trace_context(tctx[0], tctx[1]))
                if bkey is not None and COSTS.enabled:
                    # Ambient billing key for the whole chunk: store
                    # fetches and device transfers inside it bill to
                    # the map that caused them, not to overhead.
                    tstack.enter_context(COSTS.context(bkey))
                if _chunk_has_refs(chunk):
                    try:
                        with _wspan("worker.resolve_refs"), \
                                global_timer.section("pool.store_resolve"):
                            client = get_store_client()
                            chunk = [_resolve_item(it, client)
                                     for it in chunk]
                    except StoreFetchError as err:
                        # Degrade, don't fail: ask the master to resend
                        # this chunk with inline payloads (the store is
                        # an optimization, never a correctness
                        # dependency).
                        logger.warning(
                            "store: fetch failed (%s); requesting inline "
                            "resend of chunk seq=%s base=%s",
                            err, seq, base)
                        result_ep.send(serialization.dumps(
                            ("storemiss", seq, base, len(chunk), ident)))
                        if bkey is not None and COSTS.enabled:
                            # The failed resolve was still work this
                            # map caused; no tasks executed though.
                            COSTS.charge(bkey, cpu_s=(
                                time.perf_counter() - chunk_t0))
                        _ship_spans()
                        _ship_cost()
                        # The handout is consumed even though nothing
                        # ran: the resilient fetch thread budgets
                        # FETCHED chunks (maxtasksperchild), so skipping
                        # this increment would leave the main loop
                        # waiting on a chunk the fetcher will never
                        # deliver.
                        completed_chunks += 1
                        if maxtasksperchild \
                                and completed_chunks >= maxtasksperchild:
                            reason = "recycle"
                            break
                        continue
                fn = funcs.get(digest, blob)
                with _wspan("worker.execute", items=len(chunk)):
                    values = _run_chunk(fn, chunk, star)
                if store_inline_max > 0:
                    with _wspan("worker.encode_results"):
                        values = _encode_results(values, get_store_client,
                                                 store_addr,
                                                 store_inline_max)
            if bkey is not None and COSTS.enabled:
                # Chunk busy-seconds (resolve + execute + encode wall)
                # and executions INCLUDING duplicates — the master's
                # first-fill `tasks` count is the exactly-once side;
                # the difference is the duplicate count.
                COSTS.charge(bkey,
                             cpu_s=time.perf_counter() - chunk_t0,
                             tasks_executed=len(chunk))
            result_ep.send(
                serialization.dumps(("result", seq, base, values, ident))
            )
            _ship_spans()
            _ship_profile()
            _ship_device()
            _ship_cost()
            completed_chunks += 1
            if plan is not None:
                plan.maybe_kill_worker(completed_chunks)
            if maxtasksperchild and completed_chunks >= maxtasksperchild:
                reason = "recycle"
                break
    except (TransportClosed, OSError):
        pass  # master went away; the watchdog handles hard exits
    finally:
        if heartbeater is not None:
            heartbeater.stop()
        task_ep.close()
        result_ep.close()
    return reason


# ---------------------------------------------------------------------------
# Master side
# ---------------------------------------------------------------------------


class Pool:
    """Round-robin push pool (reference ZPool, fiber/pool.py:881-1422)."""

    _resilient = False

    def __init__(
        self,
        processes: Optional[int] = None,
        initializer: Optional[Callable] = None,
        initargs: Tuple = (),
        maxtasksperchild: Optional[int] = None,
    ) -> None:
        from fiber_tpu import config
        from fiber_tpu.backends import get_backend

        cfg = config.get()
        # Config may have changed since import (fiber_tpu.init); the
        # telemetry plane follows the pool's view of it.
        telemetry.refresh()
        #: Per-pool exact counts surfaced by Pool.stats() (the registry
        #: twins aggregate across every pool in the process).
        self._n_submitted = 0
        self._n_completed = 0
        self._n_resubmitted = 0
        #: Latest device-telemetry snapshot per worker (host:pid), from
        #: the ("dev", ...) result-stream frames — Pool.device_stats().
        self._device_workers: Dict[str, dict] = {}
        #: Accounting plane (docs/observability.md "Resource
        #: accounting"): latest cumulative cost snapshot per worker
        #: (host:pid) from ("cost", ...) frames; seq -> billing key for
        #: this pool's in-flight maps; seq -> map-start perf_counter
        #: (wall_s billing); completed billing key -> job_id so a cost
        #: frame landing AFTER the last result still refreshes the
        #: persisted per-job record.
        self._cost_workers: Dict[str, dict] = {}
        self._seq_bill: Dict[int, Tuple[str, str, str]] = {}
        self._map_wall0: Dict[int, float] = {}
        self._job_records: Dict[Tuple[str, str, str], str] = {}
        self._map_budgets: Dict[Tuple[str, str, str], CostBudget] = {}
        #: raw-content digest -> store-space digest for device-map
        #: broadcast args (_device_broadcast_split): repeat generations
        #: skip the serialize copy, paying one zero-copy hash.
        self._bcast_digests: Dict[str, str] = {}
        if processes is None:
            processes = get_backend().default_pool_size()
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._n_workers = processes
        self._initializer = initializer
        self._initargs = initargs
        self._maxtasksperchild = maxtasksperchild
        # Workers are packed cpu_per_job sub-workers per job, the last job
        # taking the remainder (reference: fiber/pool.py:1009-1057).
        self._cpu_per_job = max(1, int(cfg.cpu_per_job))
        # Hierarchical dispatch (docs/architecture.md "Hierarchical
        # dispatch"): with dispatch_mode="hier" each packed job runs a
        # per-host sub-master that fetches whole chunk RANGES (one
        # REQ/REP frame per range) and returns results aggregated, so
        # master frame count scales with hosts instead of workers. Only
        # meaningful on the resilient pool (ranges live in the pending
        # table); packed jobs that lose their sub-master degrade to
        # direct per-worker dispatch on respawn.
        self._dispatch_mode = str(getattr(cfg, "dispatch_mode", "direct"))
        self._range_chunks = max(1, int(getattr(cfg,
                                                "dispatch_range_chunks",
                                                16)))
        self._hier_degraded = False
        from fiber_tpu.health import CircuitBreaker

        #: Health plane (fiber_tpu/health.py). The detector is armed by
        #: ResilientPool only — a plain pool has no pending table, so a
        #: death declaration would have nothing to resubmit. The spawn
        #: breaker gates _maintain_workers: a refusing backend is
        #: retried on exponential backoff instead of every 0.2s tick
        #: (the terminal _SPAWN_FAIL_LIMIT escalation below remains).
        self._detector = None
        self._spawn_key = "spawn"
        self._spawn_breaker = CircuitBreaker(
            fail_threshold=int(cfg.spawn_breaker_threshold),
            base_backoff=float(cfg.spawn_breaker_backoff),
            max_backoff=float(cfg.spawn_breaker_backoff_max),
        )

        ip, _, _ = get_backend().get_listen_addr()
        self._task_ep = Endpoint("rep" if self._resilient else "w")
        self._task_addr = self._task_ep.bind(ip)
        self._result_ep = Endpoint("r")
        self._result_addr = self._result_ep.bind(ip)

        # By-reference data plane (fiber_tpu/store): args/results above
        # store_inline_max ride as ObjectRefs against this process's
        # store server. Failure to bring the store up only costs the
        # optimization — everything ships inline.
        self._store_inline_max = (
            int(cfg.store_inline_max) if cfg.store_enabled else 0
        )
        self._objstore = None
        self._store_server = None
        self._store_addr = None
        if self._store_inline_max > 0:
            try:
                from fiber_tpu import store as storemod

                self._store_server, self._store_addr = \
                    storemod.ensure_server(ip)
                self._objstore = self._store_server.store
            except Exception:  # noqa: BLE001
                logger.warning(
                    "object store unavailable; pool ships payloads "
                    "inline", exc_info=True)
                self._store_inline_max = 0
        #: seq -> (func_digest, func_blob, star, original items): kept
        #: while a ref-bearing map is in flight so a worker that cannot
        #: resolve a ref gets its chunk resent INLINE (storemiss path)
        #: instead of failing tasks.
        self._seq_ctx: Dict[int, Tuple] = {}
        self._seq_ctx_lock = threading.Lock()
        self._store_fallbacks = 0
        #: Durable-map ledger plane (docs/robustness.md): seq -> open
        #: MapLedger for maps submitted with job_id=. The result loop
        #: journals each completed chunk through it; resume restores
        #: journaled chunks without re-execution.
        self._ledgers: Dict[int, Any] = {}
        self._ledger_local = None   # fallback LocalStore when _objstore off
        self._ledger_last: Dict[str, Any] = {}
        self._n_restored = 0
        #: Streaming data plane (docs/streaming.md): seq -> live
        #: admission window in chunks (the policy plane's
        #: shrink_stream_window knob mutates it mid-stream), seq ->
        #: pre-shrink window for the owned revert, (seq, base) ->
        #: (raw chunk items, store digests) — the storemiss-resend
        #: source once the producer iterator has moved past the chunk,
        #: released as each chunk fills so it stays O(window). Stream
        #: seqs in _stream_lazy defer oversized-result resolution to
        #: yield time, so spilled results park in the store's tiers
        #: instead of master RAM.
        self._stream_windows: Dict[int, int] = {}
        self._stream_window_orig: Dict[int, int] = {}
        self._stream_ctx: Dict[Tuple[int, int], Tuple] = {}
        self._stream_lazy: set = set()
        self._stream_admit_waits = 0

        self._store = ResultStore()
        # Scheduler plane (fiber_tpu/sched, docs/scheduling.md): the
        # task queue IS the per-pool scheduler — items stay
        # (payload, (seq, base)) tuples and every existing requeue path
        # (death reclaim, storemiss resend, reply-failure) routes
        # through policy unchanged. Speculation only arms on the
        # resilient pool: it needs the pending table + dedup-on-fill
        # machinery that makes duplicate execution safe.
        #: ident -> host placement key self-reported in "ready" frames.
        self._ident_hosts: Dict[bytes, Optional[str]] = {}
        self._host_suspect_fn = getattr(get_backend(), "host_suspect",
                                        None)
        self._sched = Scheduler(
            n_workers=processes,
            policy=str(cfg.sched_policy),
            locality=bool(cfg.locality_enabled),
            speculation=bool(cfg.speculation_enabled) and self._resilient,
            speculation_quantile=float(cfg.speculation_quantile),
            is_done=self._store.is_done,
            on_new_work=self._on_sched_work,
        )
        self._taskq = self._sched

        self._workers: List = []
        self._workers_lock = threading.Lock()
        self._spawning_slots = 0   # sub-worker slots with spawns in flight
        self._spawn_fail_streak = 0  # consecutive failed worker starts
        self._last_spawn_error: Optional[str] = None
        self._reaped = False       # join() finished reaping; no late adds
        self._closed = False
        self._terminated = False
        self._workers_started = False
        self._pool_meta: Optional[Dict[str, Any]] = None

        # Continuous monitor plane (docs/observability.md): the sampler
        # pulls queue-depth/inflight through this probe each tick so
        # the time-series (and the watchdog's queue-growth rule) never
        # read a stale gauge. Registered unconditionally — with the
        # monitor off the probe list is simply never walked.
        from fiber_tpu.telemetry.timeseries import TIMESERIES

        self._monitor_probe = self._update_monitor_gauges
        TIMESERIES.add_probe(self._monitor_probe)

        # Policy plane (docs/observability.md "Autonomous operations"):
        # registering makes this pool's maps throttleable by billing
        # key when the accounting watchdog raises budget_exceeded.
        # Weak registration — the engine never pins a closed pool.
        try:
            from fiber_tpu.telemetry import policy as policymod

            policymod.register_pool(self)
        except Exception:  # noqa: BLE001 - observability, never fatal
            pass

        self._result_thread = threading.Thread(
            target=self._result_loop, name="fiber-pool-results", daemon=True
        )
        self._result_thread.start()
        self._task_thread = threading.Thread(
            target=self._task_loop, name="fiber-pool-tasks", daemon=True
        )
        self._task_thread.start()
        self._worker_thread: Optional[threading.Thread] = None

    # -- worker management (lazy) -----------------------------------------
    def _ensure_workers(self, func: Callable) -> None:
        hints = {
            k: v for k, v in get_meta(func).items() if k in ("cpu", "mem", "gpu")
        }
        if self._pool_meta is None:
            self._pool_meta = hints
        elif hints and hints != self._pool_meta:
            raise ValueError(
                "all functions used with one Pool must share resource meta "
                f"(pool started with {self._pool_meta}, got {hints})"
            )
        self._start_worker_thread()

    def _start_worker_thread(self) -> None:
        if self._workers_started:
            return
        self._workers_started = True
        self._worker_thread = threading.Thread(
            target=self._worker_loop, name="fiber-pool-workers", daemon=True
        )
        self._worker_thread.start()

    def _spawn_worker(self, n_local: int):
        from fiber_tpu.process import Process

        # Hierarchical dispatch needs a packed resilient job; after a
        # sub-master death the pool degrades new jobs to direct
        # per-worker dispatch (_hier_degraded) — the proven path.
        mode = ("hier" if (self._dispatch_mode == "hier"
                           and n_local > 1
                           and self._resilient
                           and not self._hier_degraded)
                else "direct")
        p = Process(
            target=pool_worker,
            args=(
                self._task_addr,
                self._result_addr,
                self._resilient,
                self._initializer,
                self._initargs,
                self._maxtasksperchild,
                n_local,
                getattr(self, "_ctl_addr", None),
                self._store_addr,
                mode,
            ),
            name=f"PoolWorker-{uuid.uuid4().hex[:8]}",
            daemon=True,
        )
        try:
            p.start()
            p._n_local = n_local
            with self._workers_lock:
                self._spawn_fail_streak = 0
                self._last_spawn_error = None
            self._spawn_breaker.record_success(self._spawn_key)
            return p
        except Exception as exc:
            logger.warning("pool worker start failed; will retry",
                           exc_info=True)
            with self._workers_lock:
                self._spawn_fail_streak += 1
                self._last_spawn_error = f"{type(exc).__name__}: {exc}"
            if self._spawn_breaker.record_failure(self._spawn_key):
                logger.warning(
                    "pool: spawn breaker OPEN for %r after repeated "
                    "start failures; backing off", self._spawn_key)
            return None

    def _worker_loop(self) -> None:
        """Maintain the worker population; reap the dead, start missing
        (reference: fiber/pool.py:975-1082). Keeps running through a
        close() drain so deaths mid-drain are still repaired."""
        while not self._terminated and (
            not self._closed or self._store.outstanding() > 0
        ):
            self._maintain_workers()
            time.sleep(0.2)

    def _draining_done(self) -> bool:
        return self._closed and self._store.outstanding() == 0

    def _maintain_workers(self) -> None:
        with self._workers_lock:
            dead = [p for p in self._workers if p is not None and not p.is_alive()]
            for p in dead:
                self._workers.remove(p)
                self._on_worker_death(p)
            # Sub-worker slots still covered by live jobs (plus spawns in
            # flight); jobs pack cpu_per_job sub-workers each, the last
            # one the remainder.
            covered = (
                sum(getattr(p, "_n_local", 1) for p in self._workers)
                + self._spawning_slots
            )
        missing_subs = self._n_workers - covered
        if missing_subs <= 0:
            return
        # Respawning continues through a close() drain (resubmitted chunks
        # need somewhere to run) and stops only once drained.
        if self._terminated or self._draining_done():
            return
        # Breaker open: the target refused spawns repeatedly — skip this
        # tick instead of hammering it; the open period (exponential
        # backoff + jitter) is the retry schedule. The escalation check
        # below already ran in the tick that opened the breaker, so a
        # ripe streak can never be stranded behind an open breaker.
        if not self._spawn_breaker.allow(self._spawn_key):
            return
        plan = []
        while missing_subs > 0:
            n_local = min(self._cpu_per_job, missing_subs)
            plan.append(n_local)
            missing_subs -= n_local
        # Spawn concurrently: worker launch is ~1s of interpreter boot +
        # handshake each, and serial spawn would put that on the critical
        # path of the first map. Each thread registers (or reaps) its own
        # worker, so a spawn outliving the pacing join below can never
        # leave an untracked live process, and a terminate() that raced
        # the spawn reaps it immediately.
        with self._workers_lock:
            self._spawning_slots += sum(plan)

        def spawn_one(n_local: int) -> None:
            try:
                p = self._spawn_worker(n_local)
            except BaseException:
                p = None
            finally:
                with self._workers_lock:
                    self._spawning_slots -= n_local
            if p is None:
                return
            with self._workers_lock:
                if not self._terminated and not self._reaped:
                    self._workers.append(p)
                    return
            # Stragglers that finished after terminate()/join() reaped the
            # pool are shut down immediately, never left untracked.
            p.terminate()

        threads = [
            threading.Thread(target=spawn_one, args=(n,), daemon=True)
            for n in plan
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        # Escalation: transient start failures are retried forever with
        # live workers still draining the queue, but a backend that has
        # refused EVERY start since the last success — with zero workers
        # alive to make progress — is a permanent condition (bad image,
        # unsatisfiable reservation): fail pending maps loudly rather
        # than hang them. Streak threshold comfortably exceeds the
        # transient-failure fault-injection the suite pins
        # (TimeoutBackend-style: a few failures, then success).
        with self._workers_lock:
            streak = self._spawn_fail_streak
            alive = any(p.is_alive() for p in self._workers)
            last_err = self._last_spawn_error
        if streak >= _SPAWN_FAIL_LIMIT and not alive \
                and self._store.outstanding() > 0:
            logger.error(
                "pool: %d consecutive worker start failures with no live "
                "workers; failing pending work (last error: %s)",
                streak, last_err,
            )
            self._store.abort_all(
                WorkerStartError(
                    f"workers could not be started after {streak} "
                    f"consecutive attempts (last error: {last_err})"
                ),
                reason="worker start failure",
                direct=True,
            )

    def _on_worker_death(self, proc) -> None:
        logger.debug("pool worker %s died", proc.name)

    def resize(self, processes: int) -> int:
        """Retarget the worker count in place — the serve tier's warm
        pool (docs/serving.md) scales one long-lived pool elastically
        instead of paying cold spawn per tenant.

        Scale-UP spawns immediately (and starts the maintain loop if no
        map has run yet, so standby capacity is warm BEFORE the first
        chunk needs it). Scale-DOWN terminates excess workers without
        touching the books: the maintain loop's existing dead-sweep
        observes the exits and runs the normal death path — for the
        resilient pool that reclaims + resubmits anything a victim
        still owed, so callers that scale down under load degrade to a
        resubmit, never a loss (callers are expected to scale down only
        when idle anyway). Returns the new target."""
        target = max(1, int(processes))
        victims = []
        with self._workers_lock:
            self._n_workers = target
            covered = (
                sum(getattr(p, "_n_local", 1) for p in self._workers)
                + self._spawning_slots
            )
            excess = covered - target
            if excess > 0:
                for p in self._workers:
                    if excess <= 0:
                        break
                    n_local = getattr(p, "_n_local", 1)
                    if n_local > excess:
                        continue  # would overshoot below the target
                    victims.append(p)
                    excess -= n_local
        self._sched.set_n_workers(target)
        for p in victims:
            try:
                p.terminate()
            except Exception:  # noqa: BLE001 - already-dead is fine
                pass
        if not self._closed and not self._terminated:
            self._start_worker_thread()
            self._maintain_workers()
        return target

    # -- scheduler plane hooks (fiber_tpu/sched) ---------------------------
    def _on_sched_work(self) -> None:
        """The speculation monitor queued a duplicate: parked requests'
        reservation gates may now clear — nudge the handout loop (same
        posture as the submit/result-side wake twins)."""
        if getattr(self, "_parked_count", 0):
            try:
                self._task_ep.wake()
            except (TransportClosed, OSError):
                pass

    def _suspect_defers(self, ident: bytes) -> bool:
        """Health-plane placement input: True when this requester's host
        is currently suspect (backend failure detector / open spawn
        breaker) AND healthier workers exist AND work is scarce enough
        that giving the suspect host a chunk risks stranding it. With
        chunks plentiful even a suspect host helps; with every host
        suspect, serving beats a placement deadlock."""
        fn = self._host_suspect_fn
        if fn is None:
            return False
        host = self._ident_hosts.get(ident)
        if host is None:
            return False
        try:
            if not fn(host):
                return False
        except Exception:  # noqa: BLE001 - health probe must never wedge
            return False
        if self._taskq.qsize() > self._n_workers:
            return False
        for other_host in self._ident_hosts.values():
            if other_host is None or other_host == host:
                continue
            try:
                if not fn(other_host):
                    FLIGHT.record(
                        "sched", "park", ident=ident.hex()[:8],
                        host=host,
                        reason="host suspect while healthier workers "
                               "exist and work is scarce")
                    return True
            except Exception:  # noqa: BLE001
                continue
        return False

    # -- accounting plane (docs/observability.md "Resource accounting") ----
    def _bill_frame(self, seq: Optional[int], tx: int = 0, rx: int = 0,
                    dispatch_s: float = 0.0,
                    bkey: Optional[Tuple] = None) -> None:
        """Bill one pool frame's wire bytes (payload length -> framing
        wire size) and optional dispatch seconds to its map — by
        ``seq`` (the master's seq -> key table), by an explicit
        worker-tagged ``bkey``, or to the overhead bucket when neither
        attributes it (heartbeats, frames of completed maps). The
        master is the authoritative wire observation point: every pool
        frame crosses its endpoints exactly once."""
        if not COSTS.enabled:
            return
        key = tuple(bkey) if bkey else (
            self._seq_bill.get(seq) if seq is not None else None)
        fields: Dict[str, float] = {}
        if tx:
            fields["wire_tx"] = accounting.wire_size(tx)
        if rx:
            fields["wire_rx"] = accounting.wire_size(rx)
        if dispatch_s:
            fields["dispatch_s"] = dispatch_s
        if fields:
            COSTS.charge(key, **fields)

    # -- task egress -------------------------------------------------------
    def _task_loop(self) -> None:
        """Move tasks from the local queue onto the wire with explicit
        flow control (reference hot loop: fiber/pool.py:952-963)."""
        while True:
            item = self._taskq.get()
            _g_queue_depth.set(self._taskq.qsize())
            if item is None:
                return
            payload, _key = item
            # Backpressure waits on the store's condition (woken by
            # every completion) instead of a 10ms poll; the timeout
            # only bounds how long a terminate() can go unnoticed.
            waited_t0 = None
            while not self._store.wait_outstanding_below(
                    MAX_INFLIGHT_TASKS, timeout=0.5):
                if waited_t0 is None:
                    waited_t0 = time.perf_counter()
                if self._terminated:
                    return
            if waited_t0 is not None:
                _m_backpressure_waits.inc()
                FLIGHT.record(
                    "pool", "backpressure", seq=item[1][0],
                    wait_s=round(time.perf_counter() - waited_t0, 4))
            while True:
                if self._terminated:
                    return
                try:
                    t0 = time.perf_counter()
                    self._task_ep.send(payload, timeout=1.0)
                    # add(), not section(): a timed-out send retry is a
                    # wait for peers, not dispatch cost — only the
                    # successful handout is recorded.
                    global_timer.add("pool.dispatch",
                                     time.perf_counter() - t0)
                    self._bill_frame(item[1][0], tx=len(payload),
                                     dispatch_s=time.perf_counter() - t0)
                    _m_chunks_dispatched.inc()
                    if FLIGHT.enabled:
                        FLIGHT.record("pool", "dispatch",
                                      seq=item[1][0], base=item[1][1])
                    break
                except TimeoutError:
                    continue
                except (TransportClosed, OSError):
                    return

    def _result_loop(self) -> None:
        while True:
            try:
                data = self._result_ep.recv()
            except (TransportClosed, OSError):
                return
            # A malformed frame must not kill the loop — that silently
            # hangs every outstanding .get() (advisor, round 1).
            try:
                with global_timer.section("pool.deserialize"):
                    msg = serialization.loads(data)
                detector = self._detector
                if msg[0] == "hb":
                    if detector is not None:
                        detector.beat(msg[1])
                    # Heartbeats are traffic no map causes: the
                    # explicit overhead bucket.
                    self._bill_frame(None, rx=len(data))
                    continue
                if msg[0] == "spans":
                    # Worker-side trace spans riding the result stream
                    # (same transport posture as heartbeats): fold them
                    # into the master's ring buffer, where trace_dump
                    # assembles the cluster-wide timeline. The optional
                    # 4th field is the causing chunk's billing key.
                    if detector is not None:
                        detector.beat(msg[1])
                    tracing.SPANS.add_all(msg[2])
                    self._bill_frame(None, rx=len(data),
                                     bkey=msg[3] if len(msg) > 3 else None)
                    continue
                if msg[0] == "prof":
                    # Worker-side sampling-profiler stacks (same
                    # posture as spans): merge into the master's
                    # cluster aggregate, keyed by the worker's
                    # host:pid label (Pool.profile_dump renders it).
                    ident, label, folded = msg[1], msg[2], msg[3]
                    if detector is not None:
                        detector.beat(ident)
                    from fiber_tpu.telemetry.profiler import AGGREGATE

                    AGGREGATE.merge(label, folded)
                    self._bill_frame(None, rx=len(data),
                                     bkey=msg[4] if len(msg) > 4 else None)
                    continue
                if msg[0] == "dev":
                    # Worker-side device-telemetry snapshots (transfer
                    # accounting, compiles — docs/observability.md
                    # "Device telemetry"): cumulative per worker, so
                    # latest wins; Pool.device_stats() renders them.
                    ident, label, snap = msg[1], msg[2], msg[3]
                    if detector is not None:
                        detector.beat(ident)
                    self._device_workers[str(label)] = snap
                    self._bill_frame(None, rx=len(data),
                                     bkey=msg[4] if len(msg) > 4 else None)
                    continue
                if msg[0] == "cost":
                    # Worker cost frames (accounting plane): cumulative
                    # per worker, latest wins; Pool.cost() merges them
                    # over the master's own ledger. Their own wire cost
                    # is accounting traffic -> overhead.
                    ident, label, snap = msg[1], msg[2], msg[3]
                    if detector is not None:
                        detector.beat(ident)
                    self._on_cost_frame(str(label), snap)
                    self._bill_frame(None, rx=len(data))
                    continue
                if msg[0] == "storemiss":
                    _, seq, base, n, ident = msg
                    if detector is not None:
                        detector.beat(ident)  # a report proves liveness
                    self._bill_frame(seq, rx=len(data))
                    self._on_store_miss(seq, base, n, ident)
                    continue
                if msg[0] == "fbatch":
                    # Children's per-chunk telemetry ("spans"/"prof"/
                    # "dev"/"cost"), batched by a per-host sub-master so
                    # master ingress scales with hosts rather than
                    # chunks. The outer frame's wire cost bills once as
                    # overhead; the inner messages carried no wire of
                    # their own (billed wire must still equal endpoint
                    # counters for Pool.cost() reconciliation).
                    _, raws, ident = msg
                    if detector is not None:
                        detector.beat(ident)
                    self._bill_frame(None, rx=len(data))
                    for raw in raws:
                        try:
                            inner = serialization.loads(raw)
                            k = inner[0]
                            if k == "spans":
                                tracing.SPANS.add_all(inner[2])
                            elif k == "prof":
                                from fiber_tpu.telemetry.profiler import (
                                    AGGREGATE)

                                AGGREGATE.merge(inner[2], inner[3])
                            elif k == "dev":
                                self._device_workers[str(inner[2])] = (
                                    inner[3])
                            elif k == "cost":
                                self._on_cost_frame(str(inner[2]),
                                                    inner[3])
                        except Exception:
                            logger.exception(
                                "pool: dropping malformed fbatch entry")
                    continue
                if msg[0] == "rbatch":
                    # Aggregated results from a per-host sub-master
                    # (hierarchical dispatch): one frame, many chunks.
                    # Billed ONCE against the first chunk's map — billed
                    # wire must equal actual wire for Pool.cost()
                    # reconciliation.
                    _, entries, ident = msg
                    if detector is not None:
                        detector.beat(ident)
                    self._bill_frame(entries[0][0] if entries else None,
                                     rx=len(data))
                    for seq, base, values in entries:
                        if (seq not in self._stream_lazy
                                and any(isinstance(v, ObjectRef)
                                        for v in values)):
                            with global_timer.section(
                                    "pool.store_resolve"):
                                values = self._resolve_result_refs(
                                    values)
                        self._n_completed += len(values)
                        _m_tasks_completed.inc(len(values))
                        self._on_result(seq, base, values, ident)
                        if self._ledgers:
                            self._journal_chunk(seq, base, values)
                        bill_key = (self._seq_bill.get(seq)
                                    if COSTS.enabled else None)
                        newly = self._store.fill(seq, base, values)
                        if newly and bill_key is not None:
                            COSTS.charge(bill_key, tasks=newly)
                        if self._stream_windows:
                            self._release_stream_chunk(seq, base)
                    _g_inflight.set(self._store.outstanding())
                    continue
                if msg[0] != "result":
                    continue
                _, seq, base, values, ident = msg
                if detector is not None:
                    # Results prove liveness as well as any beat: a
                    # worker mid-long-GIL-hold may miss beats while
                    # still making progress, and progress must never
                    # read as death.
                    detector.beat(ident)
                self._bill_frame(seq, rx=len(data))
                if (seq not in self._stream_lazy
                        and any(isinstance(v, ObjectRef)
                                for v in values)):
                    # Stream seqs without a journal skip the eager
                    # resolve: the refs stay in the store's RAM/disk
                    # tiers (which spill under pressure) and resolve at
                    # YIELD time — incremental result spill, master RAM
                    # stays O(window) even with oversized results.
                    with global_timer.section("pool.store_resolve"):
                        values = self._resolve_result_refs(values)
                self._n_completed += len(values)
                _m_tasks_completed.inc(len(values))
                self._on_result(seq, base, values, ident)
                if self._ledgers:
                    # Durable maps: one buffered append on this hot
                    # loop; the ledger's writer thread owns the
                    # serialize + disk persist + fsync.
                    self._journal_chunk(seq, base, values)
                # Billing key captured BEFORE the fill: the fill that
                # completes the map fires the completion callbacks
                # (which seal and release the key) synchronously, and
                # the final chunk's tasks must still bill.
                bill_key = (self._seq_bill.get(seq) if COSTS.enabled
                            else None)
                newly = self._store.fill(seq, base, values)
                if newly and bill_key is not None:
                    # Exactly-once task billing: the first fill of each
                    # slot bills it; a speculation duplicate or
                    # death/storemiss resubmit fills nothing new and
                    # bills nothing.
                    COSTS.charge(bill_key, tasks=newly)
                if self._stream_windows:
                    # A filled stream chunk's raw-items context (and its
                    # encoded-arg store refs) are dead weight: release
                    # now, not at stream end — O(window) master state.
                    self._release_stream_chunk(seq, base)
                _g_inflight.set(self._store.outstanding())
            except Exception:
                logger.exception("pool: dropping malformed result frame")

    def _on_result(self, seq, base, values, ident) -> None:
        pass

    # -- by-reference payloads (fiber_tpu/store) ---------------------------
    def _encode_items(self, items: List[Any], seq_digests: List[str],
                      bkey=None, device_hint: bool = False) -> List[Any]:
        """Replace large args with ObjectRefs (top level and one tuple
        level deep, which covers map-over-tuples and starmap). The memo
        keys on object identity so the classic broadcast pattern — the
        same params object in every item — is hashed and stored ONCE
        per map, not once per task. ``bkey`` bills each stored payload
        to the submitting map (accounting plane); ``device_hint`` makes
        refs SHARED across items (the broadcast idiom, detected via the
        memo) device-destined so resolving workers route them through
        the shared device tier (one H2D per host per digest). Per-item
        payloads never get the hint: mesh-replicating every distinct
        item would cost n_dev x HBM per item and churn the tier's LRU
        out of the actual broadcast params."""
        memo: Dict[int, Tuple[Any, Any]] = {}
        return [self._encode_item(it, memo, seq_digests, bkey,
                                  device_hint)
                for it in items]

    def _encode_item(self, item, memo, seq_digests, bkey=None,
                     device_hint: bool = False):
        if type(item) is tuple:
            return tuple(self._encode_obj(e, memo, seq_digests, bkey,
                                          device_hint)
                         for e in item)
        return self._encode_obj(item, memo, seq_digests, bkey,
                                device_hint)

    def _encode_obj(self, obj, memo, seq_digests, bkey=None,
                    device_hint: bool = False):
        if isinstance(obj, ObjectRef):
            return obj  # user pre-put it; ships as-is
        key = id(obj)
        hit = memo.get(key)
        if hit is not None:
            enc = hit[1]
            if device_hint and isinstance(enc, ObjectRef) \
                    and not enc.device_hint:
                # Second sighting of the same object: this ref is a
                # broadcast shared across items, the only shape worth
                # mesh replication. One shared instance rides every
                # item, so flipping it here marks them all (chunks are
                # serialized after encoding finishes).
                enc.device_hint = True
            return enc
        hint = _payload_size_hint(obj)
        if hint is not None and hint <= self._store_inline_max:
            return obj
        try:
            data = serialization.dumps(obj)
        except Exception:  # noqa: BLE001
            return obj  # let the inline path raise the real error
        if len(data) <= self._store_inline_max:
            memo[key] = (obj, obj)
            return obj
        ref = self._objstore.put_bytes(data, refs=1,
                                       owner=self._store_addr)
        seq_digests.append(ref.digest)
        if bkey is not None:
            COSTS.charge(bkey, store_put_bytes=len(data))
        # The memo holds the original object alive so its id() cannot
        # be recycled mid-encode.
        memo[key] = (obj, ref)
        return ref

    def _arm_store_fallback(self, seq, digest, blob, star, items,
                            seq_digests, tctx, bkey=None) -> None:
        """Keep enough context to resend any chunk inline (storemiss),
        and release the map's store refs when it completes (success,
        failure or abort — completion callbacks fire on all three)."""
        with self._seq_ctx_lock:
            self._seq_ctx[seq] = (digest, blob, star, items, tctx, bkey)
        # The active broadcast is precious while the map is in flight:
        # the replication hook copies it off a suspect host so recovery
        # (and late locality fetches) never need the dead one.
        from fiber_tpu.store.replicate import REPLICATOR

        REPLICATOR.note(seq_digests)

        def _cleanup() -> None:
            with self._seq_ctx_lock:
                self._seq_ctx.pop(seq, None)
            REPLICATOR.forget(seq_digests)
            for d in seq_digests:
                self._objstore.release(d)

        self._store.add_callback(seq, _cleanup)

    def _probe_ref_locations(self, digests: List[str]) -> None:
        """Ask the backend which hosts already cache these objects
        (host-agent ``store_has``, the path ``put_object`` prestages
        through) and feed the scheduler's locality map. Bounded to a
        handful of digests per map and entirely best-effort: a slow or
        dead agent costs the optimization, never the submit."""
        if not self._sched.locality:
            return
        from fiber_tpu.backends import get_backend

        locate = getattr(get_backend(), "locate_object", None)
        if locate is None:
            return
        for dig in list(dict.fromkeys(digests))[:4]:
            try:
                for host in locate(dig):
                    self._sched.note_host_has(host, (dig,))
            except Exception:  # noqa: BLE001 - locality is optional
                return

    def _on_store_miss(self, seq, base, n, ident) -> None:
        """A worker could not resolve this chunk's refs (store down,
        object evicted unspilled, injected chaos): resend the chunk
        with INLINE payloads. Dedup on fill makes double delivery
        harmless; a done map is simply dropped."""
        with self._seq_ctx_lock:
            ctx = self._seq_ctx.get(seq)
        if ctx is None or self._store.is_done(seq):
            return
        fdigest, blob, star, items, tctx, bkey = ctx
        if items is None:
            # Stream: the source iterator moved on long ago; the
            # per-chunk context table holds the only raw-items copy
            # (released when the chunk fills — a filled chunk never
            # storemisses meaningfully, dedup drops the resend).
            with self._seq_ctx_lock:
                sctx = self._stream_ctx.get((seq, base))
            if sctx is None:
                return
            chunk = sctx[0][:n]
        else:
            chunk = items[base:base + n]
        # Same trace context (and billing key) as the original handout:
        # the inline resend is one more hop of the same logical task,
        # not a new trace — and its duplicate wire bytes bill to the
        # map that caused them.
        payload = serialization.dumps(
            ("task", seq, base, fdigest, blob, chunk, star, tctx, bkey)
        )
        self._store_fallbacks += 1
        _m_store_fallbacks.inc()
        FLIGHT.record("store", "storemiss", seq=seq, base=base,
                      ident=ident.hex()[:8],
                      reason="worker could not resolve refs; "
                             "resending inline")
        logger.warning(
            "store: worker %s could not resolve refs (seq=%d base=%d); "
            "resending chunk inline", ident.hex()[:8], seq, base)
        self._taskq.put((payload, (seq, base)))

    def _resolve_result_refs(self, values: List[Any]) -> List[Any]:
        """Master-side resolution of by-reference results: this process
        owns the store the workers pushed to, so resolution is a local
        read + lifecycle release. A missing/corrupt object fails ONLY
        the affected slot, catchably."""
        out = []
        for v in values:
            if not isinstance(v, ObjectRef):
                out.append(v)
                continue
            data = (self._objstore.get_bytes(v.digest)
                    if self._objstore is not None else None)
            if data is None:
                out.append(_Failure(
                    StoreFetchError(
                        f"result object {v.digest[:12]} missing from "
                        "the master store"), "", direct=True))
                continue
            try:
                out.append(serialization.loads(data))
            except Exception as err:  # noqa: BLE001
                out.append(_Failure(err, traceback.format_exc(),
                                    direct=True))
            finally:
                self._objstore.release(v.digest)
        return out

    # -- durable maps (fiber_tpu/store/ledger, docs/robustness.md) ---------
    def _ledger_store(self):
        """Store the journaled result payloads persist into: the pool's
        own object store when the by-reference plane is up, else the
        process LocalStore (its disk tier works regardless — durability
        must not depend on the wire plane being enabled)."""
        if self._objstore is not None:
            return self._objstore
        if self._ledger_local is None:
            from fiber_tpu import store as storemod

            self._ledger_local = storemod.local_store()
        return self._ledger_local

    def _ledger_open(self, job_id: str, func: Callable, items: List[Any],
                     chunksize: int, star: bool,
                     trace_id: Optional[str]):
        """Open (or resume) the job's write-ahead ledger. Returns
        ``(ledger|None, completed, chunksize, trace_id)`` — on resume
        the recorded chunking and trace id override the caller's, so
        chunk spans line up with the journal and resubmitted chunks
        keep their trace (envelope-reuse rule)."""
        from fiber_tpu import config as _config
        from fiber_tpu.store import ledger as ledgermod
        from fiber_tpu.store.replicate import REPLICATOR

        cfg = _config.get()
        if not bool(cfg.ledger_enabled):
            return None, {}, chunksize, trace_id
        path = ledgermod.job_path(job_id)
        tdigest = ledgermod.task_digest(func, len(items), star)
        store = self._ledger_store()
        fsync_s = float(cfg.ledger_fsync_s)

        def note_chunk(digest: str) -> None:
            # Journaled results are PRECIOUS: the replication hook
            # copies them off a suspect host (docs/robustness.md).
            REPLICATOR.note((digest,))

        if os.path.exists(path):
            try:
                header, completed, _done = ledgermod.load(path)
            except ValueError:
                # A crash between file creation and the header fsync
                # leaves a headerless file: nothing was dispatched under
                # it, so the job simply starts fresh (appending — load
                # skips any torn garbage before the new header).
                logger.warning("ledger: %s has no readable header; "
                               "starting job %r fresh", path, job_id)
                header = None
            if header is not None:
                if header.get("task_digest") != tdigest:
                    raise ValueError(
                        f"job_id {job_id!r} was journaled by a "
                        "different task spec (function / item count / "
                        "call shape changed); pick a new job_id or "
                        f"delete {path}")
                chunksize = int(header.get("chunksize") or chunksize)
                led = ledgermod.MapLedger(path, store,
                                          fsync_interval=fsync_s,
                                          on_chunk=note_chunk)
                led.adopt(completed)
                REPLICATOR.note(d for _, d in completed.values())
                if header.get("trace") and trace_id is not None:
                    trace_id = str(header["trace"])
                FLIGHT.record("store", "ledger", job=job_id,
                              event="resume", completed=len(completed))
                return led, completed, chunksize, trace_id
        led = ledgermod.MapLedger(path, store, fsync_interval=fsync_s,
                                  on_chunk=note_chunk)
        spec_digest = None
        try:
            # Resumable spec payload: `fiber-tpu resume <job_id>` runs
            # from a dead master's ledger alone, so the call itself must
            # be reconstructible. The function is cloudpickled BY VALUE:
            # a plain pickle of a `__main__`-defined function is a
            # by-reference pointer only the dead master's re-imported
            # main module could resolve — the resume CLI is a different
            # __main__. Persisted to the disk tier like the chunk
            # payloads; an unpicklable spec only loses the CLI path
            # (re-calling map with the job_id still resumes).
            try:
                import cloudpickle as _cp

                func_blob = _cp.dumps(func)
            except Exception:  # noqa: BLE001 - no cloudpickle / exotic fn
                func_blob = serialization.dumps(func)
            spec_data = serialization.dumps(
                (func_blob, list(items), bool(star), int(chunksize)))
            spec_digest = store.put_bytes(
                spec_data, refs=1, persist=True).digest
        except Exception:  # noqa: BLE001
            logger.warning(
                "ledger: spec payload for job %r not serializable; "
                "`fiber-tpu resume` needs the original call site",
                job_id, exc_info=True)
        led.write_header({
            "job_id": job_id, "task_digest": tdigest,
            "spec": spec_digest, "n_items": len(items),
            "chunksize": int(chunksize), "star": bool(star),
            "trace": trace_id,
        })
        return led, {}, chunksize, trace_id

    def _ledger_restore_all(self, job_id,
                            completed) -> Dict[int, List[Any]]:
        """Fetch every journaled chunk's result values; a payload lost
        from every tier just re-executes its chunk (lineage posture:
        recompute only what was lost)."""
        out: Dict[int, List[Any]] = {}
        for base, (n, digest) in completed.items():
            values = self._ledger_restore(digest, n)
            if values is None:
                logger.warning(
                    "ledger: job %r chunk base=%d payload %s lost from "
                    "every store tier; re-executing that chunk",
                    job_id, base, digest[:12])
                FLIGHT.record("store", "ledger", job=job_id,
                              event="lost", base=base, digest=digest[:8])
                continue
            out[base] = values
        return out

    def _ledger_restore(self, digest: str,
                        n: int) -> Optional[List[Any]]:
        store = self._ledger_store()
        data = store.get_bytes(digest)
        if data is None:
            # Master disk lost the payload (new machine, wiped staging):
            # the per-host caches are the second line — exactly what the
            # suspect-time replication hook keeps populated.
            from fiber_tpu.backends import get_backend

            fetch = getattr(get_backend(), "fetch_object", None)
            if fetch is not None:
                try:
                    data = fetch(digest)
                except Exception:  # noqa: BLE001
                    data = None
            if data is not None:
                try:  # republish so the next resume reads local disk
                    store.put_bytes(data, persist=True, digest=digest)
                except Exception:  # noqa: BLE001
                    pass
        if data is None:
            return None
        try:
            values = serialization.loads(data)
        except Exception:  # noqa: BLE001 - corrupt payload == lost
            return None
        if not isinstance(values, list) or len(values) != n:
            return None
        return values

    def _journal_chunk(self, seq: int, base: int,
                       values: List[Any]) -> None:
        led = self._ledgers.get(seq)
        if led is None or led.has(base):
            return
        if any(isinstance(v, _Failure) for v in values):
            # Failed slots are not completions: the chunk re-executes on
            # resume (idempotent tasks; a deterministic failure simply
            # fails again, visibly).
            return
        led.record_chunk(base, len(values), values)

    def _ledger_done(self, seq: int) -> None:
        """Map completion: close the journal with a ``done`` record and
        release the job's precious-digest registrations."""
        led = self._ledgers.pop(seq, None)
        if led is None:
            return
        from fiber_tpu.store.replicate import REPLICATOR

        led.record_done()
        led.close()
        REPLICATOR.forget(led.digests)

    def ledger_stats(self) -> Dict[str, Any]:
        """Durability counters: the last job_id map's restore/pending
        split (the exactly-once proof surface — restored + executed ==
        total), lifetime restored-task count, and the replication
        registry snapshot."""
        from fiber_tpu.store.replicate import REPLICATOR

        out = dict(self._ledger_last)
        out["tasks_restored_total"] = self._n_restored
        out["active_ledgers"] = len(self._ledgers)
        out["replication"] = REPLICATOR.snapshot()
        return out

    def put_object(self, obj: Any) -> ObjectRef:
        """Explicitly stage one object in the pool's store and get the
        ref back: pass it (alone, or inside arg tuples) to any map/apply
        and workers resolve it through the per-host cache. For payloads
        the automatic threshold already catches this is redundant — it
        exists for pinning very hot broadcasts across many maps without
        re-probing, and for sub-threshold objects you still want
        deduplicated. Held for the pool's lifetime (spilled, not
        dropped, under memory pressure)."""
        if self._objstore is None:
            raise ValueError(
                "object store is disabled (store_enabled=False or "
                "store_inline_max=0)")
        return self._objstore.put(obj, refs=1, owner=self._store_addr)

    def store_stats(self) -> Dict[str, Any]:
        """Operator counters for the by-reference plane (exposed next to
        the backend's host_health): hit/miss/bytes from this process's
        store server plus the pool's inline-fallback count."""
        out: Dict[str, Any] = {
            "enabled": self._objstore is not None,
            "inline_fallbacks": self._store_fallbacks,
        }
        if self._store_server is not None:
            out.update(self._store_server.stats())
        return out

    # -- accounting plane read side ----------------------------------------
    def _on_cost_frame(self, label: str, snap: dict) -> None:
        """One worker's cumulative cost snapshot landed: latest wins per
        worker. Budgets re-check with the worker-observed fields merged
        in (cpu_s lives only on workers), and persisted per-job records
        of already-completed jobs the frame touches are refreshed — the
        final chunk's cost frame always lands AFTER the last result."""
        self._cost_workers[label] = snap
        if not COSTS.enabled:
            return
        workers = accounting.merge_worker_costs(self._cost_workers)
        for kstr in (snap.get("costs") or {}):
            key = accounting.parse_key(kstr)
            if key[2] == "overhead":
                continue
            COSTS.check_budget(key, extra=workers.get(kstr))
            job_id = self._job_records.get(key)
            if job_id is not None:
                accounting.write_job_record(job_id,
                                            self._cost_report_for(key))

    def _cost_report_for(self, key) -> Dict[str, Any]:
        kstr = accounting.key_str(key)
        workers = accounting.merge_worker_costs(self._cost_workers)
        return accounting.build_report(
            key, COSTS.vector(key), workers.get(kstr, {}),
            self._map_budgets.get(tuple(key)))

    def _finish_billing(self, seq: int, job_id, ledger, budget) -> None:
        """Map completion (success, failure or abort): seal the map's
        cost — wall clock, final ledger disk bytes — release its budget
        state and per-job metric label slots, and persist the per-job
        cost record beside the PR-7 ledger when the map was durable."""
        key = self._seq_bill.pop(seq, None)
        if key is None:
            return
        t0 = self._map_wall0.pop(seq, None)
        if t0 is not None:
            COSTS.charge(key, wall_s=time.perf_counter() - t0)
        if ledger is not None:
            COSTS.charge(key, ledger_bytes=ledger.bytes_written)
        COSTS.release_key(key)
        if job_id is not None:
            # Remembered (bounded) so a cost frame landing after the
            # last result still refreshes the record (_on_cost_frame).
            self._job_records[key] = job_id
            while len(self._job_records) > 16:
                self._job_records.pop(next(iter(self._job_records)))
            accounting.write_job_record(job_id,
                                        self._cost_report_for(key))

    def throttle_billing_key(self, key, factor: float = 4.0) -> int:
        """Cut the WDRR weight of every in-flight map billed to
        ``key`` (a (tenant, job, map) tuple — the policy plane's
        budget_exceeded remediation). The maps keep progressing at the
        scheduler's 0.25 weight floor; they just stop crowding out
        in-budget tenants. Returns how many maps were throttled."""
        key = tuple(key)
        seqs = [seq for seq, bk in list(self._seq_bill.items())
                if bk == key]
        return sum(1 for seq in seqs
                   if self._sched.throttle_map(seq, factor))

    def unthrottle_billing_key(self, key) -> int:
        """Restore the original weights (budget anomaly's clear-edge
        revert). Maps that completed meanwhile already restored via
        release_map; this covers the ones still running."""
        key = tuple(key)
        seqs = [seq for seq, bk in list(self._seq_bill.items())
                if bk == key]
        return sum(1 for seq in seqs
                   if self._sched.unthrottle_map(seq))

    def preempt_map(self, seq: int) -> bool:
        """Stop one in-flight map NOW, keeping it resumable (the serve
        tier's budget-enforcement escalation past WDRR throttling,
        docs/serving.md). Order matters:

        1. pop the ledger and close it WITHOUT a ``done`` record — the
           journal keeps every chunk completed so far, and the missing
           ``done`` is exactly what makes ``fiber-tpu resume`` (and the
           serve daemon's replay) pick the job back up;
        2. fail the map's unset slots with :class:`JobPreemptedError` —
           the completion callbacks this fires do the actual reclaim:
           ``release_map`` drops the map's queued AND in-flight chunks
           from the scheduler (late results for a released seq are
           already ignored), ``_ledger_done`` no-ops (ledger popped in
           step 1), ``_finish_billing`` seals and persists the cost
           record so the tenant is billed for what actually ran.

        Returns False when ``seq`` already completed (nothing to do)."""
        if self._store.is_done(seq):
            return False
        led = self._ledgers.pop(seq, None)
        if led is not None:
            from fiber_tpu.store.replicate import REPLICATOR

            led.close()
            REPLICATOR.forget(led.digests)
        self._store.fail(
            seq,
            JobPreemptedError(
                f"map seq={seq} preempted by the serve tier "
                "(budget enforcement); journaled progress kept — "
                "resumable via `fiber-tpu resume`"),
            reason="preempted", direct=True)
        return True

    def preempt_billing_key(self, key) -> int:
        """Preempt every in-flight map billed to ``key`` (a
        ``(tenant, job, map)`` tuple). Returns how many maps were
        actually stopped."""
        key = tuple(key)
        seqs = [seq for seq, bk in list(self._seq_bill.items())
                if bk == key]
        return sum(1 for seq in seqs if self.preempt_map(seq))

    def preempt_job(self, job_id: str) -> int:
        """Preempt every in-flight map billed to ``job_id`` regardless
        of tenant/map component. Returns how many maps were stopped."""
        seqs = [seq for seq, bk in list(self._seq_bill.items())
                if len(bk) >= 2 and bk[1] == job_id]
        return sum(1 for seq in seqs if self.preempt_map(seq))

    def cost(self, job_id: Optional[str] = None) -> Dict[str, Any]:
        """Per-map/per-tenant CostReports (docs/observability.md
        "Resource accounting"): the process cost ledger's keys merged
        with every worker's shipped cost frames, each field taken from
        its authoritative observation point (wire/tasks: master;
        cpu/store-fetch/device-transfer: workers). ``job_id=`` filters
        to that job's maps and adds an aggregated ``job`` summary.
        The ``overhead`` buckets (master and workers) are explicit —
        per-key wire bytes + overhead always sum to ``totals``."""
        snap = COSTS.snapshot()
        workers = accounting.merge_worker_costs(self._cost_workers)
        over_str = accounting.key_str(accounting.OVERHEAD_KEY)
        reports = []
        for kstr in sorted(snap["costs"]):
            key = accounting.parse_key(kstr)
            if key[2] == "overhead":
                continue
            if job_id is not None and key[1] != job_id:
                continue
            reports.append(accounting.build_report(
                key, snap["costs"][kstr], workers.get(kstr, {}),
                self._map_budgets.get(key)))
        out: Dict[str, Any] = {
            "reports": reports,
            "overhead": dict(snap["costs"].get(over_str) or {}),
            "worker_overhead": dict(workers.get(over_str) or {}),
            "totals": COSTS.totals(),
            "cost_workers": len(self._cost_workers),
            # Exact framing-boundary counters of this pool's endpoints:
            # billed wire (per-key + overhead) reconciles against these
            # — the remainder is credit/flow-control traffic the pool
            # layer never sees, reported here instead of silently
            # dropped.
            "transport": {
                "task_ep": {"bytes_tx": self._task_ep.bytes_tx,
                            "bytes_rx": self._task_ep.bytes_rx},
                "result_ep": {"bytes_tx": self._result_ep.bytes_tx,
                              "bytes_rx": self._result_ep.bytes_rx},
            },
        }
        if job_id is not None:
            job_total: Dict[str, float] = {}
            for rep in reports:
                for field, n in rep["total"].items():
                    job_total[field] = job_total.get(field, 0.0) + n
            out["job"] = {"job_id": job_id, "maps": len(reports),
                          "total": {k: round(v, 6)
                                    for k, v in sorted(job_total.items())}}
        return out

    # -- telemetry (docs/observability.md) ---------------------------------
    def stats(self) -> Dict[str, Any]:
        """Aggregated pool introspection: the global_timer's ``pool.*``
        sections (count, total_s, mean_s) plus this pool's exact task
        counters — the one timing surface (the same sections also land
        in the registry's ``timer_seconds`` histogram)."""
        return {
            "timers": {name: stat for name, stat
                       in global_timer.stats().items()
                       if name.startswith("pool.")},
            "tasks_submitted": self._n_submitted,
            "tasks_completed": self._n_completed,
            "tasks_restored": self._n_restored,
            "chunks_resubmitted": self._n_resubmitted,
            "store_fallbacks": self._store_fallbacks,
            "stream_admit_waits": self._stream_admit_waits,
            "streams_active": len(self._stream_windows),
            "queue_depth": self._taskq.qsize(),
            "outstanding": self._store.outstanding(),
            "workers": len(self._workers),
            "sched": self._sched.snapshot(),
            # Accounting-plane summary (full reports: Pool.cost()).
            "costs": {
                kstr: {"tasks": vec.get("tasks", 0.0),
                       "wire_tx": vec.get("wire_tx", 0.0),
                       "wire_rx": vec.get("wire_rx", 0.0)}
                for kstr, vec in COSTS.snapshot()["costs"].items()
            } if COSTS.enabled else {},
        }

    def metrics(self) -> Dict[str, dict]:
        """Snapshot of the process metrics registry (every plane's
        counters, not just this pool's) — the master-side sibling of the
        host agent's ``telemetry_snapshot`` op."""
        self._update_monitor_gauges()
        return telemetry.REGISTRY.snapshot()

    def _update_monitor_gauges(self) -> None:
        """Push this pool's pull-style state into the registry gauges
        (the monitor sampler's per-tick probe; also run by metrics())."""
        _g_queue_depth.set(self._taskq.qsize())
        _g_inflight.set(self._store.outstanding())
        if self._stream_windows:
            fill = 0
            for seq in list(self._stream_windows):
                total, yielded, _fin = self._store.stream_fill_state(seq)
                fill += max(0, total - yielded)
            _g_stream_window_fill.set(fill)

    def timeseries(self) -> Dict[str, Any]:
        """This process's continuous-monitor surface: the sampled
        time-series rings, the latest derived rates (tasks/s, bytes/s,
        heartbeat age) and the anomaly watchdog's state — the
        master-side sibling of the host agent's ``monitor_snapshot``
        op (docs/observability.md "Continuous monitoring")."""
        from fiber_tpu.telemetry.monitor import monitor_payload
        from fiber_tpu.telemetry.timeseries import TIMESERIES

        self._update_monitor_gauges()
        if TIMESERIES.enabled:
            # Extra-fresh tick (same posture as the agent's
            # monitor_snapshot op): results that landed since the last
            # interval must be in the surface the caller reads NOW.
            TIMESERIES.sample_once()
        return monitor_payload()

    def profiles(self) -> Dict[str, int]:
        """Merged cluster profile (flamegraph folded stacks -> sample
        counts): this process's sampler aggregate plus every profile
        frame the workers shipped back on the result stream. Empty
        unless ``profiler_hz`` > 0 (docs/observability.md "Sampling
        profiler")."""
        from fiber_tpu.telemetry import profiler as profmod

        return profmod.merge_folded(profmod.PROFILER.snapshot(),
                                    profmod.AGGREGATE.merged())

    def profile_dump(self, path: str, chrome: bool = False) -> str:
        """Write the merged cluster profile — flamegraph folded text by
        default (``flamegraph.pl``/speedscope/Perfetto ingest it), or
        the Chrome-trace flame view with ``chrome=True``. Returns
        ``path``."""
        from fiber_tpu.telemetry import profiler as profmod

        folded = self.profiles()
        if chrome:
            from fiber_tpu import config as _cfg

            hz = float(_cfg.get().profiler_hz) or 97.0
            return profmod.write_chrome_profile(path, folded, hz)
        with open(path, "w") as fh:
            fh.write(profmod.folded_text(folded))
        return path

    def device_stats(self) -> Dict[str, Any]:
        """Device telemetry plane surface (docs/observability.md
        "Device telemetry"): per-process transfer bytes+seconds (by
        site), compile count+seconds, recompile state, HBM and
        live-array stats (honest ``None`` on CPU), and the last live
        MFU — for the master, every worker that shipped ``("dev", …)``
        frames on the result stream, and every cluster host (the
        backend's ``cluster_devices`` agent sweep, same host keys as
        ``host_health``/``store_stats``)."""
        from fiber_tpu.backends import get_backend
        from fiber_tpu.telemetry.device import DEVICE

        out: Dict[str, Any] = {
            "master": DEVICE.snapshot(),
            "workers": {k: dict(v)
                        for k, v in self._device_workers.items()},
        }
        cluster = getattr(get_backend(), "cluster_devices", None)
        if cluster is not None:
            try:
                out["hosts"] = cluster()
            except Exception as exc:  # noqa: BLE001 - operator surface
                out["hosts"] = {"error": repr(exc)}
        return out

    def trace_dump(self, path: str,
                   xla_dir: Optional[str] = None) -> str:
        """Write the process span store — master spans plus every worker
        span shipped back on the result stream — as Chrome trace-event
        JSON loadable in Perfetto / chrome://tracing (pid = host,
        tid = worker pid). When an XLA profiler capture exists —
        ``xla_dir=`` names its log directory, or a
        ``utils.profiling.trace`` region ran in this process (the
        device plane notes the newest capture) — its device ops merge
        in beside the host spans on the dual clock
        (docs/observability.md "Unified timeline"). Returns ``path``."""
        from fiber_tpu.telemetry import export
        from fiber_tpu.telemetry.device import DEVICE

        spans = tracing.SPANS.snapshot()
        wall_start = None
        if xla_dir is None:
            noted = DEVICE.last_xla_trace()
            if noted is not None:
                cand_dir, cand_wall, _mono = noted
                # Auto-merge only a capture that OVERLAPS this dump's
                # span window: a profiling.trace region from minutes
                # ago must not glue stale device ops onto an unrelated
                # map's timeline (an explicit xla_dir= always merges).
                t0 = min((float(sp.get("ts", 0.0)) for sp in spans),
                         default=None)
                if t0 is None or cand_wall >= t0 - 60.0:
                    xla_dir, wall_start = cand_dir, cand_wall
        return export.write_chrome_trace(path, spans,
                                         xla_dir=xla_dir,
                                         xla_wall_start=wall_start)

    def flight_dump(self, path: str) -> str:
        """Write this process's flight-recorder buffer (pool submits and
        dispatches, scheduler decisions, store/transport/health
        anomalies) as JSON — the companion artifact ``fiber-tpu
        explain`` joins with the trace. Returns ``path``."""
        import json

        from fiber_tpu.utils.logging import LOG_RING

        with open(path, "w") as fh:
            json.dump({"host": tracing.host_id(), "pid": os.getpid(),
                       "dropped": FLIGHT.dropped,
                       "events": FLIGHT.snapshot(),
                       # Log-ring tail: `fiber-tpu explain --flight`
                       # shows what the process was LOGGING next to the
                       # events it blames (docs/observability.md).
                       "logs": LOG_RING.tail(200)}, fh, default=str)
        return path

    # -- submission --------------------------------------------------------
    def _submit(
        self,
        func: Callable,
        iterable: Iterable[Any],
        chunksize: Optional[int],
        star: bool,
        callback: Optional[Callable] = None,
        error_callback: Optional[Callable] = None,
        single: bool = False,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
        tenant: Optional[str] = None,
    ) -> AsyncResult:
        if self._closed or self._terminated:
            raise ValueError("Pool not running")
        self._ensure_workers(func)
        items = list(iterable)
        seq = self._store.add(len(items))
        result = AsyncResult(self._store, seq, single=single)
        _register_async_callbacks(self._store, seq, result,
                                  callback, error_callback)
        if not items:
            return result
        # Accounting plane (docs/observability.md "Resource
        # accounting"): every map gets a (tenant, job, map) billing key
        # that rides the task envelope tail; the map's serialize /
        # dispatch / wire / fill observations bill to it, workers bill
        # their chunk costs to the same key, and an optional CostBudget
        # raises the budget_exceeded anomaly when crossed.
        mid = next(_MAP_IDS)
        # tenant= overrides the process-wide COSTS.tenant: the serve
        # daemon multiplexes many tenants' jobs through ONE pool, so
        # billing identity must be per-map, not per-process.
        bill_key = (tenant if tenant else COSTS.tenant,
                    job_id if job_id is not None else f"map-{mid}",
                    f"m{mid}")
        if COSTS.enabled:
            self._seq_bill[seq] = bill_key
            self._map_wall0[seq] = time.perf_counter()
            if budget is not None:
                COSTS.set_budget(bill_key, budget)
                self._map_budgets[bill_key] = budget
                while len(self._map_budgets) > 64:
                    self._map_budgets.pop(next(iter(self._map_budgets)))
        elif budget is not None:
            logger.warning("accounting disabled; budget for job %r is "
                           "not enforced", job_id)
        if chunksize is None:
            # Ceil division (multiprocessing's formula): floor leaves a
            # remainder chunk that lands as one worker's straggler tail —
            # at 200 tasks x 4 workers that is a 17th chunk computing
            # alone while three workers idle, most of the measured 10ms
            # overhead gap vs mp. Capped so huge maps still stream
            # (reference fixed chunk: fiber/pool.py:1169-1170).
            chunksize = max(1, min(DEFAULT_CHUNKSIZE,
                                   -(-len(items) // (self._n_workers * 4))))
        # One trace per sampled map: its id + the serialize span's id
        # ride every task envelope so worker spans join the same trace
        # (docs/observability.md). Unsampled maps ship tctx=None and the
        # workers record nothing. Sampled BEFORE the ledger opens: the
        # header records the id, and a resumed map adopts the recorded
        # one — resubmitted-after-crash chunks keep their trace (the
        # envelope-reuse rule, same as storemiss/death resubmission).
        trace_id = telemetry.maybe_start_trace()
        # Durable-map ledger (docs/robustness.md): with job_id= the map
        # is journaled write-ahead and resumable across master crashes.
        # A pre-existing ledger for this job_id means THIS call is the
        # resume: restore its journaled chunks, run only the remainder.
        ledger = None
        completed: Dict[int, Tuple[int, str]] = {}
        if job_id is not None:
            try:
                ledger, completed, chunksize, trace_id = \
                    self._ledger_open(job_id, func, items, chunksize,
                                      star, trace_id)
            except ValueError:
                self._store.fail(seq, RuntimeError("ledger rejected"),
                                 reason="ledger spec mismatch")
                raise
            except Exception:  # noqa: BLE001 - durability best-effort
                logger.warning(
                    "ledger: journaling disabled for job %r (open "
                    "failed); the map runs but is not resumable",
                    job_id, exc_info=True)
                ledger, completed = None, {}
        restorable: Dict[int, List[Any]] = {}
        if completed:
            restore_t0 = time.perf_counter()
            restorable = self._ledger_restore_all(job_id, completed)
            if COSTS.enabled:
                # Restored chunks bill RESTORE cost, never execute
                # cost: the journaled results are fetched, not re-run
                # (tasks_restored is charged at the fill below).
                COSTS.charge(bill_key, restore_s=(
                    time.perf_counter() - restore_t0))
        # Scheduler registration before any chunk is queued: priority is
        # the WDRR weight across concurrently active maps; the map's
        # state (queued duplicates included) is dropped at completion.
        self._sched.register_map(seq, priority)
        self._store.add_callback(
            seq, lambda: self._sched.release_map(seq))
        if ledger is not None:
            self._ledgers[seq] = ledger
            self._store.add_callback(seq,
                                     lambda: self._ledger_done(seq))
        if COSTS.enabled:
            # Registered AFTER the ledger-done callback so the writer
            # thread has closed (bytes_written is final) when the
            # map's cost is sealed and its job record persisted.
            self._store.add_callback(
                seq, lambda: self._finish_billing(seq, job_id, ledger,
                                                  budget))
        self._n_submitted += len(items)
        _m_tasks_submitted.inc(len(items))
        spans = _chunk_spans(len(items), chunksize)
        pending = [s for s in spans if s[0] not in restorable]
        if ledger is not None:
            self._ledger_last = {
                "job_id": job_id, "seq": seq, "trace": trace_id,
                "chunks": len(spans),
                "restored_chunks": len(restorable),
                "pending_chunks": len(pending),
                "restored_tasks": sum(len(v)
                                      for v in restorable.values()),
            }
        FLIGHT.record("pool", "submit", seq=seq, items=len(items),
                      trace=trace_id, job=job_id,
                      restored_chunks=len(restorable) or None)
        root_span = (tracing.span("pool.serialize", trace=trace_id,
                                  seq=seq, items=len(items))
                     if trace_id and pending else contextlib.nullcontext())
        if pending:
            ser_t0 = time.perf_counter()
            env_key = bill_key if COSTS.enabled else None
            with global_timer.section("pool.serialize"), root_span as sp:
                tctx = (trace_id, sp["span"]) if sp is not None else None
                blob = serialization.dumps(func)
                digest = hashlib.md5(blob).digest()
                enc_items = items
                if self._objstore is not None and self._store_inline_max:
                    seq_digests: List[str] = []
                    # Accelerator-destined maps (@meta tpu/gpu/device)
                    # mark their BROADCAST refs (shared across items —
                    # the encoder's memo detects sharing) so resolving
                    # workers route those through the shared device
                    # tier — one H2D per host per digest, not per
                    # worker. Per-item refs stay unhinted.
                    fmeta = get_meta(func)
                    dev_hint = bool(fmeta.get("tpu") or fmeta.get("gpu")
                                    or fmeta.get("device"))
                    try:
                        with global_timer.section("pool.store_encode"):
                            enc_items = self._encode_items(
                                items, seq_digests, env_key,
                                device_hint=dev_hint)
                    except Exception:  # noqa: BLE001 - optimization only
                        logger.warning(
                            "store: arg encoding failed; shipping inline",
                            exc_info=True)
                        enc_items = items
                        seq_digests = []
                    if seq_digests:
                        self._arm_store_fallback(seq, digest, blob, star,
                                                 items, seq_digests, tctx,
                                                 env_key)
                        # Locality seed: this host's store owns the refs,
                        # and the backend may know other hosts that
                        # already cache them (prestaged via put_object).
                        self._sched.note_host_has(local_host_key(),
                                                  seq_digests)
                        self._probe_ref_locations(seq_digests)
                for base, size in pending:
                    chunk = enc_items[base:base + size]
                    digs = _chunk_digests(chunk)
                    if digs:
                        self._sched.register_chunk((seq, base), digs)
                    payload = serialization.dumps(
                        ("task", seq, base, digest, blob, chunk, star,
                         tctx, env_key)
                    )
                    self._taskq.put((payload, (seq, base)))
            if COSTS.enabled:
                COSTS.charge(bill_key, serialize_s=(
                    time.perf_counter() - ser_t0))
        if restorable:
            # Journaled chunks fill directly — never re-executed, never
            # re-dispatched; exactly one result per task is the ledger's
            # contract. Fills run after the remainder is queued so a
            # fully-restored map completes (and fires its callbacks)
            # only once everything is registered.
            n_restored = 0
            for base, values in restorable.items():
                self._store.fill(seq, base, values)
                n_restored += len(values)
            self._n_restored += n_restored
            if COSTS.enabled:
                # Exactly-once across crashes: restored tasks bill as
                # tasks_restored, never as executed/billed tasks (the
                # result loop only bills frames, and restored chunks
                # never cross the wire again).
                COSTS.charge(bill_key, tasks_restored=n_restored)
            logger.warning(
                "ledger: job %r resumed — restored %d/%d chunks "
                "(%d tasks) from the journal; executing %d chunks",
                job_id, len(restorable), len(spans), n_restored,
                len(pending))
        _g_queue_depth.set(self._taskq.qsize())
        if self._resilient and getattr(self, "_parked_count", 0):
            # New chunks can clear parked requests' reservation gates.
            # Narrow except: only shutdown races are benign — wake()'s
            # wrong-mode RuntimeError must stay loud.
            try:
                self._task_ep.wake()
            except (TransportClosed, OSError):
                pass
        return result

    # -- streaming data plane (docs/streaming.md) --------------------------
    def _submit_stream(self, func: Callable, iterable: Iterable[Any],
                       chunksize: Optional[int], star: bool,
                       priority: float = 1.0,
                       job_id: Optional[str] = None,
                       budget: Optional[CostBudget] = None,
                       windowed: bool = True,
                       ordered: bool = True):
        """Open a streaming map: a background admission loop pulls from
        the caller's iterator lazily, keeping at most ``stream_window``
        chunks encoded + in flight + un-yielded at any instant, so the
        master never materializes the task list. Returns
        ``(seq, ledger, chunksize)`` for the imap variants to build
        their consumer iterator around."""
        from fiber_tpu import config as _config

        if self._closed or self._terminated:
            raise ValueError("Pool not running")
        self._ensure_workers(func)
        cfg = _config.get()
        it = iter(iterable)
        seq = self._store.add_stream()
        mid = next(_MAP_IDS)
        bill_key = (COSTS.tenant,
                    job_id if job_id is not None else f"map-{mid}",
                    f"m{mid}")
        if COSTS.enabled:
            self._seq_bill[seq] = bill_key
            self._map_wall0[seq] = time.perf_counter()
            if budget is not None:
                COSTS.set_budget(bill_key, budget)
                self._map_budgets[bill_key] = budget
                while len(self._map_budgets) > 64:
                    self._map_budgets.pop(next(iter(self._map_budgets)))
        elif budget is not None:
            logger.warning("accounting disabled; budget for job %r is "
                           "not enforced", job_id)
        # No length to divide: the streaming default is the chunk cap
        # itself (a short stream just produces few chunks).
        chunksize = max(1, int(chunksize if chunksize is not None
                               else DEFAULT_CHUNKSIZE))
        trace_id = telemetry.maybe_start_trace()
        # Stream journal (docs/streaming.md "Stream ledger"): admits
        # (input payloads, resumable without the producer), result
        # chunks, and the consumer's cursor — `fiber-tpu resume` works
        # on a half-consumed stream from these alone.
        ledger = None
        completed: Dict[int, Tuple[int, str]] = {}
        if job_id is not None:
            try:
                ledger, completed, chunksize, trace_id = \
                    self._stream_ledger_open(job_id, func, chunksize,
                                             star, trace_id)
            except ValueError:
                self._store.fail(seq, RuntimeError("ledger rejected"),
                                 reason="ledger spec mismatch")
                raise
            except Exception:  # noqa: BLE001 - durability best-effort
                logger.warning(
                    "ledger: journaling disabled for stream job %r "
                    "(open failed); the stream runs but is not "
                    "resumable", job_id, exc_info=True)
                ledger, completed = None, {}
        window = (max(1, int(cfg.stream_window)) if windowed
                  else 1 << 30)
        self._stream_windows[seq] = window
        if ledger is None:
            # Without a journal the master never needs result VALUES on
            # the hot loop: oversized results stay ObjectRefs in the
            # store's spillable tiers and resolve at yield time.
            self._stream_lazy.add(seq)
        self._sched.register_map(seq, priority)
        if windowed:
            # Window-aware handout: a hier sub-master's range must not
            # swallow the whole admission window — other hosts would
            # starve inside it.
            self._sched.note_stream(seq, max(1, window // 4))
        self._store.add_callback(
            seq, lambda: self._sched.release_map(seq))
        self._store.add_callback(
            seq, lambda: self._stream_cleanup(seq))
        if ledger is not None:
            self._ledgers[seq] = ledger
            self._store.add_callback(seq,
                                     lambda: self._ledger_done(seq))
        if COSTS.enabled:
            self._store.add_callback(
                seq, lambda: self._finish_billing(seq, job_id, ledger,
                                                  budget))
        blob = serialization.dumps(func)
        fdigest = hashlib.md5(blob).digest()
        env_key = bill_key if COSTS.enabled else None
        if trace_id:
            with tracing.span("pool.stream_open", trace=trace_id,
                              seq=seq) as sp:
                tctx = (trace_id, sp["span"])
        else:
            tctx = None
        # Storemiss context for streams: items=None marks "per-chunk,
        # see _stream_ctx" (the iterator can't be replayed).
        with self._seq_ctx_lock:
            self._seq_ctx[seq] = (fdigest, blob, star, None, tctx,
                                  env_key)
        self._store.add_callback(
            seq, lambda: self._seq_ctx.pop(seq, None))
        FLIGHT.record("pool", "stream", seq=seq, event="open",
                      window=window if windowed else None,
                      chunksize=chunksize, trace=trace_id, job=job_id,
                      restored_chunks=len(completed) or None)
        threading.Thread(
            target=self._stream_admit,
            args=(seq, it, fdigest, blob, star, chunksize, tctx,
                  env_key, ledger, completed, job_id),
            name=f"fiber-stream-admit-{seq}", daemon=True,
        ).start()
        return seq, ledger, chunksize

    def _stream_admit(self, seq, it, fdigest, blob, star, chunksize,
                      tctx, env_key, ledger, completed, job_id) -> None:
        """The windowed admission loop (one daemon thread per stream):
        pull one chunk from the producer, park while the window is full
        (condition-variable on the ResultStore — the same no-busy-wait
        posture as ``_task_loop``'s inflight gate), encode, journal the
        admit, dispatch. Exhaustion finalizes the stream entry."""
        from fiber_tpu.store.replicate import REPLICATOR

        admitted_chunks = 0
        restored_tasks = 0
        restored_chunks = 0
        try:
            while True:
                if self._terminated or self._store.is_done(seq):
                    return  # aborted/failed mid-stream; no finalize
                window = self._stream_windows.get(seq, 1)
                # "At most `window` chunks un-yielded at any instant":
                # admitting the next chunk is legal once the backlog is
                # a chunk short of the window.
                limit = max(0, window - 1) * chunksize
                waited_t0 = None
                # First probe is non-blocking so even a sub-tick park
                # registers as an episode (the gauge the slow-consumer
                # drills read); subsequent waits ride the condition
                # with a bounded tick, _task_loop posture.
                while not self._store.wait_stream_capacity(
                        seq, limit,
                        timeout=(0.0 if waited_t0 is None else 0.5)):
                    if waited_t0 is None:
                        waited_t0 = time.perf_counter()
                        self._stream_admit_waits += 1
                        _m_stream_admit_waits.inc()
                    if self._terminated:
                        return
                    if self._closed:
                        break
                    # Re-read per wait tick: a policy-plane
                    # shrink/restore takes effect mid-park.
                    window = self._stream_windows.get(seq, window)
                    limit = max(0, window - 1) * chunksize
                if waited_t0 is not None and FLIGHT.enabled:
                    FLIGHT.record(
                        "pool", "stream", seq=seq, event="admit_wait",
                        wait_s=round(time.perf_counter() - waited_t0, 4),
                        reason="window full; consumer slower than "
                               "producer — admission parked")
                if self._closed:
                    # close() mid-admission is producer EOF: the
                    # consumer abandoned the iterator (or the operator
                    # is shutting down). Truncate here — join()'s drain
                    # must see a finalized entry, not an admission loop
                    # parked forever on capacity no consumer will free.
                    logger.warning(
                        "stream: pool closed with stream seq=%d still "
                        "admitting; truncating after %d chunk(s)", seq,
                        admitted_chunks)
                    break
                if self._store.is_done(seq):
                    return
                # Admit a BURST: every chunk the current window has
                # room for rides one capacity check (one lock acquire,
                # one park/wake cycle per windowful instead of per
                # chunk — measurable at 1M tasks). The burst respects
                # the same invariant as chunk-at-a-time admission:
                # un-yielded slots never exceed window * chunksize.
                total, yielded, _fin = self._store.stream_fill_state(seq)
                room = limit - max(0, total - yielded)
                burst = max(1, room // chunksize + 1)
                exhausted = False
                for _ in range(burst):
                    chunk = list(itertools.islice(it, chunksize))
                    if not chunk:
                        exhausted = True  # producer done
                        break
                    base = self._store.extend(seq, len(chunk))
                    admitted_chunks += 1
                    self._n_submitted += len(chunk)
                    _m_tasks_submitted.inc(len(chunk))
                    rec = completed.get(base) if completed else None
                    if rec is not None and rec[0] == len(chunk):
                        values = self._ledger_restore(rec[1], rec[0])
                        if values is not None:
                            # Journaled on a previous run: fill
                            # directly, never re-execute (exactly-once
                            # across crashes; billed as
                            # tasks_restored).
                            self._store.fill(seq, base, values)
                            self._n_restored += len(values)
                            restored_tasks += len(values)
                            restored_chunks += 1
                            if env_key is not None:
                                COSTS.charge(env_key,
                                             tasks_restored=len(values))
                            continue
                    ser_t0 = time.perf_counter()
                    enc_chunk = chunk
                    chunk_digs: List[str] = []
                    if (self._objstore is not None
                            and self._store_inline_max):
                        try:
                            with global_timer.section(
                                    "pool.store_encode"):
                                enc_chunk = self._encode_items(
                                    chunk, chunk_digs, env_key)
                        except Exception:  # noqa: BLE001 - optimization
                            logger.warning("store: stream arg encoding "
                                           "failed; shipping inline",
                                           exc_info=True)
                            enc_chunk = chunk
                            chunk_digs = []
                    if chunk_digs:
                        REPLICATOR.note(chunk_digs)
                        self._sched.note_host_has(local_host_key(),
                                                  chunk_digs)
                    with self._seq_ctx_lock:
                        self._stream_ctx[(seq, base)] = (chunk,
                                                         tuple(chunk_digs))
                    if ledger is not None:
                        # Admit record BEFORE dispatch (write-ahead):
                        # the input payload persists so `fiber-tpu
                        # resume` can re-execute this chunk without
                        # the producer.
                        ledger.record_admit(base, len(chunk), chunk)
                    digs = _chunk_digests(enc_chunk)
                    if digs:
                        self._sched.register_chunk((seq, base), digs)
                    payload = serialization.dumps(
                        ("task", seq, base, fdigest, blob, enc_chunk,
                         star, tctx, env_key))
                    if env_key is not None:
                        COSTS.charge(env_key, serialize_s=(
                            time.perf_counter() - ser_t0))
                    self._taskq.put((payload, (seq, base)))
                    if self._resilient and getattr(self, "_parked_count",
                                                   0):
                        try:
                            self._task_ep.wake()
                        except (TransportClosed, OSError):
                            pass
                _g_queue_depth.set(self._taskq.qsize())
                total, yielded, _fin = self._store.stream_fill_state(seq)
                _g_stream_window_fill.set(max(0, total - yielded))
                if exhausted:
                    break  # producer exhausted
        except Exception as err:  # noqa: BLE001 - producer raised
            logger.exception("stream: producer/admission failed for "
                             "seq=%d", seq)
            self._store.fail(seq, err, reason="stream producer raised",
                             direct=True)
            return
        if ledger is not None:
            self._ledger_last = {
                "job_id": job_id, "seq": seq, "stream": True,
                "chunks": admitted_chunks,
                "restored_chunks": restored_chunks,
                "pending_chunks": admitted_chunks - restored_chunks,
                "restored_tasks": restored_tasks,
            }
        FLIGHT.record("pool", "stream", seq=seq, event="finalize",
                      chunks=admitted_chunks,
                      restored_chunks=restored_chunks or None)
        self._store.finalize(seq)

    def _stream_ledger_open(self, job_id: str, func: Callable,
                            chunksize: int, star: bool,
                            trace_id: Optional[str]):
        """Open (or resume) a STREAM journal: ``kind="stream"`` header
        keyed by a length-free task digest (the item count is unknowable
        up front), admit records carrying the input payloads, result
        chunks, and the consumer cursor. Returns
        ``(ledger|None, completed, chunksize, trace_id)``."""
        from fiber_tpu import config as _config
        from fiber_tpu.store import ledger as ledgermod
        from fiber_tpu.store.replicate import REPLICATOR

        cfg = _config.get()
        if not bool(cfg.ledger_enabled):
            return None, {}, chunksize, trace_id
        path = ledgermod.job_path(job_id)
        tdigest = ledgermod.stream_task_digest(func, star)
        store = self._ledger_store()
        fsync_s = float(cfg.ledger_fsync_s)

        def note_chunk(digest: str) -> None:
            REPLICATOR.note((digest,))

        completed: Dict[int, Tuple[int, str]] = {}
        admits: Dict[int, Tuple[int, str]] = {}
        header = None
        if os.path.exists(path):
            try:
                header, admits, completed, _cursor, _done = \
                    ledgermod.load_stream(path)
            except ValueError:
                logger.warning("ledger: %s has no readable header; "
                               "starting stream job %r fresh", path,
                               job_id)
                header = None
        if header is not None:
            if header.get("kind") != "stream":
                raise ValueError(
                    f"job_id {job_id!r} was journaled as a classic map, "
                    "not a stream; pick a new job_id, or resume it via "
                    "map(..., job_id=)")
            if header.get("task_digest") != tdigest:
                raise ValueError(
                    f"stream job_id {job_id!r} was journaled by a "
                    "different task spec (function / call shape "
                    f"changed); pick a new job_id or delete {path}")
            # Recorded chunking wins: admit/result bases only line up
            # against the journal under the original chunk size.
            chunksize = int(header.get("chunksize") or chunksize)
            if header.get("trace") and trace_id is not None:
                trace_id = str(header["trace"])
            led = ledgermod.MapLedger(path, store,
                                      fsync_interval=fsync_s,
                                      on_chunk=note_chunk)
            led.adopt(completed)
            led.adopt_admits(admits)
            REPLICATOR.note(d for _, d in completed.values())
            FLIGHT.record("store", "ledger", job=job_id,
                          event="stream_resume",
                          admits=len(admits), completed=len(completed))
            return led, completed, chunksize, trace_id
        led = ledgermod.MapLedger(path, store, fsync_interval=fsync_s,
                                  on_chunk=note_chunk)
        func_digest = None
        try:
            # The function travels BY VALUE (cloudpickle) like the
            # classic spec payload, so the resume CLI can re-execute
            # admitted chunks from a dead master's journal alone.
            try:
                import cloudpickle as _cp

                func_blob = _cp.dumps(func)
            except Exception:  # noqa: BLE001
                func_blob = serialization.dumps(func)
            spec_data = serialization.dumps(
                (func_blob, bool(star), int(chunksize)))
            func_digest = store.put_bytes(
                spec_data, refs=1, persist=True).digest
        except Exception:  # noqa: BLE001
            logger.warning(
                "ledger: stream spec for job %r not serializable; "
                "`fiber-tpu resume` needs the original call site",
                job_id, exc_info=True)
        led.write_header({
            "kind": "stream", "job_id": job_id, "task_digest": tdigest,
            "spec": func_digest, "chunksize": int(chunksize),
            "star": bool(star), "trace": trace_id,
        })
        return led, {}, chunksize, trace_id

    def _release_stream_chunk(self, seq: int, base: int) -> None:
        """A stream chunk filled: its raw-items storemiss context and
        encoded-arg store refs are dead weight — drop them now so
        master state stays O(window), not O(stream length)."""
        from fiber_tpu.store.replicate import REPLICATOR

        with self._seq_ctx_lock:
            sctx = self._stream_ctx.pop((seq, base), None)
        if sctx is None:
            return
        digs = sctx[1]
        if digs:
            REPLICATOR.forget(digs)
            if self._objstore is not None:
                for d in digs:
                    self._objstore.release(d)

    def _stream_cleanup(self, seq: int) -> None:
        """Stream completion (success, failure or abort): drop every
        per-stream table entry and release any chunk contexts that
        never filled (failure paths)."""
        self._stream_windows.pop(seq, None)
        self._stream_window_orig.pop(seq, None)
        self._stream_lazy.discard(seq)
        with self._seq_ctx_lock:
            leftover = [k for k in self._stream_ctx if k[0] == seq]
        for (_s, base) in leftover:
            self._release_stream_chunk(seq, base)
        if not self._stream_windows:
            _g_stream_window_fill.set(0)

    def _stream_results(self, seq: int, ordered: bool, lazy: bool,
                        ledger, chunksize: int):
        """Consumer-side iterator for a stream: resolves deferred
        by-reference results at yield time (the incremental-spill leg)
        and, on an ordered durable stream, journals the consumer cursor
        at chunk boundaries so `fiber-tpu resume` can skip the consumed
        prefix."""
        inner = (self._store.iter_ordered(seq) if ordered
                 else self._store.iter_unordered(seq))
        if ledger is None or not ordered:
            # Unordered consumption records no cursor: a count cannot
            # say WHICH results were consumed; resume re-emits every
            # journaled result instead. With no per-item bookkeeping
            # left, delegate — at 1M tiny tasks an extra Python-level
            # loop body per item is measurable.
            if not lazy:
                yield from inner
                return
            for v in inner:
                if isinstance(v, ObjectRef):
                    v = self._resolve_result_refs([v])[0]
                yield v
            return
        consumed = 0
        for v in inner:
            if lazy and isinstance(v, ObjectRef):
                v = self._resolve_result_refs([v])[0]
            yield v
            consumed += 1
            if consumed % chunksize == 0:
                ledger.record_cursor(consumed)

    def shrink_stream_window(self, factor: float = 0.5) -> int:
        """Policy-plane hook (queue_growth -> shrink_stream_window):
        cut every active stream's admission window, throttling a
        runaway producer at the source. The pre-shrink width is kept
        for the owned revert; floor one chunk so streams always
        progress. Returns how many streams were shrunk."""
        factor = min(1.0, max(0.05, float(factor)))
        n = 0
        for seq, win in list(self._stream_windows.items()):
            new = max(1, int(win * factor))
            if new < win:
                self._stream_window_orig.setdefault(seq, win)
                self._stream_windows[seq] = new
                n += 1
        return n

    def restore_stream_window(self) -> int:
        """Clear-edge revert of shrink_stream_window: restore every
        still-active stream's original window. Streams that completed
        meanwhile already dropped their state via _stream_cleanup."""
        n = 0
        for seq, orig in list(self._stream_window_orig.items()):
            if self._stream_windows.get(seq, orig) != orig:
                self._stream_windows[seq] = orig
                n += 1
            self._stream_window_orig.pop(seq, None)
        return n

    # -- public API --------------------------------------------------------
    def apply(self, func: Callable, args: Tuple = (), kwds: Optional[Dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(
        self,
        func: Callable,
        args: Tuple = (),
        kwds: Optional[Dict] = None,
        callback: Optional[Callable] = None,
        error_callback: Optional[Callable] = None,
        priority: float = 1.0,
    ) -> AsyncResult:
        if kwds:
            import functools

            func = functools.partial(func, **kwds)
        return self._submit(func, [tuple(args)], 1, True,
                            callback, error_callback, single=True,
                            priority=priority)

    def _device_dispatch(
        self, func: Callable, items: List[Any], star: bool
    ) -> Optional[List[Any]]:
        """Run a @meta(device=True) function on the mesh; None if the
        function isn't device-hinted. Enforces the same pool-state
        contract as the host path."""
        if not self._wants_device(func):
            return None
        return self._run_device(func, items, star)

    def _wants_device(self, func: Callable) -> bool:
        """Pool-state check happens here so state errors always surface at
        the submit site, distinct from errors the user function raises."""
        if not get_meta(func).get("device"):
            return False
        if self._closed or self._terminated:
            raise ValueError("Pool not running")
        return True

    def _run_device(self, func: Callable, items: List[Any],
                    star: bool) -> List[Any]:
        try:
            from fiber_tpu.parallel import device_map
        except ImportError as err:  # pragma: no cover
            raise RuntimeError(
                "@meta(device=True) requires the fiber_tpu.parallel "
                "device path"
            ) from err
        t0 = time.perf_counter()
        items, bcast, bpos = self._device_broadcast_split(items, star)
        if bcast:
            out = device_map(func, items, star=star, broadcast=bcast,
                             broadcast_positions=bpos)
        else:
            # No split: keep the pre-device-tier call shape so stubs
            # and older device_map signatures stay compatible.
            out = device_map(func, items, star=star)
        wall = time.perf_counter() - t0
        flops_meta = get_meta(func).get("flops")
        if COSTS.enabled and items:
            # Device maps bill too: one mesh call, no wire — device
            # seconds, task count and (when @meta declares the analytic
            # cost) FLOPs, under a key of their own.
            mid = next(_MAP_IDS)
            dev_key = (COSTS.tenant, f"map-{mid}", f"m{mid}")
            fields: Dict[str, float] = {
                "device_s": wall, "wall_s": wall,
                "tasks": float(len(items)),
            }
            if flops_meta:
                fields["flops"] = float(flops_meta) * len(items)
            COSTS.charge(dev_key, **fields)
            COSTS.release_key(dev_key)
        # Live MFU (docs/observability.md "Device telemetry"): a
        # function declaring its analytic cost (@meta(device=True,
        # flops=<per item>) — utils/flops.py counters supply the
        # number) lands its achieved MFU in the pool_map_mfu gauge
        # whenever the device peak resolves; CPU runs record None
        # honestly, exactly the bench-cluster posture.
        if flops_meta and items:
            from fiber_tpu.telemetry.device import DEVICE

            DEVICE.note_map_flops(float(flops_meta) * len(items),
                                  wall, len(items))
        return out

    def _device_broadcast_split(
        self, items: List[Any], star: bool
    ) -> "Tuple[List[Any], tuple, tuple]":
        """Detect broadcast args in a device map and lift them onto the
        mesh ONCE (docs/objectstore.md "Device tier").

        A position of every star-tuple holding the IDENTICAL array
        object (id-identity — the ES/POET idiom ``[(params, s) for s
        in seeds]``) is a broadcast: instead of stacking pop-size
        copies and paying pop-size x nbytes of H2D per call, the array
        is content-addressed, replicated across the mesh through the
        store's device tier (accounted under the ``ici`` site), and
        passed unbatched. Repeat generations with the same digest hit
        the tier: zero wire bytes, zero H2D. Returns ``(items with the
        positions stripped, broadcast args, positions)`` — unchanged
        inputs when nothing qualifies. With the tier off/demoted the
        qualifying args still pass unbatched (never stacked) but
        un-cached: every call re-pays the mesh transfer."""
        if not star or len(items) < 2:
            return items, (), ()
        first = items[0]
        if not isinstance(first, tuple) or len(first) < 2:
            return items, (), ()
        import numpy as np

        width = len(first)
        positions = []
        for j in range(width):
            cand = first[j]
            if not isinstance(cand, np.ndarray) or \
                    cand.nbytes < _DEVICE_BCAST_MIN:
                continue
            if all(isinstance(it, tuple) and len(it) == width
                   and it[j] is cand for it in items):
                positions.append(j)
        # At least one per-item position must remain — an all-broadcast
        # map has nothing to shard over the pool axis.
        if not positions or len(positions) == width:
            return items, (), ()
        from fiber_tpu import store as storemod

        tier = storemod.device_store_tier()
        bcast = []
        digests = []
        for j in positions:
            arr = first[j]
            if tier is None:
                bcast.append(arr)
                continue
            dig = self._bcast_store_digest(arr)
            bcast.append(tier.put(dig, arr))
            digests.append(dig)
        if digests:
            # Locality seed: the scheduler's host->digest map learns
            # this host holds the broadcast content, so a host-path map
            # of the same payload prefers these workers.
            try:
                self._sched.note_host_has(local_host_key(), digests)
            except Exception:  # noqa: BLE001 - placement hint only
                pass
        pos_set = set(positions)
        stripped = [tuple(a for j, a in enumerate(it)
                          if j not in pos_set) for it in items]
        return stripped, tuple(bcast), tuple(positions)

    def _bcast_store_digest(self, arr) -> str:
        """STORE-space digest (digest_of over serialization.dumps —
        the space ObjectRefs live in, so the locality seed matches
        host-path refs of the identical payload; a raw dtype|shape|
        bytes digest never intersects it) with a content-addressed
        shortcut: the raw buffer is hashed zero-copy and mapped to the
        serialized-form digest, so repeat generations of the ES
        broadcast idiom skip the serialize copy. Sound under in-place
        mutation — both sides of the cache are pure content
        addresses."""
        import hashlib

        import numpy as np

        from fiber_tpu.store.core import digest_of

        buf = np.ascontiguousarray(arr)
        h = hashlib.sha256()
        h.update(f"{arr.dtype}|{arr.shape}|".encode())
        h.update(memoryview(buf).cast("B"))
        raw = h.hexdigest()
        dig = self._bcast_digests.get(raw)
        if dig is None:
            dig = digest_of(serialization.dumps(arr))
            self._bcast_digests[raw] = dig
            while len(self._bcast_digests) > 32:
                self._bcast_digests.pop(next(iter(self._bcast_digests)))
        return dig

    def _dispatch_async(self, func, items, star, chunksize,
                        callback, error_callback, priority=1.0,
                        job_id=None, budget=None, tenant=None):
        """Device-or-host submission shared by every map variant, with
        async error contracts preserved on the device path (user-function
        errors reach error_callback / .get(); only pool-state errors
        surface at the submit site, like the host path).

        The device dispatch runs on a background thread: ``map_async``
        returns before the mesh result exists and callbacks fire off the
        submitting thread — the same contract as the host path (round-2
        verdict, Weak #4: the old inline dispatch blocked the caller for
        the whole mesh run). Each dispatch gets a private ResultStore so
        device work never feeds host-path flow control
        (MAX_INFLIGHT_TASKS) or worker-start escalation."""
        if not self._wants_device(func):
            return self._submit(func, items, chunksize, star,
                                callback, error_callback,
                                priority=priority, job_id=job_id,
                                budget=budget, tenant=tenant)
        if job_id is not None:
            # Device dispatch is one mesh call, not a chunk stream —
            # there is nothing partial to journal or resume.
            logger.warning("ledger: job_id %r ignored for "
                           "@meta(device=True) dispatch", job_id)
        store = ResultStore()
        seq = store.add(len(items))
        result = AsyncResult(store, seq, single=False)
        _register_async_callbacks(store, seq, result,
                                  callback, error_callback)
        if not items:
            return result

        trace_id = telemetry.maybe_start_trace()

        def run() -> None:
            dev_span = (tracing.span("pool.device_dispatch",
                                     trace=trace_id, items=len(items))
                        if trace_id else contextlib.nullcontext())
            with dev_span:
                try:
                    out = list(self._run_device(func, items, star))
                except Exception as err:  # noqa: BLE001
                    store.fail(seq, err, reason="device dispatch failed")
                    return
            store.fill(seq, 0, out)

        threading.Thread(target=run, name="fiber-device-dispatch",
                         daemon=True).start()
        return result

    def map(
        self,
        func: Callable,
        iterable: Iterable[Any],
        chunksize: Optional[int] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
        tenant: Optional[str] = None,
    ) -> List[Any]:
        """``job_id=`` makes the map durable (docs/robustness.md): the
        task spec and every completed chunk are journaled write-ahead
        under ``<staging>/ledger/<job_id>``, and a master crash is
        survivable — ``fiber-tpu resume <job_id>`` (or re-calling map
        with the same job_id) restores completed results and re-executes
        only the remainder. Tasks must be idempotent (the resilient-pool
        contract already requires this).

        ``budget=`` sets soft :class:`CostBudget` caps for the map
        (docs/observability.md "Resource accounting"): crossing any cap
        raises the ``budget_exceeded`` watchdog anomaly + flight event.
        Measurement, not enforcement — the map keeps running."""
        return self.map_async(func, iterable, chunksize,
                              priority=priority, job_id=job_id,
                              budget=budget, tenant=tenant).get()

    def map_async(
        self,
        func: Callable,
        iterable: Iterable[Any],
        chunksize: Optional[int] = None,
        callback: Optional[Callable] = None,
        error_callback: Optional[Callable] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
        tenant: Optional[str] = None,
    ):
        return self._dispatch_async(func, list(iterable), False, chunksize,
                                    callback, error_callback, priority,
                                    job_id=job_id, budget=budget,
                                    tenant=tenant)

    def starmap(
        self,
        func: Callable,
        iterable: Iterable[Tuple],
        chunksize: Optional[int] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
        tenant: Optional[str] = None,
    ) -> List[Any]:
        return self.starmap_async(func, iterable, chunksize,
                                  priority=priority, job_id=job_id,
                                  budget=budget, tenant=tenant).get()

    def starmap_async(
        self,
        func: Callable,
        iterable: Iterable[Tuple],
        chunksize: Optional[int] = None,
        callback: Optional[Callable] = None,
        error_callback: Optional[Callable] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
        tenant: Optional[str] = None,
    ):
        return self._dispatch_async(func, [tuple(t) for t in iterable],
                                    True, chunksize, callback,
                                    error_callback, priority,
                                    job_id=job_id, budget=budget,
                                    tenant=tenant)

    def imap(
        self,
        func: Callable,
        iterable: Iterable[Any],
        chunksize: Optional[int] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
    ):
        """Ordered lazy map over ANY iterable (docs/streaming.md).

        With ``stream_enabled`` (the default) this is a true streaming
        pipeline: a windowed admission loop pulls from ``iterable``
        lazily — at most ``stream_window`` chunks are encoded + in
        flight + un-yielded at any instant — so master memory is
        O(window), not O(n), and a slow consumer backpressures
        admission (which parks dispatch, which drains transport
        credits). ``job_id=`` journals the *stream*: admitted input
        chunks, completed result chunks, and the consumer's cursor, so
        ``fiber-tpu resume`` works on a half-consumed stream.

        With ``stream_enabled=False`` the map still accepts any
        iterable and dispatches without a window; the input is only
        materialized up front when ``job_id`` + ``ledger_enabled``
        demand the classic fixed task digest (ledger identity is
        ``f(func, n_items)``, which needs the full length — the
        tradeoff is O(n) master RAM in exchange for the classic
        whole-map journal format)."""
        return self._imap_impl(func, iterable, chunksize, priority,
                               job_id, budget, ordered=True)

    def imap_unordered(
        self,
        func: Callable,
        iterable: Iterable[Any],
        chunksize: Optional[int] = None,
        priority: float = 1.0,
        job_id: Optional[str] = None,
        budget: Optional[CostBudget] = None,
    ):
        """Unordered variant of :meth:`imap` — results yield as chunks
        complete, and each yielded slot's payload reference is released
        immediately, so master RSS stays flat across arbitrarily long
        streams (large results spill through the object store and are
        resolved at yield time). Same streaming / fallback /
        materialization rules as :meth:`imap`; an unordered durable
        stream journals results but no consumer cursor (a position
        count cannot identify WHICH unordered results were consumed —
        resume re-emits every journaled result)."""
        return self._imap_impl(func, iterable, chunksize, priority,
                               job_id, budget, ordered=False)

    def _imap_impl(self, func, iterable, chunksize, priority, job_id,
                   budget, ordered: bool):
        from fiber_tpu import config as _config

        if self._wants_device(func):
            # Device maps run as one mesh dispatch over the whole
            # batch; they are the one shape that genuinely needs the
            # materialized list.
            return iter(self._run_device(func, list(iterable),
                                         star=False))
        cfg = _config.get()
        windowed = bool(cfg.stream_enabled)
        if (not windowed and job_id is not None
                and bool(cfg.ledger_enabled)):
            # Classic durable path: the whole-map ledger's identity is
            # f(func, n_items), so the length must be known up front.
            items = list(iterable)
            res = self._submit(func, items, chunksize, False,
                               priority=priority, job_id=job_id,
                               budget=budget)
            inner = (self._store.iter_ordered(res._seq) if ordered
                     else self._store.iter_unordered(res._seq))
            return _ResultIterator(inner)
        seq, ledger, csz = self._submit_stream(
            func, iterable, chunksize, False, priority=priority,
            job_id=job_id if windowed else None, budget=budget,
            windowed=windowed, ordered=ordered)
        return _ResultIterator(self._stream_results(
            seq, ordered, seq in self._stream_lazy, ledger, csz))

    # -- lifecycle ---------------------------------------------------------
    def wait_workers(self, n: Optional[int] = None,
                     timeout: Optional[float] = None) -> bool:
        """Block until n (default: all) worker connections are up
        (reference: fiber/pool.py:1405-1422). Starts the (normally lazy)
        worker population if needed."""
        self._start_worker_thread()
        if n is None:
            n = self._n_workers
            if (self._dispatch_mode == "hier" and self._resilient
                    and self._cpu_per_job > 1
                    and not self._hier_degraded):
                # Hierarchical dispatch: one upstream result connection
                # per sub-master JOB, not per sub-worker.
                n = -(-self._n_workers // self._cpu_per_job)
        return self._result_ep.wait_for_peers(n, timeout)

    def close(self) -> None:
        """No new tasks; workers exit once submitted work drains (the
        release itself happens in join(), deterministically)."""
        self._closed = True

    def _release_workers(self) -> None:
        """Send one exit message per connected task consumer; strict
        round-robin delivers exactly one to each."""
        exit_payload = serialization.dumps(_EXIT)
        for _ in range(self._task_ep.peer_count()):
            try:
                self._task_ep.send(exit_payload, timeout=5.0)
            except (TimeoutError, TransportClosed, OSError):
                break

    def join(self) -> None:
        if not self._closed and not self._terminated:
            raise ValueError("join() before close()/terminate()")
        # 1. Drain all submitted work.
        while self._store.outstanding() > 0 and not self._terminated:
            time.sleep(0.05)
        # 2. Stop the maintainer so the worker list can no longer change.
        if self._worker_thread is not None:
            self._worker_thread.join(60)
        # 3. Release and reap the workers.
        if not self._terminated and not self._resilient:
            self._release_workers()
        with self._workers_lock:
            self._reaped = True  # late spawn stragglers self-terminate
            workers = list(self._workers)
        for p in workers:
            p.join(10)
            if p.is_alive():
                logger.warning("pool worker %s did not exit; terminating",
                               p.name)
                p.terminate()
                p.join(10)
        with self._workers_lock:
            self._workers = []
        self._shutdown_transport()

    def terminate(self) -> None:
        self._terminated = True
        self._closed = True
        with self._workers_lock:
            workers = list(self._workers)
        for p in workers:
            try:
                p.terminate()
            except Exception:
                pass
        for p in workers:
            try:
                p.join(10)
            except Exception:
                pass
        with self._workers_lock:
            self._workers = []
        self._store.abort_all(RuntimeError("pool terminated"))
        self._shutdown_transport()

    def _shutdown_transport(self) -> None:
        from fiber_tpu.telemetry.timeseries import TIMESERIES

        TIMESERIES.remove_probe(self._monitor_probe)
        self._taskq.put(None)
        self._sched.close()
        self._task_ep.close()
        self._result_ep.close()
        # Incomplete job ledgers stay on disk (that IS the durability
        # contract — `fiber-tpu resume` picks them up); only the writer
        # threads are stopped, after a final drain.
        for led in list(self._ledgers.values()):
            try:
                led.close()
            except Exception:  # noqa: BLE001
                pass
        self._ledgers.clear()

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc_info) -> None:
        if not self._closed:
            self.close()
        self.join()

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            if not self._terminated and not self._closed:
                self.terminate()
        except Exception:
            pass


class PoisonChunkError(Exception):
    """One chunk killed every worker that received it (e.g. its payload
    cannot deserialize in the worker); the map fails instead of
    crash-looping the pool forever."""


#: Consecutive death-resubmissions of ONE chunk before its map fails.
_POISON_CAP = 8


class ResilientPool(Pool):
    """REQ/REP pool with a pending table and resubmission on worker death
    (reference ResilientZPool, fiber/pool.py:1425-1688) — the default
    ``fiber_tpu.Pool``. Only safe for idempotent task functions."""

    _resilient = True

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        # ident -> {(seq, base): (payload, nitems)}
        self._pending: Dict[bytes, Dict[Tuple[int, int], Tuple[bytes, int]]] = {}
        #: len() of the handout loop's parked-request table, mirrored
        #: here (single-writer: the task loop) so result/submit paths
        #: can skip the wake nudge when nothing is waiting on a gate.
        self._parked_count = 0
        #: (seq, base) -> how many workers died holding that chunk; a
        #: chunk that keeps killing workers is POISON (e.g. its payload
        #: cannot deserialize in the worker) and must fail the map
        #: rather than crash-loop the pool forever.
        self._chunk_deaths: Dict[Tuple[int, int], int] = {}
        #: seq -> consecutive worker deaths attributed to that map with
        #: NO completed chunk in between (any result resets it). Catches
        #: the every-chunk-is-poison map, where per-chunk counts spread
        #: across the whole map and would take chunks*cap deaths to fire.
        self._seq_deaths: Dict[int, int] = {}
        self._pid_to_idents: Dict[int, set] = {}
        self._reaped_pids: set = set()
        # Dead-ident guard against stale "ready"s queued before a
        # sub-worker's death was processed. The window is short, so the
        # set is bounded: oldest entries fall out once the deque is full
        # (a long-lived die-heavy pool must not leak one entry per crash).
        self._dead_idents: set = set()
        self._dead_idents_order: "deque[bytes]" = deque(maxlen=4096)
        self._pending_lock = threading.Lock()
        #: Idents that declared themselves sub-masters ("hier" 5th field
        #: on their ready frames): their handouts are packed into ranges.
        self._hier_idents: set = set()
        super().__init__(*args, **kwargs)
        # Health plane: workers beat on the result stream; silence past
        # suspect_timeout declares the ident dead and reclaims its
        # pending chunks through the SAME path as an observed process
        # death — so a hung host (no FIN, no exit code) is survived
        # before TCP would notice. Declared idents are permanent: pool
        # idents are never reused, and a falsely-declared (merely slow)
        # worker is told to exit on its next "ready", its duplicate
        # results deduped by ResultStore.fill. Workers can't connect
        # before this point (they spawn lazily at first submit), so no
        # beat can precede the detector.
        from fiber_tpu import config as _config
        from fiber_tpu.health import FailureDetector

        _cfg = _config.get()
        if float(_cfg.heartbeat_interval or 0) > 0 \
                and float(_cfg.suspect_timeout or 0) > 0:
            self._detector = FailureDetector(
                float(_cfg.suspect_timeout), self._on_peer_suspect,
                permanent=True, name="fiber-pool-detector",
            ).start()
        # Dedicated control endpoint for packing-parent sub-worker
        # reports. Deliberately NOT the result endpoint (its peer count
        # is what wait_workers() reads as "workers connected") and NOT
        # the REQ/REP task endpoint (its single-threaded loop parks in
        # the task-handout wait, which would deadlock against a
        # resubmission-bearing report). Only packed jobs ever report,
        # so unpacked pools skip the listener + thread entirely.
        self._ctl_ep = None
        self._ctl_addr = None
        if self._cpu_per_job > 1:
            from fiber_tpu.backends import get_backend

            ip, _, _ = get_backend().get_listen_addr()
            self._ctl_ep = Endpoint("r")
            self._ctl_addr = self._ctl_ep.bind(ip)
            self._ctl_thread = threading.Thread(
                target=self._ctl_loop, name="fiber-pool-ctl", daemon=True
            )
            self._ctl_thread.start()

    def _ctl_loop(self) -> None:
        while True:
            try:
                data = self._ctl_ep.recv()
            except (TransportClosed, OSError):
                return
            try:
                msg = serialization.loads(data)
                if msg[0] == "subdead":
                    self._on_subworker_death(msg[1])
                elif msg[0] == "subgone":
                    self._on_subworker_gone(msg[1])
            except Exception:
                logger.exception("pool: dropping malformed control frame")

    def _shutdown_transport(self) -> None:
        super()._shutdown_transport()
        if self._detector is not None:
            self._detector.stop()
        if self._ctl_ep is not None:
            self._ctl_ep.close()

    def _on_peer_suspect(self, ident: bytes) -> None:
        """Failure-detector declaration: treat the silent ident exactly
        like a reported death (resubmit its pending chunks, block
        future handouts to it). Runs on the detector thread."""
        host = self._ident_hosts.get(ident)
        n = self._reclaim_ident(ident)
        if FLIGHT.enabled:
            # Black-box capture off the detector thread: the master's
            # own flight view of the dead ident, plus a best-effort pull
            # of the peer host's postmortem op (docs/observability.md).
            threading.Thread(
                target=self._capture_postmortem,
                args=(ident, host, n, "suspect"),
                name="fiber-postmortem", daemon=True,
            ).start()
        if n:
            logger.warning(
                "health: worker ident %s silent past suspect_timeout; "
                "declared dead, resubmitted %d pending chunks",
                ident.hex()[:8], n)
            # Resubmitted chunks can clear parked requests' gates.
            if self._parked_count:
                try:
                    self._task_ep.wake()
                except (TransportClosed, OSError):
                    pass
        else:
            logger.info(
                "health: idle worker ident %s silent past "
                "suspect_timeout; declared dead (nothing to resubmit)",
                ident.hex()[:8])

    def _capture_postmortem(self, ident: bytes, host, resubmitted: int,
                            reason: str) -> None:
        """Write the black-box bundle for one declared-dead worker: the
        master's flight events (which carry the ident's dispatch /
        resubmit history) plus, when the backend knows the peer's host,
        that host agent's ``postmortem`` op — its flight buffer, stack
        dump and any crash bundles workers on that host flushed.
        Entirely best-effort: postmortem capture must never take the
        health plane down with it."""
        from fiber_tpu.telemetry import postmortem

        peer = None
        if host is not None:
            try:
                from fiber_tpu.backends import get_backend

                collect = getattr(get_backend(), "collect_postmortem",
                                  None)
                if collect is not None:
                    peer = collect(host)
            except Exception:  # noqa: BLE001 - peer pull is optional
                logger.warning("postmortem: peer pull for %s failed",
                               host, exc_info=True)
        try:
            path = postmortem.capture_and_write(
                reason, ident=ident.hex(), peer_host=host,
                chunks_resubmitted=resubmitted, peer=peer)
            logger.warning("postmortem: bundle for worker %s written "
                           "to %s", ident.hex()[:8], path)
        except Exception:  # noqa: BLE001
            logger.warning("postmortem: bundle write failed",
                           exc_info=True)

    def _mark_ident_dead(self, ident: bytes) -> None:
        # Caller holds _pending_lock.
        if ident in self._dead_idents:
            return
        if len(self._dead_idents_order) == self._dead_idents_order.maxlen:
            self._dead_idents.discard(self._dead_idents_order[0])
        self._dead_idents_order.append(ident)
        self._dead_idents.add(ident)

    # Task handout: answer each worker's "ready" request with a task and
    # record it in the pending table until its result arrives.
    #
    # Reservation gate (reference regression, fiber
    # tests/test_pool.py:179-234): the worker-side fetch thread
    # pipelines — it requests chunk N+1 while chunk N computes — so
    # without a gate a fast worker's SECOND request can win a scarce
    # chunk over a sibling's FIRST, serializing two tasks that must run
    # concurrently (interlocked workloads then deadlock). A repeat
    # request (ident already has unfinished chunks) is therefore parked
    # whenever the queued chunks don't exceed one-per-potentially-idle
    # worker; parked requests are re-evaluated every loop turn and
    # answered out of order via the rep endpoint's recv_req/reply.
    # With chunks plentiful (the normal pipelined regime) the gate
    # passes immediately, so the REQ/REP overlap that closed the 10 ms
    # overhead gap is untouched.

    def _gate_allows(self, ident: bytes) -> bool:
        # Serve if the requester is idle (no unfinished chunks), or if
        # enough chunks remain to leave one for every worker that has
        # none. qsize() is approximate; the gate re-evaluates each turn.
        # Health-plane placement: a requester on a suspect host is
        # parked while healthier workers exist and work is scarce —
        # parked requests re-evaluate every turn, so a revived host
        # (the backend detector is non-permanent) resumes service.
        if self._suspect_defers(ident):
            return False
        with self._pending_lock:
            if not self._pending.get(ident):
                return True
            busy = sum(1 for t in self._pending.values() if t)
        reserve = max(0, self._n_workers - busy)
        return self._taskq.qsize() > reserve

    def _task_loop(self) -> None:
        # Runs until the pool's transport shuts down (join/terminate close
        # the endpoints → recv raises). During a close() drain it keeps
        # answering "ready" requests — with remaining tasks first, then
        # with exit messages so every worker is released.
        parked: Dict[bytes, Tuple[Any, int]] = {}  # ident -> (chan, pid)

        def sync_parked() -> None:
            # SINGLE-WRITER INVARIANT: _parked_count is written only
            # here, on the task loop's thread. submit/_on_result threads
            # read it unlocked (_gate_allows) — that is safe only
            # because a stale read degrades to the 0.5 s recv-timeout
            # retry, never to a lost task. If the loop is ever
            # restructured to mutate parked from another thread, this
            # must become a locked counter.
            self._parked_count = len(parked)

        def drain_done() -> bool:
            return self._draining_done() and self._taskq.empty()

        def reply_exit(chan) -> None:
            try:
                payload = serialization.dumps(_EXIT)
                self._task_ep.reply(chan, payload)
                self._bill_frame(None, tx=len(payload))
            except (TransportClosed, OSError):
                pass

        def serve(ident: bytes, fiber_pid: int, chan) -> None:
            """Hand the next chunk (or exit) to one cleared requester;
            re-parks nothing — the caller already passed the gate."""
            host = self._ident_hosts.get(ident)
            item = None
            while item is None:
                if self._terminated:
                    return
                if drain_done():
                    reply_exit(chan)
                    return
                try:
                    # Scheduler handout (docs/scheduling.md): WDRR map
                    # choice + locality scan for this requester; never
                    # hands a worker its own chunk's speculative dup.
                    item = self._taskq.get_for(ident, host, timeout=0.5)
                except pyqueue.Empty:
                    continue
                if item is None:
                    return
                if self._store.is_done(item[1][0]):
                    # Leftover chunk of a completed/poison-failed map:
                    # handing it out would burn workers on a map whose
                    # error already surfaced.
                    item = None
            items = [item]
            if ident in self._hier_idents and self._range_chunks > 1:
                # Hierarchical handout: top the range up with whatever
                # else is immediately available (never blocking — the
                # first chunk already waited its turn), bounded by the
                # knob. One frame then carries the whole range, so the
                # master's frame count and encode CPU scale with hosts.
                range_cap = self._range_chunks
                if item is not None:
                    # Streaming maps cap the range (window-aware
                    # handout): a whole admission window inside one
                    # sub-master's range would starve other hosts and
                    # defeat backpressure granularity.
                    cap = self._taskq.range_cap(item[1][0])
                    if cap:
                        range_cap = min(range_cap, cap)
                while len(items) < range_cap:
                    try:
                        extra = self._taskq.get_for(ident, host,
                                                    timeout=0)
                    except pyqueue.Empty:
                        break
                    if extra is None:
                        break
                    if self._store.is_done(extra[1][0]):
                        continue
                    items.append(extra)
            with self._pending_lock:
                # The worker may have been reaped while we waited for a
                # task — its pending table is gone and nobody would
                # ever resubmit these chunks. Requeue for the next
                # "ready".
                if (fiber_pid in self._reaped_pids
                        or ident in self._dead_idents):
                    for it in items:
                        self._taskq.put(it)
                    return
                table = self._pending.setdefault(ident, {})
                for payload, key in items:
                    table[key] = payload
            if len(items) == 1 and ident not in self._hier_idents:
                wire = items[0][0]
            else:
                # Range envelope: raw chunk payloads ride untouched
                # (encoded once at submit; the sub-master never decodes
                # them), tagged with their pending keys.
                wire = serialization.dumps(
                    ("range", [(key[0], key[1], payload)
                               for payload, key in items]))
                self._sched.note_range(len(items))
            first_key = items[0][1]
            try:
                t0 = time.perf_counter()
                self._task_ep.reply(chan, wire)
                global_timer.add("pool.dispatch",
                                 time.perf_counter() - t0)
                # One billed frame for the whole range: billed wire
                # must equal actual wire (Pool.cost() reconciliation).
                self._bill_frame(first_key[0], tx=len(wire),
                                 dispatch_s=time.perf_counter() - t0)
                _m_chunks_dispatched.inc(len(items))
                if FLIGHT.enabled:
                    FLIGHT.record("pool", "dispatch", seq=first_key[0],
                                  base=first_key[1],
                                  ident=ident.hex()[:8],
                                  chunks=len(items))
                _g_queue_depth.set(self._taskq.qsize())
                # Service-time clock starts at the successful handout;
                # the speculation monitor ages these entries.
                for payload, key in items:
                    self._sched.dispatched(key, ident, host, payload)
            except (TransportClosed, OSError):
                # Requester died between asking and receiving; put the
                # chunks back for the next "ready" and keep serving.
                # Counted as resubmissions: same cause (worker death),
                # different observation path than the pending reclaim.
                with self._pending_lock:
                    table = self._pending.get(ident, {})
                    for _, key in items:
                        table.pop(key, None)
                for it in items:
                    self._taskq.put(it)
                self._n_resubmitted += len(items)
                _m_chunks_resubmitted.inc(len(items))

        while True:
            # Re-evaluate parked requests first: results arriving or
            # chunks queueing since last turn may have cleared gates.
            for ident in list(parked):
                chan, pid = parked[ident]
                with self._pending_lock:
                    stale = (pid in self._reaped_pids
                             or ident in self._dead_idents)
                if stale or not chan.alive:
                    del parked[ident]
                    sync_parked()
                    if stale:
                        reply_exit(chan)
                    continue
                if drain_done():
                    del parked[ident]
                    sync_parked()
                    reply_exit(chan)
                    continue
                if self._gate_allows(ident):
                    del parked[ident]
                    sync_parked()
                    serve(ident, pid, chan)
            try:
                req, chan = self._task_ep.recv_req(timeout=0.5)
            except TimeoutError:
                if self._terminated:
                    return
                continue
            except (TransportClosed, OSError):
                return
            # Handout-control traffic no single map causes: the
            # explicit overhead bucket, never silently dropped.
            self._bill_frame(None, rx=len(req))
            msg = serialization.loads(req)
            if msg[0] != "ready":
                continue
            ident, fiber_pid = msg[1], msg[2]
            # 3-tuple readys predate the scheduler plane; the placement
            # host key rides as an optional 4th field (same back-compat
            # posture as the task envelope's trace context). A 5th field
            # of "hier" marks a per-host sub-master, whose handouts are
            # packed into chunk ranges.
            if len(msg) > 3:
                self._ident_hosts[ident] = msg[3]
            if len(msg) > 4 and msg[4] == "hier":
                self._hier_idents.add(ident)
            # A stale "ready" from a worker that was already reaped must
            # not receive (and thereby strand) a task: its pending table is
            # gone and nobody would ever resubmit the chunk. Same for an
            # ident whose sub-worker death was already processed.
            with self._pending_lock:
                stale = (fiber_pid in self._reaped_pids
                         or ident in self._dead_idents)
            if stale:
                reply_exit(chan)
                continue
            with self._pending_lock:
                self._pending.setdefault(ident, {})
                self._pid_to_idents.setdefault(fiber_pid, set()).add(ident)
            if self._terminated:
                return
            if drain_done():
                reply_exit(chan)
                continue
            if self._gate_allows(ident):
                serve(ident, fiber_pid, chan)
            else:
                parked[ident] = (chan, fiber_pid)
                sync_parked()

    def _on_result(self, seq, base, values, ident) -> None:
        # Scheduler bookkeeping first: the first result retires every
        # in-flight copy of the chunk (speculation's first-result-wins;
        # the loser's late duplicate is a no-op here and its values are
        # deduped by ResultStore.fill) and contributes the service-time
        # sample + organic locality knowledge.
        self._sched.completed((seq, base), ident,
                              self._ident_hosts.get(ident))
        with self._pending_lock:
            table = self._pending.get(ident)
            if table is not None:
                table.pop((seq, base), None)
            # Completed chunks can't be poison; drop any death count so
            # the table stays bounded by in-flight chunks. Progress on
            # a map also clears its no-progress death streak.
            self._chunk_deaths.pop((seq, base), None)
            self._seq_deaths.pop(seq, None)
        # A completed chunk can clear a parked request's gate (the
        # requester is now idle) — nudge the handout loop instead of
        # letting it notice at its next recv timeout. Skipped entirely
        # while nothing is parked (the hot path of a plentiful-chunk
        # map must not pay an inbox put per result).
        if self._parked_count:
            # Narrow except: shutdown races only (see submit-side twin).
            try:
                self._task_ep.wake()
            except (TransportClosed, OSError):
                pass

    def _on_store_miss(self, seq, base, n, ident) -> None:
        """Resilient twist on the inline resend: the reporting worker's
        pending entry for this chunk is retired first, so a later death
        of that worker doesn't also resubmit the ref-bearing payload it
        couldn't resolve (dedup would absorb it, but the doomed handout
        would burn a fetch cycle). New chunks can clear parked
        requests' reservation gates — nudge the handout loop."""
        self._sched.abandon((seq, base), ident)
        with self._pending_lock:
            table = self._pending.get(ident)
            if table is not None:
                table.pop((seq, base), None)
        super()._on_store_miss(seq, base, n, ident)
        if self._parked_count:
            try:
                self._task_ep.wake()
            except (TransportClosed, OSError):
                pass

    def _reclaim_ident(self, ident: bytes) -> int:
        """Retire one sub-worker ident: block future handouts to it, drop
        its bookkeeping, and requeue whatever it still owed. Returns the
        number of chunks resubmitted. Duplicate executions this can cause
        are safe: resilient-pool tasks must be idempotent and duplicate
        results are deduped by ResultStore.fill."""
        if self._detector is not None:
            # Death observed (or declared): the detector must never
            # post-mortem-suspect this ident, and late beats from a
            # not-actually-dead declaree must not resurrect it.
            self._detector.forget(ident)
        # Scheduler: the dead ident's chunk copies stop aging (their
        # payloads re-enter the queue below; a copy whose chunk already
        # completed — e.g. a speculation winner beat the death — is
        # dropped at put() instead of burning another worker).
        self._sched.abandon_ident(ident)
        self._ident_hosts.pop(ident, None)
        with self._pending_lock:
            self._mark_ident_dead(ident)
            table = self._pending.pop(ident, {})
            for idents in self._pid_to_idents.values():
                idents.discard(ident)
            resubmit = []
            poisoned = []
            # Death attribution is a heuristic: the OLDEST held chunk
            # (handout order = dict insertion order) is the one being
            # executed — or the only one, when a payload dies during
            # decode. Younger staged chunks are bystanders and are NOT
            # counted; a staged decode-poison gets counted on a later
            # cycle when it lands first. A false positive needs one
            # innocent chunk to be the oldest across CAP+1 consecutive
            # deaths without ever completing — and the error is direct
            # and catchable either way.
            counted = False
            for key, payload in table.items():
                if self._store.is_done(key[0]):
                    # Leftover of a completed/already-failed map: no
                    # counting (no result will ever pop the entries)
                    # and no resubmission.
                    self._chunk_deaths.pop(key, None)
                    self._seq_deaths.pop(key[0], None)
                    continue
                if not counted:
                    counted = True
                    deaths = self._chunk_deaths.get(key, 0) + 1
                    self._chunk_deaths[key] = deaths
                    seq_deaths = self._seq_deaths.get(key[0], 0) + 1
                    self._seq_deaths[key[0]] = seq_deaths
                    if (deaths > _POISON_CAP
                            or seq_deaths > 3 * _POISON_CAP):
                        poisoned.append(key)
                        self._chunk_deaths.pop(key, None)
                        self._seq_deaths.pop(key[0], None)
                        continue
                resubmit.append((payload, key))
        # Fail poisoned maps BEFORE requeueing, so this very call's
        # bystander chunks of a just-poisoned seq are dropped by the
        # is_done filter instead of burning further workers.
        for seq, base in poisoned:
            logger.error(
                "map seq=%d is killing workers without progress "
                "(latest culprit chunk base=%d) — failing it as poison",
                seq, base)
            self._store.fail(
                seq,
                PoisonChunkError(
                    f"map chunks keep killing workers with no progress "
                    f"(last culprit at base {base}; does the task "
                    "function/payload deserialize and run in the "
                    "worker?)"),
                reason="poison chunk", direct=True)
        requeued = 0
        for payload, key in resubmit:
            if self._store.is_done(key[0]):
                continue  # e.g. failed by this call's poison path
            self._taskq.put((payload, key))
            requeued += 1
        if requeued:
            self._n_resubmitted += requeued
            _m_chunks_resubmitted.inc(requeued)
            FLIGHT.record("pool", "resubmit", ident=ident.hex()[:8],
                          chunks=requeued,
                          reason="worker death / suspect reclaim")
        return requeued

    def _on_subworker_death(self, ident: bytes) -> None:
        """Resubmit one crashed sub-worker's pending chunks while its job
        keeps running (finer-grained than the reference, whose blast
        radius with cpu_per_job>1 is the whole job: fiber/pool.py:1612-1659
        only fires on job death). The packing parent respawns the
        sub-worker in place, so capacity is repaired too."""
        n = self._reclaim_ident(ident)
        if n:
            logger.info("resubmitted %d chunks from dead sub-worker", n)

    def _on_subworker_gone(self, ident: bytes) -> None:
        """A packed sub-worker retired cleanly (maxtasksperchild): drop its
        bookkeeping (normally empty; a crash-at-exit loses nothing)."""
        self._reclaim_ident(ident)

    def _on_worker_death(self, proc) -> None:
        """Resubmit everything the dead worker still owed
        (reference: fiber/pool.py:1612-1659) — through the same
        poison-counting reclaim as sub-worker death, so a chunk that
        kills whole workers escalates identically."""
        pid = proc.pid
        if (getattr(proc, "_n_local", 1) > 1
                and self._dispatch_mode == "hier"
                and not self._hier_degraded):
            # A dead packed job under hierarchical dispatch was a
            # sub-master. Its pending range is reclaimed below like any
            # death, but the REPLACEMENT jobs run direct per-worker
            # dispatch: repeated sub-master loss must converge on the
            # proven path, not crash-loop the hierarchy.
            self._hier_degraded = True
            logger.warning(
                "hier: sub-master job %s died; degrading this pool to "
                "direct per-worker dispatch", proc.name)
            FLIGHT.record("hier", "degrade", job=proc.name,
                          reason="sub-master death; respawns use "
                                 "direct dispatch")
        with self._pending_lock:
            self._reaped_pids.add(pid)
            idents = self._pid_to_idents.pop(pid, set())
        n = sum(self._reclaim_ident(ident) for ident in idents)
        if n:
            logger.info(
                "resubmitted %d chunks from dead worker %s",
                n, proc.name,
            )


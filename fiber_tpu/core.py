"""Core abstractions: Backend interface, JobSpec, Job, ProcessStatus.

This is the load-bearing seam of the whole framework (reference parity:
fiber/core.py:18-113). Everything above it — Process, Pool, Managers, Ring,
the CLI — only ever talks to a Backend through these six methods, which is
what makes the test suite's fault injection a five-line subclass and lets
the same user program run on local subprocesses, a simulated multi-host
cluster, or a real TPU pod slice unchanged.
"""

from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional, Sequence, Tuple

class ProcessStatus(enum.Enum):
    INITIAL = 0
    STARTED = 1
    STOPPED = 2


class JobSpec:
    """Everything a backend needs to start one job (one framework process).

    Reference parity: fiber/core.py:28-57.
    """

    def __init__(
        self,
        command: Sequence[str],
        image: Optional[str] = None,
        name: str = "fiber-tpu-job",
        cpu: Optional[int] = None,
        mem: Optional[int] = None,
        gpu: Optional[int] = None,
        tpu: Optional[int] = None,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        host_hint: Optional[str] = None,
    ) -> None:
        # NOTE: the reference JobSpec carries ``volumes`` (k8s PVCs,
        # fiber/core.py:46-51). fiber_tpu deliberately has no such field:
        # code rides the staging plane (utils/staging.py), artifacts ride
        # ``fiber-tpu cp`` or shared storage mounted outside the
        # framework — docs/migration.md.
        self.command = list(command)
        self.image = image
        self.name = name
        self.cpu = cpu
        self.mem = mem
        self.gpu = gpu
        self.tpu = tpu
        self.env = dict(env or {})
        self.cwd = cwd
        # Placement hint for multi-host backends (e.g. pin to pod host k).
        self.host_hint = host_hint

    def __repr__(self) -> str:
        return (
            f"JobSpec(name={self.name!r}, cpu={self.cpu}, mem={self.mem}, "
            f"tpu={self.tpu}, host_hint={self.host_hint!r})"
        )


class Job:
    """Handle to a created job. ``data`` is backend-private (a Popen object,
    a TPU-VM worker descriptor, ...). Reference parity: fiber/core.py:60-76.
    """

    def __init__(self, data: Any, jid: Any) -> None:
        self.data = data
        self.jid = jid
        self.host: Optional[str] = None
        self.update()

    def update(self) -> None:
        """Refresh cached fields (host/ip) from backend data."""


class Backend:
    """Abstract scheduler driver — the six-method interface.

    Reference parity: fiber/core.py:79-113. Subclass and override all six;
    tests inject faults by subclassing and breaking ``create_job``.
    """

    name = "abstract"

    def create_job(self, job_spec: JobSpec) -> Job:
        raise NotImplementedError

    def get_job_status(self, job: Job) -> ProcessStatus:
        raise NotImplementedError

    def get_job_logs(self, job: Job) -> str:
        raise NotImplementedError

    def wait_for_job(self, job: Job, timeout: Optional[float]) -> Optional[int]:
        """Block until the job exits; return exit code (None on timeout)."""
        raise NotImplementedError

    def terminate_job(self, job: Job) -> None:
        raise NotImplementedError

    def kill_job(self, job: Job) -> None:
        """Force-kill (SIGKILL semantics). Defaults to terminate_job for
        backends without a distinct hard-kill path."""
        self.terminate_job(job)

    def get_listen_addr(self) -> Tuple[str, int, str]:
        """(ip, port, ifname) other processes of this tree should dial.
        port==0 means "caller picks a random port"."""
        raise NotImplementedError

    # --- optional capabilities -------------------------------------------
    def list_jobs(self) -> List[Job]:  # pragma: no cover - optional
        """Live jobs created by this backend (leak-check fixture support)."""
        return []

    def stage_code(self, digest: str, files) -> bool:
        """Distribute a content-addressed workspace snapshot to every host
        (``files`` = [(relpath, bytes, mode), ...]). Returns True when the
        snapshot is available cluster-wide under the agents' staging roots
        (``{FIBER_STAGING}/code/<digest>``); False = backend has no remote
        hosts, nothing to do. The Docker-image role of the reference
        (fiber/cli.py:218-414) without a container registry."""
        return False

    def child_env(self) -> Dict[str, str]:
        """Extra environment for spawned jobs (e.g. resolved cluster
        addresses so children dial the parent's cluster instead of
        re-deriving their own)."""
        return {}

    def child_config(self) -> Dict[str, Any]:
        """Config-key overrides shipped to children in the preparation
        data, merged over the parent's resolved config."""
        return {}

    def default_pool_size(self) -> int:
        """Natural Pool(None) size for this substrate. Local: CPU count;
        multi-host backends: one worker per host (SURVEY.md §2 packing:
        one framework process per TPU-VM host drives that host's
        devices; cpu_per_job then packs sub-workers within it)."""
        import os

        return os.cpu_count() or 4

"""Tiny transformer LM over the sequence-parallel attention planes.

The reference framework has no model-training story at all (it is a
task-parallel library); this module is the beyond-parity demonstration
that fiber_tpu's long-context planes — ring attention
(:func:`fiber_tpu.ops.ring_attention`) and Ulysses
(:func:`fiber_tpu.ops.ulysses_attention`) — are not inference toys: a
causal LM trains through them with jax AD (their gradients match
full-matrix attention; tests/test_device.py pins that), with the
sequence axis sharded over the mesh so context length scales with
device count.

Deliberately small and dependency-free (pure jnp pytree params, no
flax): the framework's flagship workloads are population-based, and
this exists to prove the sequence-parallel plane end to end —
embedding -> [RMSNorm -> attention -> residual -> RMSNorm -> MLP ->
residual] x L -> norm -> logits.
"""

from __future__ import annotations

from typing import Optional


class TinyLM:
    """Causal byte/token LM. ``attention`` picks the plane:
    ``"ring"`` (sequence sharded via ppermute ring + online softmax),
    ``"ulysses"`` (all-to-all head/seq swap; needs
    ``heads % n_devices == 0``), ``"flash"`` (the Pallas
    flash-attention kernels, forward AND backward — single device runs
    them directly with the whole sequence in HBM and scores streamed
    through VMEM; pass a multi-device ``mesh=`` and the sequence
    shards over the ring with the kernel as every rotation's
    per-device block), or
    ``"reference"`` (full score matrix, single device — for parity
    tests).

    ``apply(params, tokens (S,)) -> (S, vocab)`` logits;
    ``loss(params, tokens)`` is mean next-token cross-entropy.
    ``S`` must equal ``max_seq`` (static shapes; pad shorter text).
    """

    def __init__(
        self,
        vocab: int = 256,
        dim: int = 64,
        heads: int = 8,
        layers: int = 2,
        max_seq: int = 256,
        mlp_mult: int = 4,
        mesh=None,
        attention: str = "ring",
        kv_heads: Optional[int] = None,
    ) -> None:
        if dim % heads:
            raise ValueError(f"dim {dim} not divisible by heads {heads}")
        if attention not in ("ring", "ulysses", "flash", "reference"):
            raise ValueError(f"unknown attention {attention!r}")
        if kv_heads is not None and kv_heads < 1:
            # 0 must not silently mean "full MHA" (a GQA A/B would
            # quietly measure nothing) and negatives pass Python's
            # modulo only to crash deep inside init().
            raise ValueError(f"kv_heads must be >= 1, got {kv_heads}")
        kv_heads = kv_heads or heads
        if heads % kv_heads:
            raise ValueError(
                f"heads {heads} not divisible by kv_heads {kv_heads}")
        self._flash_multi = False
        if mesh is not None:
            import numpy as np

            multi = int(np.prod(list(mesh.shape.values()))) > 1
            if multi and "pool" not in mesh.shape:
                # Loud, at construction: the sequence-parallel planes
                # shard over the mesh's "pool" axis — without this
                # check the mistake surfaces as a KeyError deep inside
                # the first apply().
                raise ValueError(
                    "multi-device TinyLM needs a mesh with a 'pool' "
                    f"axis; got axes {tuple(mesh.shape)}")
            if multi and attention == "flash":
                # Multi-device flash = ring attention with the Pallas
                # kernel as the per-device block: the sequence shards
                # over the mesh AND every rotation streams scores
                # through VMEM (ring_attention local="flash").
                self._flash_multi = True
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        # kv_heads < heads is grouped-query attention: the flash plane
        # reads the small KV natively (kernel index maps share KV
        # blocks across each query group); the XLA planes broadcast KV
        # to full heads at attend time (compute identical, memory not
        # saved there — GQA's KV-cache/HBM win is a kernel property).
        self.kv_heads = kv_heads
        self.head_dim = dim // heads
        self.layers = layers
        self.max_seq = max_seq
        self.mlp_mult = mlp_mult
        self.attention = attention
        self._mesh = mesh

    # ------------------------------------------------------------------
    def init(self, key) -> dict:
        import jax
        import jax.numpy as jnp

        k_emb, k_pos, k_out, key = jax.random.split(key, 4)
        scale = 0.02
        params = {
            "embed": scale * jax.random.normal(
                k_emb, (self.vocab, self.dim)),
            "pos": scale * jax.random.normal(
                k_pos, (self.max_seq, self.dim)),
            "out": scale * jax.random.normal(
                k_out, (self.dim, self.vocab)),
            "final_norm": jnp.ones((self.dim,)),
            "blocks": [],
        }
        for _ in range(self.layers):
            keys = jax.random.split(key, 7)
            key = keys[6]
            d, h = self.dim, self.mlp_mult * self.dim
            blk = {
                "norm1": jnp.ones((d,)),
                "wo": scale * jax.random.normal(keys[1], (d, d)),
                "norm2": jnp.ones((d,)),
                "w1": scale * jax.random.normal(keys[2], (d, h)),
                "b1": jnp.zeros((h,)),
                "w2": scale * jax.random.normal(keys[3], (h, d)),
                "b2": jnp.zeros((d,)),
            }
            if self.kv_heads == self.heads:
                blk["wqkv"] = scale * jax.random.normal(
                    keys[0], (d, 3 * d))
            else:
                kv_dim = self.kv_heads * self.head_dim
                blk["wq"] = scale * jax.random.normal(keys[0], (d, d))
                blk["wkv"] = scale * jax.random.normal(
                    keys[4], (d, 2 * kv_dim))
            params["blocks"].append(blk)
        return params

    # ------------------------------------------------------------------
    def _attend(self, q, k, v):
        if k.shape[1] != q.shape[1] and self.attention != "flash":
            # GQA on the XLA planes: broadcast KV to full heads (repeat
            # order matches the kernel's ih // group sharing). Only the
            # flash kernels read the small KV natively.
            import jax.numpy as jnp

            reps = q.shape[1] // k.shape[1]
            k = jnp.repeat(k, reps, axis=1)
            v = jnp.repeat(v, reps, axis=1)
        if self.attention == "reference":
            from fiber_tpu.ops.ring_attention import reference_attention

            return reference_attention(q, k, v, causal=True)
        if self.attention == "flash":
            from fiber_tpu.ops.pallas_attention import (
                flash_attention,
                flash_available,
            )

            # Interpreter off-TPU so parity tests run anywhere; the
            # kernel proper needs Mosaic.
            if self._flash_multi:
                from fiber_tpu.ops.ring_attention import ring_attention

                return ring_attention(
                    q, k, v, mesh=self._mesh, causal=True,
                    local="flash", interpret=not flash_available())
            return flash_attention(q, k, v, causal=True,
                                   interpret=not flash_available())
        if self.attention == "ulysses":
            from fiber_tpu.ops.ulysses_attention import ulysses_attention

            return ulysses_attention(q, k, v, mesh=self._mesh,
                                     causal=True)
        from fiber_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh=self._mesh, causal=True)

    @staticmethod
    def _rms(x, g):
        import jax.numpy as jnp

        return g * x / jnp.sqrt(
            jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)

    def apply(self, params, tokens):
        """tokens (max_seq,) int -> logits (max_seq, vocab)."""
        import jax
        import jax.numpy as jnp

        S, H, Dh = self.max_seq, self.heads, self.head_dim
        KVH = self.kv_heads
        x = params["embed"][tokens] + params["pos"]          # (S, dim)
        for blk in params["blocks"]:
            h = self._rms(x, blk["norm1"])
            if KVH == H:
                qkv = h @ blk["wqkv"]                        # (S, 3*dim)
                q, k, v = jnp.split(qkv, 3, axis=-1)
                k = k.reshape(S, H, Dh)
                v = v.reshape(S, H, Dh)
            else:
                q = h @ blk["wq"]                            # (S, dim)
                kv = h @ blk["wkv"]                          # (S, 2*kvd)
                k, v = jnp.split(kv, 2, axis=-1)
                k = k.reshape(S, KVH, Dh)
                v = v.reshape(S, KVH, Dh)
            q = q.reshape(S, H, Dh)
            attn = self._attend(q, k, v).reshape(S, -1)
            x = x + attn @ blk["wo"]
            h = self._rms(x, blk["norm2"])
            x = x + jax.nn.gelu(h @ blk["w1"] + blk["b1"]) @ blk["w2"] \
                + blk["b2"]
        x = self._rms(x, params["final_norm"])
        return x @ params["out"]

    def loss(self, params, tokens):
        """Mean next-token cross-entropy over positions 0..S-2."""
        import jax
        import jax.numpy as jnp

        logits = self.apply(params, tokens)[:-1]             # (S-1, V)
        targets = tokens[1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, targets[:, None], axis=1))


def make_train_step(model: TinyLM, optimizer, batched: bool = False):
    """(params, opt_state, tokens) -> (params, opt_state, loss), jitted.
    ``optimizer`` is any optax-style (init, update) pair. With
    ``batched=True`` tokens is (B, max_seq) and the loss is the batch
    mean — the batch axis vmaps straight over the sequence-sharded
    attention (each sequence still spans the mesh)."""
    import jax

    if batched:
        def loss_fn(params, tokens):
            import jax.numpy as jnp

            return jnp.mean(
                jax.vmap(lambda t: model.loss(params, t))(tokens))
    else:
        loss_fn = model.loss

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        # Plain tree-map instead of optax.apply_updates: the optimizer
        # only needs the (init, update) protocol — no hard optax
        # dependency in the library (it isn't in install_requires).
        params = jax.tree_util.tree_map(
            lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return jax.jit(step)

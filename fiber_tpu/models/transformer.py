"""Tiny transformer LM over the sequence-parallel attention planes.

The reference framework has no model-training story at all (it is a
task-parallel library); this module is the beyond-parity demonstration
that fiber_tpu's long-context planes — ring attention
(:func:`fiber_tpu.ops.ring_attention`) and Ulysses
(:func:`fiber_tpu.ops.ulysses_attention`) — are not inference toys: a
causal LM trains through them with jax AD (their gradients match
full-matrix attention; tests/test_device.py pins that), with the
sequence axis sharded over the mesh so context length scales with
device count.

Deliberately small and dependency-free (pure jnp pytree params, no
flax): the framework's flagship workloads are population-based, and
this exists to prove the sequence-parallel plane end to end —
embedding -> [RMSNorm -> attention -> residual -> RMSNorm -> MLP ->
residual] x L -> norm -> logits.
"""

from __future__ import annotations

from typing import Optional


class TinyLM:
    """Causal byte/token LM. ``attention`` picks the plane:
    ``"ring"`` (sequence sharded via ppermute ring + online softmax),
    ``"ulysses"`` (all-to-all head/seq swap; needs
    ``heads % n_devices == 0``), ``"flash"`` (the Pallas
    flash-attention kernels, forward AND backward — single device runs
    them directly with the whole sequence in HBM and scores streamed
    through VMEM; pass a multi-device ``mesh=`` and the sequence
    shards over the ring with the kernel as every rotation's
    per-device block), or
    ``"reference"`` (full score matrix, single device — for parity
    tests).

    ``pos`` picks the positional scheme: ``"learned"`` (absolute
    table, the default) or ``"rope"`` (rotary embeddings on q/k per
    layer — relative positions, the modern long-context choice; no
    position table in the params).

    ``apply(params, tokens (S,)) -> (S, vocab)`` logits;
    ``loss(params, tokens)`` is mean next-token cross-entropy.
    ``S`` must equal ``max_seq`` (static shapes; pad shorter text).
    """

    def __init__(
        self,
        vocab: int = 256,
        dim: int = 64,
        heads: int = 8,
        layers: int = 2,
        max_seq: int = 256,
        mlp_mult: int = 4,
        mesh=None,
        attention: str = "ring",
        kv_heads: Optional[int] = None,
        pos: str = "learned",
        window: Optional[int] = None,
    ) -> None:
        if dim % heads:
            raise ValueError(f"dim {dim} not divisible by heads {heads}")
        if attention not in ("ring", "ulysses", "flash", "reference"):
            raise ValueError(f"unknown attention {attention!r}")
        if pos not in ("learned", "rope"):
            raise ValueError(f"unknown positional scheme {pos!r}")
        if pos == "rope" and (dim // heads) % 2:
            raise ValueError("rope needs an even head_dim")
        if window is not None:
            if window < 1:
                raise ValueError(f"window must be >= 1, got {window}")
            if attention != "flash":
                # The window lives in the flash kernels' block-skip
                # grid; the XLA planes have no windowed engine and
                # silently ignoring it would train a different model.
                raise ValueError(
                    "window= needs attention='flash' (the sliding "
                    "window is a kernel feature)")
        if kv_heads is not None and kv_heads < 1:
            # 0 must not silently mean "full MHA" (a GQA A/B would
            # quietly measure nothing) and negatives pass Python's
            # modulo only to crash deep inside init().
            raise ValueError(f"kv_heads must be >= 1, got {kv_heads}")
        kv_heads = kv_heads or heads
        if heads % kv_heads:
            raise ValueError(
                f"heads {heads} not divisible by kv_heads {kv_heads}")
        self._flash_multi = False
        if mesh is not None:
            import numpy as np

            multi = int(np.prod(list(mesh.shape.values()))) > 1
            if multi and "pool" not in mesh.shape:
                # Loud, at construction: the sequence-parallel planes
                # shard over the mesh's "pool" axis — without this
                # check the mistake surfaces as a KeyError deep inside
                # the first apply().
                raise ValueError(
                    "multi-device TinyLM needs a mesh with a 'pool' "
                    f"axis; got axes {tuple(mesh.shape)}")
            if multi and attention == "flash":
                # Multi-device flash = ring attention with the Pallas
                # kernel as the per-device block: the sequence shards
                # over the mesh AND every rotation streams scores
                # through VMEM (ring_attention local="flash").
                self._flash_multi = True
        if window is not None and self._flash_multi:
            raise ValueError(
                "window= is single-device (a windowed partial's lse "
                "is not ring-mergeable); drop the mesh or the window")
        self.vocab = vocab
        self.dim = dim
        self.heads = heads
        # kv_heads < heads is grouped-query attention: the flash plane
        # reads the small KV natively (kernel index maps share KV
        # blocks across each query group); the XLA planes broadcast KV
        # to full heads at attend time (compute identical, memory not
        # saved there — GQA's KV-cache/HBM win is a kernel property).
        self.kv_heads = kv_heads
        self.head_dim = dim // heads
        self.layers = layers
        self.max_seq = max_seq
        self.mlp_mult = mlp_mult
        self.attention = attention
        # "learned": absolute position table added to embeddings.
        # "rope": rotary embeddings applied to q/k per attention layer
        # (relative positions; the modern long-context default — decays
        # gracefully past training lengths where a learned table ends).
        self.pos = pos
        #: causal sliding window (flash plane only; None = full causal)
        self.window = window
        self._mesh = mesh

    # ------------------------------------------------------------------
    def init(self, key) -> dict:
        import jax
        import jax.numpy as jnp

        k_emb, k_pos, k_out, key = jax.random.split(key, 4)
        scale = 0.02
        params = {
            "embed": scale * jax.random.normal(
                k_emb, (self.vocab, self.dim)),
            "out": scale * jax.random.normal(
                k_out, (self.dim, self.vocab)),
            "final_norm": jnp.ones((self.dim,)),
            "blocks": [],
        }
        if self.pos == "learned":
            params["pos"] = scale * jax.random.normal(
                k_pos, (self.max_seq, self.dim))
        for _ in range(self.layers):
            keys = jax.random.split(key, 7)
            key = keys[6]
            d, h = self.dim, self.mlp_mult * self.dim
            blk = {
                "norm1": jnp.ones((d,)),
                "wo": scale * jax.random.normal(keys[1], (d, d)),
                "norm2": jnp.ones((d,)),
                "w1": scale * jax.random.normal(keys[2], (d, h)),
                "b1": jnp.zeros((h,)),
                "w2": scale * jax.random.normal(keys[3], (h, d)),
                "b2": jnp.zeros((d,)),
            }
            if self.kv_heads == self.heads:
                blk["wqkv"] = scale * jax.random.normal(
                    keys[0], (d, 3 * d))
            else:
                kv_dim = self.kv_heads * self.head_dim
                blk["wq"] = scale * jax.random.normal(keys[0], (d, d))
                blk["wkv"] = scale * jax.random.normal(
                    keys[4], (d, 2 * kv_dim))
            params["blocks"].append(blk)
        return params

    # ------------------------------------------------------------------
    def _attend(self, q, k, v):
        if k.shape[1] != q.shape[1] and self.attention != "flash":
            # GQA on the XLA planes: broadcast KV to full heads (repeat
            # order matches the kernel's ih // group sharing). Only the
            # flash kernels read the small KV natively.
            import jax.numpy as jnp

            reps = q.shape[1] // k.shape[1]
            k = jnp.repeat(k, reps, axis=1)
            v = jnp.repeat(v, reps, axis=1)
        if self.attention == "reference":
            from fiber_tpu.ops.ring_attention import reference_attention

            return reference_attention(q, k, v, causal=True)
        if self.attention == "flash":
            from fiber_tpu.ops.pallas_attention import (
                flash_attention,
                flash_available,
            )

            # Interpreter off-TPU so parity tests run anywhere; the
            # kernel proper needs Mosaic.
            if self._flash_multi:
                from fiber_tpu.ops.ring_attention import ring_attention

                return ring_attention(
                    q, k, v, mesh=self._mesh, causal=True,
                    local="flash", interpret=not flash_available())
            return flash_attention(q, k, v, causal=True,
                                   window=self.window,
                                   interpret=not flash_available())
        if self.attention == "ulysses":
            from fiber_tpu.ops.ulysses_attention import ulysses_attention

            return ulysses_attention(q, k, v, mesh=self._mesh,
                                     causal=True)
        from fiber_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh=self._mesh, causal=True)

    @staticmethod
    def _rms(x, g):
        import jax.numpy as jnp

        return g * x / jnp.sqrt(
            jnp.mean(x * x, axis=-1, keepdims=True) + 1e-6)

    def _project_qkv(self, blk, h):
        """Pre-attention projections, flat head layout. Works on (S,
        dim) rows and single (dim,) vectors alike — SHARED by apply()
        and _decode_step() so the block structure cannot silently
        diverge between the training and decode paths."""
        import jax.numpy as jnp

        if self.kv_heads == self.heads:
            return jnp.split(h @ blk["wqkv"], 3, axis=-1)
        q = h @ blk["wq"]
        k, v = jnp.split(h @ blk["wkv"], 2, axis=-1)
        return q, k, v

    @staticmethod
    def _rope_angles(positions, dh):
        """cos/sin tables for rotary embeddings at ``positions``
        (scalar or (S,)): shape (..., dh/2), base 10000."""
        import jax.numpy as jnp

        inv = 1.0 / (10000.0 ** (jnp.arange(0, dh, 2) / dh))
        ang = jnp.asarray(positions, jnp.float32)[..., None] * inv
        return jnp.cos(ang), jnp.sin(ang)

    @staticmethod
    def _rope_rotate(x, cos, sin):
        """Rotate feature pairs (half-split convention); cos/sin
        broadcast against x's leading axes. The result keeps x's dtype:
        f32 cos/sin must not silently promote a bf16 stream (which
        would also let decode's cache cast rotated keys back DOWN,
        drifting incremental decode away from full-apply)."""
        import jax.numpy as jnp

        x1, x2 = jnp.split(x, 2, axis=-1)
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin],
            axis=-1).astype(x.dtype)

    def _block_tail(self, blk, x, attn_flat):
        """Post-attention residual + MLP (shared like _project_qkv)."""
        import jax

        x = x + attn_flat @ blk["wo"]
        h = self._rms(x, blk["norm2"])
        return x + jax.nn.gelu(h @ blk["w1"] + blk["b1"]) @ blk["w2"] \
            + blk["b2"]

    def apply(self, params, tokens):
        """tokens (max_seq,) int -> logits (max_seq, vocab)."""
        import jax.numpy as jnp

        S, H, Dh = self.max_seq, self.heads, self.head_dim
        KVH = self.kv_heads
        x = params["embed"][tokens]                          # (S, dim)
        rope = None
        if self.pos == "learned":
            x = x + params["pos"]
        else:
            cos, sin = self._rope_angles(jnp.arange(S), Dh)  # (S, dh/2)
            rope = (cos[:, None, :], sin[:, None, :])
        for blk in params["blocks"]:
            h = self._rms(x, blk["norm1"])
            q, k, v = self._project_qkv(blk, h)
            q = q.reshape(S, H, Dh)
            k = k.reshape(S, KVH, Dh)
            v = v.reshape(S, KVH, Dh)
            if rope is not None:
                q = self._rope_rotate(q, *rope)
                k = self._rope_rotate(k, *rope)
            attn = self._attend(q, k, v).reshape(S, -1)
            x = self._block_tail(blk, x, attn)
        x = self._rms(x, params["final_norm"])
        return x @ params["out"]

    def loss(self, params, tokens):
        """Mean next-token cross-entropy over positions 0..S-2."""
        import jax
        import jax.numpy as jnp

        logits = self.apply(params, tokens)[:-1]             # (S-1, V)
        targets = tokens[1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(
            jnp.take_along_axis(logp, targets[:, None], axis=1))

    # ------------------------------------------------------------------
    # Inference: autoregressive decode with per-layer KV caches.
    # ------------------------------------------------------------------
    def _decode_step(self, params, caches, pos, tok):
        """One incremental position: returns (new_caches, logits).

        caches: per block {"k": (S, kv_heads, Dh), "v": same} — only
        rows [0, pos] are valid; this step writes row ``pos`` and
        attends q against the masked cache. O(S) per step with static
        shapes (jit/scan friendly), single device — decode is a
        latency path, not a sharded-compute path.
        """
        import jax
        import jax.numpy as jnp

        H, KVH, Dh = self.heads, self.kv_heads, self.head_dim
        group = H // KVH
        x = params["embed"][tok]                             # (dim,)
        rope = None
        if self.pos == "learned":
            x = x + params["pos"][pos]
        else:
            rope = self._rope_angles(pos, Dh)                # (dh/2,)
        new_caches = []
        for blk, cache in zip(params["blocks"], caches):
            h = self._rms(x, blk["norm1"])
            q, k, v = self._project_qkv(blk, h)
            q = q.reshape(KVH, group, Dh)
            k = k.reshape(KVH, Dh)
            if rope is not None:
                # Rotate q and k at THIS position; the cache stores
                # post-rotation keys (standard RoPE decode).
                q = self._rope_rotate(q, *rope)
                k = self._rope_rotate(k, *rope)
            k_cache = cache["k"].at[pos].set(k)
            v_cache = cache["v"].at[pos].set(v.reshape(KVH, Dh))
            new_caches.append({"k": k_cache, "v": v_cache})
            # (kvh, group, S) scores vs the whole cache, masked to
            # positions <= pos; f32 softmax statistics as everywhere.
            s = jnp.einsum("kgd,skd->kgs", q, k_cache,
                           preferred_element_type=jnp.float32)
            s = s / (Dh ** 0.5)
            kv_pos = jnp.arange(k_cache.shape[0])
            mask = kv_pos <= pos
            if self.window is not None:
                # A windowed model must decode windowed, or inference
                # silently runs a different model than training.
                mask = mask & (kv_pos > pos - self.window)
            s = jnp.where(mask[None, None, :], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("kgs,skd->kgd", p.astype(v_cache.dtype),
                              v_cache, preferred_element_type=jnp.float32)
            x = self._block_tail(blk, x, attn.astype(x.dtype).reshape(-1))
        x = self._rms(x, params["final_norm"])
        return new_caches, x @ params["out"]

    def generate(self, params, prompt, steps: int, key=None,
                 temperature: float = 0.0):
        """Decode ``steps`` tokens after ``prompt`` (1-D int array).
        Greedy at temperature 0 (default); otherwise samples with
        ``key``. Returns the (len(prompt) + steps,) token array. The
        whole prefill + decode runs as two ``lax.scan``s over the
        cached single-position step — one compiled program, no
        per-token dispatch. len(prompt) + steps must be <= max_seq."""
        import jax
        import jax.numpy as jnp

        prompt = jnp.asarray(prompt, jnp.int32)
        n_prompt = int(prompt.shape[0])
        if n_prompt < 1:
            raise ValueError("prompt must have at least one token")
        if n_prompt + steps > self.max_seq:
            raise ValueError(
                f"prompt ({n_prompt}) + steps ({steps}) exceeds "
                f"max_seq ({self.max_seq})")
        if temperature > 0.0 and key is None:
            raise ValueError("sampling (temperature > 0) needs a key")
        key = key if key is not None else jax.random.PRNGKey(0)

        S, KVH, Dh = self.max_seq, self.kv_heads, self.head_dim
        # Caches follow the params dtype — an f32 cache under bf16
        # params would silently double the KV-cache footprint, the very
        # memory GQA exists to save.
        cdtype = params["embed"].dtype
        caches = [
            {"k": jnp.zeros((S, KVH, Dh), cdtype),
             "v": jnp.zeros((S, KVH, Dh), cdtype)}
            for _ in params["blocks"]
        ]

        def prefill(carry, inp):
            caches = carry
            pos, tok = inp
            caches, logits = self._decode_step(params, caches, pos, tok)
            return caches, logits

        caches, logits_seq = jax.lax.scan(
            prefill, caches, (jnp.arange(n_prompt), prompt))

        def pick(logits, k):
            if temperature > 0.0:
                return jax.random.categorical(k, logits / temperature)
            return jnp.argmax(logits).astype(jnp.int32)

        def decode(carry, pos):
            caches, tok, k = carry
            k, k_step = jax.random.split(k)
            caches, logits = self._decode_step(params, caches, pos, tok)
            nxt = pick(logits, k_step).astype(jnp.int32)
            return (caches, nxt, k), nxt

        key, k_first = jax.random.split(key)  # use-once key discipline
        first = pick(logits_seq[-1], k_first).astype(jnp.int32)
        if steps <= 1:
            out = first[None][:steps]
        else:
            (_, _, _), rest = jax.lax.scan(
                decode, (caches, first, key),
                jnp.arange(n_prompt, n_prompt + steps - 1))
            out = jnp.concatenate([first[None], rest])
        return jnp.concatenate([prompt, out])


def make_train_step(model: TinyLM, optimizer, batched: bool = False):
    """(params, opt_state, tokens) -> (params, opt_state, loss), jitted.
    ``optimizer`` is any optax-style (init, update) pair. With
    ``batched=True`` tokens is (B, max_seq) and the loss is the batch
    mean — the batch axis vmaps straight over the sequence-sharded
    attention (each sequence still spans the mesh)."""
    import jax

    if batched:
        def loss_fn(params, tokens):
            import jax.numpy as jnp

            return jnp.mean(
                jax.vmap(lambda t: model.loss(params, t))(tokens))
    else:
        loss_fn = model.loss

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        # Plain tree-map instead of optax.apply_updates: the optimizer
        # only needs the (init, update) protocol — no hard optax
        # dependency in the library (it isn't in install_requires).
        params = jax.tree_util.tree_map(
            lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    jitted = jax.jit(step)
    if _needs_cpu_collective_serialization(model):
        # XLA CPU's in-process collectives can DEADLOCK when jax's
        # async dispatch interleaves two step-generations over the CPU
        # client's fixed thread pool: step k+1's per-device programs
        # park in their first rendezvous on threads step k's last
        # rendezvous still needs (core-dump-verified on the 1-core dev
        # box, RUNS/stest_abort_repro.md). Serializing steps on a CPU
        # mesh closes the window and costs nothing measurable there
        # (compute-bound); real TPU keeps full async dispatch.
        def step_sync(params, opt_state, tokens):
            out = jitted(params, opt_state, tokens)
            jax.block_until_ready(out)
            return out

        return step_sync
    return jitted


def _needs_cpu_collective_serialization(model) -> bool:
    """True when training steps run collectives across >1 virtual CPU
    device — the configuration where pipelined generations can
    deadlock XLA's in-process rendezvous (see make_train_step). The
    EFFECTIVE mesh matters: with ``mesh=None`` the ring/ulysses planes
    resolve the process-wide default mesh (all devices) at attend
    time, so a bare ``TinyLM(attention="ring")`` still runs 8-device
    collectives on the virtual CPU plane."""
    from fiber_tpu.parallel.mesh import default_mesh, is_multidevice_cpu

    mesh = getattr(model, "_mesh", None)
    if mesh is None and getattr(model, "attention", "") in (
            "ring", "ulysses"):
        mesh = default_mesh()
    return is_multidevice_cpu(mesh)

"""Pure-JAX environments: physics as jittable step functions, rollouts as
``lax.scan`` — the whole episode compiles into one XLA program with static
shapes (no Python in the loop), which is what lets a TPU evaluate whole
populations of policies in data-parallel lockstep.

CartPole matches the classic Gym CartPole-v1 dynamics (the north-star
OpenAI-ES workload, BASELINE.json configs); Pendulum is the continuous
control smoke env.
"""

from __future__ import annotations

from typing import Callable


def _survival_scan(step_fn, act_step_fn, state0, carry0, steps):
    """THE masked episode loop for survival-reward envs: +1 per step
    until termination, with static shapes (no early exit — finished
    episodes freeze their state and stop scoring). One implementation
    shared by every rollout variant so the masking/termination
    convention can't drift between them.

    ``act_step_fn(policy_carry, state) -> (policy_carry', action)``
    (stateless policies pass ``carry0=()``);
    ``step_fn(state, action) -> (state', terminated: bool)``.
    """
    import jax
    import jax.numpy as jnp

    def scan_step(carry, _):
        state, pc, done, total = carry
        new_pc, action = act_step_fn(pc, state)
        next_state, terminated = step_fn(state, action)
        reward = jnp.where(done, 0.0, 1.0)
        new_done = done | terminated
        # tree.map on BOTH freezes so pytree env states work the same
        # as pytree policy carries.
        keep_state = jax.tree.map(
            lambda old, new: jnp.where(done, old, new), state, next_state
        )
        keep_pc = jax.tree.map(
            lambda old, new: jnp.where(done, old, new), pc, new_pc
        )
        return (keep_state, keep_pc, new_done, total + reward), None

    (_, _, _, total), _ = jax.lax.scan(
        scan_step,
        (state0, carry0, jnp.asarray(False), jnp.asarray(0.0)),
        None, length=steps,
    )
    return total


class CartPole:
    obs_dim = 4
    act_dim = 2
    max_steps = 500

    # physics constants (Gym CartPole-v1)
    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5          # half pole length
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 3.141592653589793 / 180.0
    x_threshold = 2.4

    @classmethod
    def reset(cls, key):
        import jax

        return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)

    @classmethod
    def step(cls, state, action):
        """One physics step. action in {0, 1}. Returns (state, terminated)."""
        import jax.numpy as jnp

        x, x_dot, theta, theta_dot = state
        force = jnp.where(action == 1, cls.force_mag, -cls.force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = cls.masscart + cls.masspole
        polemass_length = cls.masspole * cls.length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (cls.gravity * sintheta - costheta * temp) / (
            cls.length * (4.0 / 3.0 - cls.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + cls.tau * x_dot
        x_dot = x_dot + cls.tau * xacc
        theta = theta + cls.tau * theta_dot
        theta_dot = theta_dot + cls.tau * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = (
            (jnp.abs(x) > cls.x_threshold)
            | (jnp.abs(theta) > cls.theta_threshold)
        )
        return new_state, terminated

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        """Total episode reward for a deterministic policy; fully jittable.

        ``act_fn(flat_params, obs) -> action``. Termination is handled by
        masking inside the scan (static shapes, no early exit).
        """
        steps = max_steps or cls.max_steps
        return _survival_scan(
            cls.step,
            lambda carry, state: (carry, act_fn(flat_params, state)),
            cls.reset(key), (), steps,
        )


class ParamCartPole(CartPole):
    """CartPole with mutable physics — the substrate for POET-style
    env/agent co-evolution (the reference's POET example evolves
    BipedalWalker terrains; here the evolvable environment parameters are
    the physics vector [gravity, pole_half_length, force_mag, masspole],
    harder configs = heavier/longer pole, weaker cart).

    ``env_params`` rides through rollouts as a jax array so a whole
    population of (env, agent) pairs can evaluate in one SPMD program.
    """

    #: default physics vector (matches CartPole-v1)
    DEFAULT = (9.8, 0.5, 10.0, 0.1)
    PARAM_LOW = (4.0, 0.25, 4.0, 0.05)
    PARAM_HIGH = (19.0, 1.5, 14.0, 0.6)

    @classmethod
    def step_p(cls, env_params, state, action):
        import jax.numpy as jnp

        gravity, length, force_mag, masspole = (
            env_params[0], env_params[1], env_params[2], env_params[3]
        )
        x, x_dot, theta, theta_dot = state
        force = jnp.where(action == 1, force_mag, -force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = cls.masscart + masspole
        polemass_length = masspole * length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + cls.tau * x_dot
        x_dot = x_dot + cls.tau * xacc
        theta = theta + cls.tau * theta_dot
        theta_dot = theta_dot + cls.tau * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = (
            (jnp.abs(x) > cls.x_threshold)
            | (jnp.abs(theta) > cls.theta_threshold)
        )
        return new_state, terminated

    @classmethod
    def rollout_p(cls, act_fn, env_params, flat_params, key,
                  max_steps: int | None = None):
        """Episode reward under a specific physics vector; jittable and
        vmappable over (env_params, flat_params) pairs."""
        steps = max_steps or cls.max_steps
        return _survival_scan(
            lambda state, action: cls.step_p(env_params, state, action),
            lambda carry, state: (carry, act_fn(flat_params, state)),
            cls.reset(key), (), steps,
        )

    @classmethod
    def mutate(cls, env_params, key, scale: float = 0.15):
        """Perturb the physics vector within bounds (POET env mutation)."""
        import jax
        import jax.numpy as jnp

        low = jnp.asarray(cls.PARAM_LOW)
        high = jnp.asarray(cls.PARAM_HIGH)
        noise = jax.random.normal(key, (4,)) * scale * (high - low)
        return jnp.clip(jnp.asarray(env_params) + noise, low, high)


class Pendulum:
    obs_dim = 3
    act_dim = 1
    max_steps = 200

    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    length = 1.0

    @classmethod
    def reset(cls, key):
        import jax
        import jax.numpy as jnp

        hi = jnp.asarray([3.141592653589793, 1.0])
        thetadot = jax.random.uniform(key, (2,), minval=-hi, maxval=hi)
        return thetadot  # (theta, theta_dot)

    @classmethod
    def obs(cls, state):
        import jax.numpy as jnp

        theta, theta_dot = state
        return jnp.stack([jnp.cos(theta), jnp.sin(theta), theta_dot])

    @classmethod
    def step(cls, state, torque):
        import jax.numpy as jnp

        theta, theta_dot = state
        u = jnp.clip(torque, -cls.max_torque, cls.max_torque)
        cost = (
            _angle_normalize(theta) ** 2
            + 0.1 * theta_dot**2
            + 0.001 * u**2
        )
        new_theta_dot = theta_dot + (
            3 * cls.g / (2 * cls.length) * jnp.sin(theta)
            + 3.0 / (cls.m * cls.length**2) * u
        ) * cls.dt
        new_theta_dot = jnp.clip(new_theta_dot, -cls.max_speed, cls.max_speed)
        new_theta = theta + new_theta_dot * cls.dt
        return jnp.stack([new_theta, new_theta_dot]), -cost

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        state0 = cls.reset(key)

        def scan_step(carry, _):
            state, total = carry
            torque = act_fn(flat_params, cls.obs(state))
            torque = jnp.reshape(torque, ())
            new_state, reward = cls.step(state, torque)
            return (new_state, total + reward), None

        (_, total), _ = jax.lax.scan(
            scan_step, (state0, jnp.asarray(0.0)), None, length=steps
        )
        return total


class PixelChase:
    """Procedural pixel-observation env for ConvNet-policy ES (stands in
    for the reference's Atari large-batch ES config — no ROMs needed, and
    the whole env renders/steps inside XLA).

    The agent (one blob) chases a target (another blob) on an H×W grid;
    observations are rendered single-channel images; actions are the four
    moves + stay; reward is negative distance (closing in scores higher).
    """

    H = 24
    W = 24
    obs_shape = (24, 24, 1)
    act_dim = 5
    max_steps = 60

    _MOVES = ((0, 0), (0, 1), (0, -1), (1, 0), (-1, 0))

    @classmethod
    def _render(cls, agent_yx, target_yx):
        import jax.numpy as jnp

        ys = jnp.arange(cls.H)[:, None]
        xs = jnp.arange(cls.W)[None, :]
        agent_img = jnp.exp(
            -((ys - agent_yx[0]) ** 2 + (xs - agent_yx[1]) ** 2) / 4.0
        )
        target_img = -jnp.exp(
            -((ys - target_yx[0]) ** 2 + (xs - target_yx[1]) ** 2) / 4.0
        )
        return (agent_img + target_img)[..., None]

    @classmethod
    def rollout(cls, act_fn, flat_params, key,
                max_steps: int | None = None):
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        k1, k2 = jax.random.split(key)
        agent0 = jax.random.uniform(
            k1, (2,), minval=2.0, maxval=cls.H - 3.0
        )
        target = jax.random.uniform(
            k2, (2,), minval=2.0, maxval=cls.H - 3.0
        )
        moves = jnp.asarray(cls._MOVES, dtype=jnp.float32)

        def scan_step(carry, _):
            agent, total = carry
            obs = cls._render(agent, target)
            action = act_fn(flat_params, obs)
            agent = jnp.clip(
                agent + moves[action], 0.0, float(cls.H - 1)
            )
            dist = jnp.sqrt(jnp.sum((agent - target) ** 2))
            reward = -dist / cls.H
            return (agent, total + reward), None

        (_, total), _ = jax.lax.scan(
            scan_step, (agent0, jnp.asarray(0.0)), None, length=steps
        )
        return total


def _angle_normalize(x):
    import jax.numpy as jnp

    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class ParamHillWalker:
    """Terrain-parameterized 1-D walker — the POET paper's co-evolution
    shape (the reference's gecco-2020 example evolves BipedalWalker
    terrains; this is that substrate as compiled XLA: the terrain IS the
    evolvable environment).

    A point mass drives along a height field
    ``h(x) = Σ aᵢ·sin(fᵢ·x)`` whose amplitude vector ``aᵢ`` is the
    environment's parameter vector. Observations are local terrain
    perception (velocity + slope at/ahead of the agent) — translation
    invariant, so agents generalize across terrains the way POET needs.
    Fitness is distance travelled; steeper evolved terrain = harder env.
    """

    obs_dim = 4
    act_dim = 3  # push back / coast / push forward
    max_steps = 200

    dt = 0.05
    friction = 0.5
    force_mag = 4.0
    gravity = 9.8

    #: fixed incommensurate bump frequencies; env params are amplitudes
    FREQS = (0.5, 0.9, 1.4, 2.1, 3.1, 4.3)
    DEFAULT = (0.0, 0.0, 0.0, 0.0, 0.0, 0.0)  # flat ground
    PARAM_LOW = (-1.2,) * 6
    PARAM_HIGH = (1.2,) * 6

    @classmethod
    def slope(cls, env_params, x):
        """dh/dx at position x (analytic — no finite differences)."""
        import jax.numpy as jnp

        freqs = jnp.asarray(cls.FREQS)
        amps = jnp.asarray(env_params)
        return jnp.sum(amps * freqs * jnp.cos(freqs * x))

    @classmethod
    def rollout_p(cls, act_fn, env_params, flat_params, key,
                  max_steps: int | None = None):
        """Distance travelled under a specific terrain; jittable and
        vmappable over (env_params, flat_params) pairs — same contract
        as ParamCartPole.rollout_p."""
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        x0 = 0.1 * jax.random.normal(key, ())
        v0 = jnp.asarray(0.0)

        def scan_step(carry, _):
            x, v = carry
            obs = jnp.stack([
                v,
                cls.slope(env_params, x),
                cls.slope(env_params, x + 0.5),
                cls.slope(env_params, x + 1.0),
            ])
            action = act_fn(flat_params, obs)
            force = (action.astype(jnp.float32) - 1.0) * cls.force_mag
            acc = force - cls.gravity * cls.slope(env_params, x) \
                - cls.friction * v
            v = v + cls.dt * acc
            x = x + cls.dt * v
            return (x, v), None

        (x, _v), _ = jax.lax.scan(
            scan_step, (x0, v0), None, length=steps
        )
        return x

    @classmethod
    def mutate(cls, env_params, key, scale: float = 0.15):
        """Perturb the terrain amplitudes within bounds (POET env
        mutation)."""
        import jax
        import jax.numpy as jnp

        low = jnp.asarray(cls.PARAM_LOW)
        high = jnp.asarray(cls.PARAM_HIGH)
        noise = jax.random.normal(key, (len(cls.FREQS),)) \
            * scale * (high - low)
        return jnp.clip(jnp.asarray(env_params) + noise, low, high)


def rollout_recurrent(env_cls, policy, flat_params, key,
                      max_steps: int | None = None):
    """Episode reward for a RECURRENT policy (``init_carry``/``act_step``
    interface, e.g. GRUPolicy) on a CARTPOLE-STYLE env: ``reset(key)``
    plus ``step(state, action) -> (state, terminated:bool)`` with
    survival (+1/step until termination) reward — CartPole and direct
    subclasses. Envs with shaped rewards (Pendulum) or parameterized
    steps (ParamCartPole.rollout_p) need their own recurrent variant.
    Same masked-scan loop as the stateless rollouts (shared
    ``_survival_scan``), with the policy's hidden state threaded through
    the carry — fully jittable and vmappable over (flat_params, key)."""
    steps = max_steps or env_cls.max_steps
    return _survival_scan(
        env_cls.step,
        lambda h, state: policy.act_step(flat_params, h, state),
        env_cls.reset(key), policy.init_carry(), steps,
    )

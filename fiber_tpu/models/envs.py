"""Pure-JAX environments: physics as jittable step functions, rollouts as
``lax.scan`` — the whole episode compiles into one XLA program with static
shapes (no Python in the loop), which is what lets a TPU evaluate whole
populations of policies in data-parallel lockstep.

CartPole matches the classic Gym CartPole-v1 dynamics (the north-star
OpenAI-ES workload, BASELINE.json configs); Pendulum is the continuous
control smoke env.
"""

from __future__ import annotations

from typing import Callable


def _scan_unroll() -> int:
    """FIBER_ROLLOUT_UNROLL trades compiled-code size for fewer loop
    iterations in every env rollout scan (read at trace time; TPU scans
    with tiny bodies often gain from 2-8). Sweepable without API churn:
    tune_es/bench runs set the env var."""
    import os

    try:
        return max(1, int(os.environ.get("FIBER_ROLLOUT_UNROLL", "1")))
    except ValueError:
        return 1


def _mutate_bounded(env_params, key, low, high, scale):
    """Shared POET env mutation: clip-bounded gaussian perturbation of
    the parameter vector (one implementation for every Param* env)."""
    import jax
    import jax.numpy as jnp

    low = jnp.asarray(low)
    high = jnp.asarray(high)
    noise = jax.random.normal(key, low.shape) * scale * (high - low)
    return jnp.clip(jnp.asarray(env_params) + noise, low, high)


def _survival_scan(step_fn, act_step_fn, state0, carry0, steps):
    """THE masked episode loop for survival-reward envs: +1 per step
    until termination, with static shapes (no early exit — finished
    episodes freeze their state and stop scoring). One implementation
    shared by every rollout variant so the masking/termination
    convention can't drift between them.

    ``act_step_fn(policy_carry, state) -> (policy_carry', action)``
    (stateless policies pass ``carry0=()``);
    ``step_fn(state, action) -> (state', terminated: bool)``.
    """
    import jax
    import jax.numpy as jnp

    def scan_step(carry, _):
        state, pc, done, total = carry
        new_pc, action = act_step_fn(pc, state)
        next_state, terminated = step_fn(state, action)
        reward = jnp.where(done, 0.0, 1.0)
        new_done = done | terminated
        # tree.map on BOTH freezes so pytree env states work the same
        # as pytree policy carries.
        keep_state = jax.tree.map(
            lambda old, new: jnp.where(done, old, new), state, next_state
        )
        keep_pc = jax.tree.map(
            lambda old, new: jnp.where(done, old, new), pc, new_pc
        )
        return (keep_state, keep_pc, new_done, total + reward), None

    (_, _, _, total), _ = jax.lax.scan(
        scan_step,
        (state0, carry0, jnp.asarray(False), jnp.asarray(0.0)),
        None, length=steps, unroll=_scan_unroll(),
    )
    return total


class CartPole:
    obs_dim = 4
    act_dim = 2
    max_steps = 500

    # physics constants (Gym CartPole-v1)
    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5          # half pole length
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 3.141592653589793 / 180.0
    x_threshold = 2.4

    @classmethod
    def reset(cls, key):
        import jax

        return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)

    @classmethod
    def step(cls, state, action):
        """One physics step. action in {0, 1}. Returns (state, terminated)."""
        import jax.numpy as jnp

        x, x_dot, theta, theta_dot = state
        force = jnp.where(action == 1, cls.force_mag, -cls.force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = cls.masscart + cls.masspole
        polemass_length = cls.masspole * cls.length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (cls.gravity * sintheta - costheta * temp) / (
            cls.length * (4.0 / 3.0 - cls.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + cls.tau * x_dot
        x_dot = x_dot + cls.tau * xacc
        theta = theta + cls.tau * theta_dot
        theta_dot = theta_dot + cls.tau * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = (
            (jnp.abs(x) > cls.x_threshold)
            | (jnp.abs(theta) > cls.theta_threshold)
        )
        return new_state, terminated

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        """Total episode reward for a deterministic policy; fully jittable.

        ``act_fn(flat_params, obs) -> action``. Termination is handled by
        masking inside the scan (static shapes, no early exit).
        """
        steps = max_steps or cls.max_steps
        return _survival_scan(
            cls.step,
            lambda carry, state: (carry, act_fn(flat_params, state)),
            cls.reset(key), (), steps,
        )


class ParamCartPole(CartPole):
    """CartPole with mutable physics — the substrate for POET-style
    env/agent co-evolution (the reference's POET example evolves
    BipedalWalker terrains; here the evolvable environment parameters are
    the physics vector [gravity, pole_half_length, force_mag, masspole],
    harder configs = heavier/longer pole, weaker cart).

    ``env_params`` rides through rollouts as a jax array so a whole
    population of (env, agent) pairs can evaluate in one SPMD program.
    """

    #: default physics vector (matches CartPole-v1)
    DEFAULT = (9.8, 0.5, 10.0, 0.1)
    PARAM_LOW = (4.0, 0.25, 4.0, 0.05)
    PARAM_HIGH = (19.0, 1.5, 14.0, 0.6)

    @classmethod
    def step_p(cls, env_params, state, action):
        import jax.numpy as jnp

        gravity, length, force_mag, masspole = (
            env_params[0], env_params[1], env_params[2], env_params[3]
        )
        x, x_dot, theta, theta_dot = state
        force = jnp.where(action == 1, force_mag, -force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = cls.masscart + masspole
        polemass_length = masspole * length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + cls.tau * x_dot
        x_dot = x_dot + cls.tau * xacc
        theta = theta + cls.tau * theta_dot
        theta_dot = theta_dot + cls.tau * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = (
            (jnp.abs(x) > cls.x_threshold)
            | (jnp.abs(theta) > cls.theta_threshold)
        )
        return new_state, terminated

    @classmethod
    def rollout_p(cls, act_fn, env_params, flat_params, key,
                  max_steps: int | None = None):
        """Episode reward under a specific physics vector; jittable and
        vmappable over (env_params, flat_params) pairs."""
        steps = max_steps or cls.max_steps
        return _survival_scan(
            lambda state, action: cls.step_p(env_params, state, action),
            lambda carry, state: (carry, act_fn(flat_params, state)),
            cls.reset(key), (), steps,
        )

    @classmethod
    def mutate(cls, env_params, key, scale: float = 0.15):
        """Perturb the physics vector within bounds (POET env mutation)."""
        return _mutate_bounded(env_params, key, cls.PARAM_LOW,
                               cls.PARAM_HIGH, scale)


class Pendulum:
    obs_dim = 3
    act_dim = 1
    max_steps = 200

    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    length = 1.0

    @classmethod
    def reset(cls, key):
        import jax
        import jax.numpy as jnp

        hi = jnp.asarray([3.141592653589793, 1.0])
        thetadot = jax.random.uniform(key, (2,), minval=-hi, maxval=hi)
        return thetadot  # (theta, theta_dot)

    @classmethod
    def obs(cls, state):
        import jax.numpy as jnp

        theta, theta_dot = state
        return jnp.stack([jnp.cos(theta), jnp.sin(theta), theta_dot])

    @classmethod
    def step(cls, state, torque):
        import jax.numpy as jnp

        theta, theta_dot = state
        u = jnp.clip(torque, -cls.max_torque, cls.max_torque)
        cost = (
            _angle_normalize(theta) ** 2
            + 0.1 * theta_dot**2
            + 0.001 * u**2
        )
        new_theta_dot = theta_dot + (
            3 * cls.g / (2 * cls.length) * jnp.sin(theta)
            + 3.0 / (cls.m * cls.length**2) * u
        ) * cls.dt
        new_theta_dot = jnp.clip(new_theta_dot, -cls.max_speed, cls.max_speed)
        new_theta = theta + new_theta_dot * cls.dt
        return jnp.stack([new_theta, new_theta_dot]), -cost

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        state0 = cls.reset(key)

        def scan_step(carry, _):
            state, total = carry
            torque = act_fn(flat_params, cls.obs(state))
            torque = jnp.reshape(torque, ())
            new_state, reward = cls.step(state, torque)
            return (new_state, total + reward), None

        (_, total), _ = jax.lax.scan(
            scan_step, (state0, jnp.asarray(0.0)), None, length=steps,
            unroll=_scan_unroll()
        )
        return total


class PixelChase:
    """Procedural pixel-observation env for ConvNet-policy ES (stands in
    for the reference's Atari large-batch ES config — no ROMs needed, and
    the whole env renders/steps inside XLA).

    The agent (one blob) chases a target (another blob) on an H×W grid;
    observations are rendered single-channel images; actions are the four
    moves + stay; reward is negative distance (closing in scores higher).
    """

    H = 24
    W = 24
    obs_shape = (24, 24, 1)
    act_dim = 5
    max_steps = 60

    _MOVES = ((0, 0), (0, 1), (0, -1), (1, 0), (-1, 0))

    @classmethod
    def _render(cls, agent_yx, target_yx):
        import jax.numpy as jnp

        ys = jnp.arange(cls.H)[:, None]
        xs = jnp.arange(cls.W)[None, :]
        agent_img = jnp.exp(
            -((ys - agent_yx[0]) ** 2 + (xs - agent_yx[1]) ** 2) / 4.0
        )
        target_img = -jnp.exp(
            -((ys - target_yx[0]) ** 2 + (xs - target_yx[1]) ** 2) / 4.0
        )
        return (agent_img + target_img)[..., None]

    @classmethod
    def rollout(cls, act_fn, flat_params, key,
                max_steps: int | None = None):
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        k1, k2 = jax.random.split(key)
        agent0 = jax.random.uniform(
            k1, (2,), minval=2.0, maxval=cls.H - 3.0
        )
        target = jax.random.uniform(
            k2, (2,), minval=2.0, maxval=cls.H - 3.0
        )
        moves = jnp.asarray(cls._MOVES, dtype=jnp.float32)

        def scan_step(carry, _):
            agent, total = carry
            obs = cls._render(agent, target)
            action = act_fn(flat_params, obs)
            agent = jnp.clip(
                agent + moves[action], 0.0, float(cls.H - 1)
            )
            dist = jnp.sqrt(jnp.sum((agent - target) ** 2))
            reward = -dist / cls.H
            return (agent, total + reward), None

        (_, total), _ = jax.lax.scan(
            scan_step, (agent0, jnp.asarray(0.0)), None, length=steps,
            unroll=_scan_unroll()
        )
        return total


class DeceptiveMaze:
    """Deceptive point maze — the novelty-search lineage's canonical
    domain (NS-ES/NSR-ES were demonstrated on mazes where the fitness
    gradient points into a wall, so reaching the goal requires first
    moving AWAY from it).

    A point agent starts at the origin; the goal sits directly above,
    behind a wall spanning ``|x| <= WALL_HALF`` at ``y = WALL_Y``.
    Greedy distance-minimization presses into the middle of the wall;
    the only way through is around either end. Observations are the
    position and the goal offset; actions are a continuous velocity
    (``policy.apply`` output, tanh-squashed). ``rollout_xy`` returns
    the final position — callers derive fitness (negative goal
    distance) and the behavior characterization (the position itself,
    the paper's BC) from it.
    """

    obs_dim = 4
    act_dim = 2  # (vx, vy), tanh-squashed continuous
    max_steps = 64

    GOAL = (0.0, 2.0)
    SPEED = 0.15
    WALL_Y = 1.0
    WALL_HALF = 1.0

    @classmethod
    def rollout_xy(cls, apply_fn, flat_params, key,
                   max_steps: int | None = None):
        """Final (x, y) after ``max_steps`` of policy-driven motion;
        jittable and vmappable."""
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        pos0 = 0.05 * jax.random.normal(key, (2,))
        gx, gy = cls.GOAL

        def scan_step(pos, _):
            obs = jnp.stack([pos[0], pos[1], gx - pos[0], gy - pos[1]])
            v = jnp.tanh(apply_fn(flat_params, obs)) * cls.SPEED
            new = pos + v
            # The wall blocks any step whose path crosses WALL_Y inside
            # |x| <= WALL_HALF. The test point is the x where the
            # segment intersects the wall plane (NOT the endpoint x —
            # that would let diagonal steps cut the corner by up to
            # SPEED). Park blocked steps just on the starting side.
            dy = new[1] - pos[1]
            t = jnp.where(jnp.abs(dy) > 1e-12,
                          (cls.WALL_Y - pos[1]) / jnp.where(
                              jnp.abs(dy) > 1e-12, dy, 1.0),
                          2.0)  # parallel to wall: no crossing (t>1)
            x_cross = pos[0] + t * (new[0] - pos[0])
            crosses = (t >= 0.0) & (t <= 1.0) \
                & (jnp.abs(x_cross) <= cls.WALL_HALF)
            stop_y = jnp.where(pos[1] < cls.WALL_Y,
                               cls.WALL_Y - 1e-3, cls.WALL_Y + 1e-3)
            # Blocked steps park at the intersection point (x_cross,
            # stop_y), not (new_x, stop_y): keeping the full lateral
            # displacement would re-open the corner cut over two steps
            # (advisor, round 2) — strict wall physics is what makes the
            # maze deceptive for plain ES.
            new_x = jnp.where(crosses, x_cross, new[0])
            new_y = jnp.where(crosses, stop_y, new[1])
            return jnp.stack([new_x, new_y]), None

        pos, _ = jax.lax.scan(
            scan_step, pos0, None, length=steps, unroll=_scan_unroll()
        )
        return pos

    @classmethod
    def rollout(cls, apply_fn, flat_params, key,
                max_steps: int | None = None):
        """Fitness-only rollout: negative final distance to the goal."""
        import jax.numpy as jnp

        pos = cls.rollout_xy(apply_fn, flat_params, key, max_steps)
        goal = jnp.asarray(cls.GOAL)
        return -jnp.sqrt(jnp.sum((pos - goal) ** 2))


def _angle_normalize(x):
    import jax.numpy as jnp

    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi


class ParamHillWalker:
    """Terrain-parameterized 1-D walker — the POET paper's co-evolution
    shape (the reference's gecco-2020 example evolves BipedalWalker
    terrains; this is that substrate as compiled XLA: the terrain IS the
    evolvable environment).

    A point mass drives along a height field
    ``h(x) = Σ aᵢ·sin(fᵢ·x)`` whose amplitude vector ``aᵢ`` is the
    environment's parameter vector. Observations are local terrain
    perception (velocity + slope at/ahead of the agent) — translation
    invariant, so agents generalize across terrains the way POET needs.
    Fitness is distance travelled; steeper evolved terrain = harder env.
    """

    obs_dim = 4
    act_dim = 3  # push back / coast / push forward
    max_steps = 200

    dt = 0.05
    friction = 0.5
    force_mag = 4.0
    gravity = 9.8

    #: fixed incommensurate bump frequencies; env params are amplitudes
    FREQS = (0.5, 0.9, 1.4, 2.1, 3.1, 4.3)
    DEFAULT = (0.0, 0.0, 0.0, 0.0, 0.0, 0.0)  # flat ground
    PARAM_LOW = (-1.2,) * 6
    PARAM_HIGH = (1.2,) * 6

    @classmethod
    def slope(cls, env_params, x):
        """dh/dx at position x (analytic — no finite differences)."""
        import jax.numpy as jnp

        freqs = jnp.asarray(cls.FREQS)
        amps = jnp.asarray(env_params)
        return jnp.sum(amps * freqs * jnp.cos(freqs * x))

    @classmethod
    def rollout_p(cls, act_fn, env_params, flat_params, key,
                  max_steps: int | None = None):
        """Distance travelled under a specific terrain; jittable and
        vmappable over (env_params, flat_params) pairs — same contract
        as ParamCartPole.rollout_p."""
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        x0 = 0.1 * jax.random.normal(key, ())
        v0 = jnp.asarray(0.0)

        def scan_step(carry, _):
            x, v = carry
            obs = jnp.stack([
                v,
                cls.slope(env_params, x),
                cls.slope(env_params, x + 0.5),
                cls.slope(env_params, x + 1.0),
            ])
            action = act_fn(flat_params, obs)
            force = (action.astype(jnp.float32) - 1.0) * cls.force_mag
            acc = force - cls.gravity * cls.slope(env_params, x) \
                - cls.friction * v
            v = v + cls.dt * acc
            x = x + cls.dt * v
            return (x, v), None

        (x, _v), _ = jax.lax.scan(
            scan_step, (x0, v0), None, length=steps,
            unroll=_scan_unroll()
        )
        return x

    @classmethod
    def mutate(cls, env_params, key, scale: float = 0.15):
        """Perturb the terrain amplitudes within bounds (POET env
        mutation)."""
        return _mutate_bounded(env_params, key, cls.PARAM_LOW,
                               cls.PARAM_HIGH, scale)


class ParamBipedWalker:
    """Planar biped on a parameterized obstacle course — the published
    POET domain shape (modified BipedalWalker-Hardcore: the reference's
    gecco-2020 workload evolves terrain roughness / stump / gap
    parameters) rebuilt as compiled XLA.

    Simplified articulated model that keeps the domain's control
    problem: a hull (x, y, vx, vy, phi, omega) rides two massless
    telescoping legs (world-frame hip angles theta_i, lengths L_i) with
    spring-damper ground contact; contact forces torque the hull, so the
    agent must coordinate both legs to move forward without toppling.
    Actions are bang-bang: 16 discrete combos of (hip1, hip2, dL1, dL2)
    rate signs — argmax-policy compatible (same ``policy.act`` contract
    POET drives, fiber_tpu/ops/poet.py:78).

    Env params = (4 roughness amplitudes, stump height, gap depth): the
    POET paper's difficulty axes. All zeros = flat ground. Fitness is
    forward distance; episodes freeze on termination (static shapes).
    """

    obs_dim = 14
    act_dim = 16
    max_steps = 400

    dt = 0.025
    gravity = 9.8
    mass = 1.0
    inertia = 0.5
    hip_rate = 3.0       # rad/s
    len_rate = 1.5       # m/s
    theta_lim = 0.9
    len_low, len_high = 0.5, 1.2
    k_contact = 120.0
    d_contact = 6.0
    k_friction = 4.0
    omega_damp = 1.0

    FREQS = (0.4, 0.8, 1.5, 2.7)
    DEFAULT = (0.0,) * 6
    PARAM_LOW = (0.0,) * 6
    PARAM_HIGH = (0.4, 0.4, 0.3, 0.2, 0.5, 0.6)

    @classmethod
    def height(cls, env_params, x):
        """Terrain height: roughness + periodic stumps - periodic gaps.
        Analytic (jittable); obstacles start ~3m from spawn."""
        import jax.numpy as jnp

        p = jnp.asarray(env_params)
        freqs = jnp.asarray(cls.FREQS)
        rough = jnp.sum(p[:4] * jnp.sin(freqs * x))
        stump = p[4] * jnp.exp(-jnp.sin(0.5 * (x - 3.0)) ** 2 / 0.01)
        gap = p[5] * jnp.exp(-jnp.sin(0.35 * (x - 5.0)) ** 2 / 0.02)
        return rough + stump - gap

    @classmethod
    def _slope(cls, env_params, x):
        return (cls.height(env_params, x + 0.1)
                - cls.height(env_params, x - 0.1)) / 0.2

    @classmethod
    def rollout_p(cls, act_fn, env_params, flat_params, key,
                  max_steps: int | None = None):
        """Forward distance on a specific course; jittable/vmappable —
        same contract as ParamCartPole/ParamHillWalker.rollout_p."""
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        y0 = cls.height(env_params, 0.0) + 1.0
        jitter = 0.02 * jax.random.normal(key, (2,))

        # state: x, y, vx, vy, phi, omega, th1, th2, L1, L2
        state0 = jnp.asarray([
            0.0, y0, 0.0, 0.0, jitter[0], 0.0,
            0.15 + jitter[1], -0.15, 1.0, 1.0,
        ])

        def leg_forces(x, y, vx, vy, th, L, dth, dL, env):
            fx_pos = x + L * jnp.sin(th)
            fy_pos = y - L * jnp.cos(th)
            vfx = vx + dL * jnp.sin(th) + L * jnp.cos(th) * dth
            vfy = vy - dL * jnp.cos(th) + L * jnp.sin(th) * dth
            pen = cls.height(env, fx_pos) - fy_pos
            contact = pen > 0.0
            normal = jnp.where(
                contact,
                jnp.maximum(cls.k_contact * pen - cls.d_contact * vfy,
                            0.0),
                0.0)
            friction = jnp.where(
                contact,
                jnp.clip(-cls.k_friction * vfx, -0.8 * normal,
                         0.8 * normal),
                0.0)
            rx, ry = fx_pos - x, fy_pos - y
            torque = rx * normal - ry * friction
            return friction, normal, torque, contact

        def scan_step(carry, _):
            state, done, best_x = carry
            x, y, vx, vy, phi, om, th1, th2, L1, L2 = state

            obs = jnp.stack([
                vx / 3.0, vy / 3.0, om, jnp.sin(phi), jnp.cos(phi),
                th1, th2, L1, L2,
                # previous-step contact proxies: current penetration
                jnp.asarray(
                    cls.height(env_params, x + L1 * jnp.sin(th1))
                    >= y - L1 * jnp.cos(th1), jnp.float32),
                jnp.asarray(
                    cls.height(env_params, x + L2 * jnp.sin(th2))
                    >= y - L2 * jnp.cos(th2), jnp.float32),
                cls._slope(env_params, x + 0.3),
                cls._slope(env_params, x + 0.8),
                y - cls.height(env_params, x),
            ])
            action = act_fn(flat_params, obs)
            bit = lambda k: 2.0 * jnp.asarray(
                (action >> k) & 1, jnp.float32) - 1.0
            dth1 = bit(3) * cls.hip_rate
            dth2 = bit(2) * cls.hip_rate
            dL1 = bit(1) * cls.len_rate
            dL2 = bit(0) * cls.len_rate

            f1x, f1y, t1, _c1 = leg_forces(x, y, vx, vy, th1, L1,
                                           dth1, dL1, env_params)
            f2x, f2y, t2, _c2 = leg_forces(x, y, vx, vy, th2, L2,
                                           dth2, dL2, env_params)

            ax = (f1x + f2x) / cls.mass
            ay = (f1y + f2y) / cls.mass - cls.gravity
            alpha = (t1 + t2) / cls.inertia - cls.omega_damp * om

            nvx = vx + cls.dt * ax
            nvy = vy + cls.dt * ay
            nom = om + cls.dt * alpha
            nx = x + cls.dt * nvx
            ny = y + cls.dt * nvy
            nphi = phi + cls.dt * nom
            nth1 = jnp.clip(th1 + cls.dt * dth1, -cls.theta_lim,
                            cls.theta_lim)
            nth2 = jnp.clip(th2 + cls.dt * dth2, -cls.theta_lim,
                            cls.theta_lim)
            nL1 = jnp.clip(L1 + cls.dt * dL1, cls.len_low, cls.len_high)
            nL2 = jnp.clip(L2 + cls.dt * dL2, cls.len_low, cls.len_high)

            new_state = jnp.stack([
                nx, ny, nvx, nvy, nphi, nom, nth1, nth2, nL1, nL2,
            ])
            fell = ((ny - cls.height(env_params, nx) < 0.3)
                    | (jnp.abs(nphi) > 1.2))
            keep = jnp.where(done, state, new_state)
            new_best = jnp.where(done, best_x, jnp.maximum(best_x, nx))
            return (keep, done | fell, new_best), None

        (_, _, best_x), _ = jax.lax.scan(
            scan_step, (state0, jnp.asarray(False), jnp.asarray(0.0)),
            None, length=steps, unroll=_scan_unroll(),
        )
        return best_x

    @classmethod
    def mutate(cls, env_params, key, scale: float = 0.15):
        """Perturb the course parameters within bounds (POET env
        mutation; difficulty grows from flat ground)."""
        return _mutate_bounded(env_params, key, cls.PARAM_LOW,
                               cls.PARAM_HIGH, scale)


def rollout_recurrent(env_cls, policy, flat_params, key,
                      max_steps: int | None = None):
    """Episode reward for a RECURRENT policy (``init_carry``/``act_step``
    interface, e.g. GRUPolicy) on a CARTPOLE-STYLE env: ``reset(key)``
    plus ``step(state, action) -> (state, terminated:bool)`` with
    survival (+1/step until termination) reward — CartPole and direct
    subclasses. Envs with shaped rewards (Pendulum) or parameterized
    steps (ParamCartPole.rollout_p) need their own recurrent variant.
    Same masked-scan loop as the stateless rollouts (shared
    ``_survival_scan``), with the policy's hidden state threaded through
    the carry — fully jittable and vmappable over (flat_params, key)."""
    steps = max_steps or env_cls.max_steps
    return _survival_scan(
        env_cls.step,
        lambda h, state: policy.act_step(flat_params, h, state),
        env_cls.reset(key), policy.init_carry(), steps,
    )

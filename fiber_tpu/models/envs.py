"""Pure-JAX environments: physics as jittable step functions, rollouts as
``lax.scan`` — the whole episode compiles into one XLA program with static
shapes (no Python in the loop), which is what lets a TPU evaluate whole
populations of policies in data-parallel lockstep.

CartPole matches the classic Gym CartPole-v1 dynamics (the north-star
OpenAI-ES workload, BASELINE.json configs); Pendulum is the continuous
control smoke env.
"""

from __future__ import annotations

from typing import Callable


class CartPole:
    obs_dim = 4
    act_dim = 2
    max_steps = 500

    # physics constants (Gym CartPole-v1)
    gravity = 9.8
    masscart = 1.0
    masspole = 0.1
    length = 0.5          # half pole length
    force_mag = 10.0
    tau = 0.02
    theta_threshold = 12 * 3.141592653589793 / 180.0
    x_threshold = 2.4

    @classmethod
    def reset(cls, key):
        import jax

        return jax.random.uniform(key, (4,), minval=-0.05, maxval=0.05)

    @classmethod
    def step(cls, state, action):
        """One physics step. action in {0, 1}. Returns (state, terminated)."""
        import jax.numpy as jnp

        x, x_dot, theta, theta_dot = state
        force = jnp.where(action == 1, cls.force_mag, -cls.force_mag)
        costheta = jnp.cos(theta)
        sintheta = jnp.sin(theta)
        total_mass = cls.masscart + cls.masspole
        polemass_length = cls.masspole * cls.length

        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (cls.gravity * sintheta - costheta * temp) / (
            cls.length * (4.0 / 3.0 - cls.masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass

        x = x + cls.tau * x_dot
        x_dot = x_dot + cls.tau * xacc
        theta = theta + cls.tau * theta_dot
        theta_dot = theta_dot + cls.tau * thetaacc
        new_state = jnp.stack([x, x_dot, theta, theta_dot])
        terminated = (
            (jnp.abs(x) > cls.x_threshold)
            | (jnp.abs(theta) > cls.theta_threshold)
        )
        return new_state, terminated

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        """Total episode reward for a deterministic policy; fully jittable.

        ``act_fn(flat_params, obs) -> action``. Termination is handled by
        masking inside the scan (static shapes, no early exit).
        """
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        state0 = cls.reset(key)

        def scan_step(carry, _):
            state, done, total = carry
            action = act_fn(flat_params, state)
            next_state, terminated = cls.step(state, action)
            reward = jnp.where(done, 0.0, 1.0)
            new_done = done | terminated
            new_state = jnp.where(done, state, next_state)
            return (new_state, new_done, total + reward), None

        (final_state, done, total), _ = jax.lax.scan(
            scan_step, (state0, jnp.asarray(False), jnp.asarray(0.0)),
            None, length=steps,
        )
        return total


class Pendulum:
    obs_dim = 3
    act_dim = 1
    max_steps = 200

    max_speed = 8.0
    max_torque = 2.0
    dt = 0.05
    g = 10.0
    m = 1.0
    length = 1.0

    @classmethod
    def reset(cls, key):
        import jax
        import jax.numpy as jnp

        hi = jnp.asarray([3.141592653589793, 1.0])
        thetadot = jax.random.uniform(key, (2,), minval=-hi, maxval=hi)
        return thetadot  # (theta, theta_dot)

    @classmethod
    def obs(cls, state):
        import jax.numpy as jnp

        theta, theta_dot = state
        return jnp.stack([jnp.cos(theta), jnp.sin(theta), theta_dot])

    @classmethod
    def step(cls, state, torque):
        import jax.numpy as jnp

        theta, theta_dot = state
        u = jnp.clip(torque, -cls.max_torque, cls.max_torque)
        cost = (
            _angle_normalize(theta) ** 2
            + 0.1 * theta_dot**2
            + 0.001 * u**2
        )
        new_theta_dot = theta_dot + (
            3 * cls.g / (2 * cls.length) * jnp.sin(theta)
            + 3.0 / (cls.m * cls.length**2) * u
        ) * cls.dt
        new_theta_dot = jnp.clip(new_theta_dot, -cls.max_speed, cls.max_speed)
        new_theta = theta + new_theta_dot * cls.dt
        return jnp.stack([new_theta, new_theta_dot]), -cost

    @classmethod
    def rollout(cls, act_fn: Callable, flat_params, key,
                max_steps: int | None = None):
        import jax
        import jax.numpy as jnp

        steps = max_steps or cls.max_steps
        state0 = cls.reset(key)

        def scan_step(carry, _):
            state, total = carry
            torque = act_fn(flat_params, cls.obs(state))
            torque = jnp.reshape(torque, ())
            new_state, reward = cls.step(state, torque)
            return (new_state, total + reward), None

        (_, total), _ = jax.lax.scan(
            scan_step, (state0, jnp.asarray(0.0)), None, length=steps
        )
        return total


def _angle_normalize(x):
    import jax.numpy as jnp

    return ((x + jnp.pi) % (2 * jnp.pi)) - jnp.pi

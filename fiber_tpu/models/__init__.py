"""Model zoo for the framework's population-based workloads: policy
networks and pure-JAX environments whose rollouts compile end-to-end."""

from fiber_tpu.models.policies import (  # noqa: F401
    ConvPolicy,
    GRUPolicy,
    MLPPolicy,
)
from fiber_tpu.models.transformer import (  # noqa: F401
    TinyLM,
    make_train_step,
)
from fiber_tpu.models.envs import (  # noqa: F401
    CartPole,
    DeceptiveMaze,
    ParamBipedWalker,
    ParamCartPole,
    ParamHillWalker,
    Pendulum,
    PixelChase,
    rollout_recurrent,
)

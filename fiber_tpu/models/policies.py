"""Policy networks for ES/POET workloads, exposed in the flat-vector form
evolution strategies need (perturbations are dense vectors living on the
MXU-friendly path: one (pop, dim) matmul-shaped tensor, not a pytree zoo).

Reference parity: the reference's ES examples use small torch MLPs
(examples/gecco-2020); here policies are pure JAX with a
``ravel``/``unravel`` pair so a whole population of parameter vectors is a
single 2-D array.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple


def _compute_dtype(explicit):
    """Policy matmul precision: explicit kwarg, else the
    FIBER_POLICY_DTYPE env var (trace-time, so hardware sweeps need no
    API churn), else float32. bfloat16 halves policy HBM/MXU cost on
    TPU; params/logits stay float32 at the boundary."""
    import os

    import jax.numpy as jnp

    name = explicit or os.environ.get("FIBER_POLICY_DTYPE", "")
    if not name:
        return None
    return jnp.dtype(name)


class MLPPolicy:
    """Tanh MLP: obs -> hidden* -> logits, as flat parameter vectors.

    ``compute_dtype`` (or env ``FIBER_POLICY_DTYPE``) runs the matmuls
    in reduced precision (e.g. "bfloat16") while params and outputs
    stay float32."""

    def __init__(self, obs_dim: int, act_dim: int,
                 hidden: Sequence[int] = (32, 32),
                 compute_dtype: str | None = None) -> None:
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.compute_dtype = compute_dtype
        self.sizes = (obs_dim, *hidden, act_dim)
        self.dim = sum(
            self.sizes[i] * self.sizes[i + 1] + self.sizes[i + 1]
            for i in range(len(self.sizes) - 1)
        )

    def init(self, key):
        """Flat parameter vector (dim,)."""
        import jax
        import jax.numpy as jnp

        parts = []
        for i in range(len(self.sizes) - 1):
            key, wk = jax.random.split(key)
            fan_in = self.sizes[i]
            w = jax.random.normal(
                wk, (self.sizes[i], self.sizes[i + 1])
            ) / jnp.sqrt(fan_in)
            b = jnp.zeros((self.sizes[i + 1],))
            parts.append(w.ravel())
            parts.append(b)
        return jnp.concatenate(parts)

    def apply(self, flat_params, obs):
        """Logits for one observation; jittable / vmappable."""
        import jax.numpy as jnp

        dt = _compute_dtype(self.compute_dtype)
        x = obs
        if dt is not None:
            x = x.astype(dt)
            flat_params = flat_params.astype(dt)
        offset = 0
        n_layers = len(self.sizes) - 1
        for i in range(n_layers):
            n_in, n_out = self.sizes[i], self.sizes[i + 1]
            w = flat_params[offset:offset + n_in * n_out].reshape(n_in, n_out)
            offset += n_in * n_out
            b = flat_params[offset:offset + n_out]
            offset += n_out
            x = x @ w + b
            if i < n_layers - 1:
                x = jnp.tanh(x)
        return x.astype(jnp.float32)

    def act(self, flat_params, obs):
        """Deterministic discrete action."""
        import jax.numpy as jnp

        return jnp.argmax(self.apply(flat_params, obs))


class ConvPolicy:
    """Small conv policy for image observations (Atari-style ES), kept in
    NHWC with bf16-friendly channel sizes so convs tile onto the MXU."""

    def __init__(self, obs_shape: Tuple[int, int, int], act_dim: int,
                 channels: Sequence[int] = (16, 32),
                 hidden: int = 128,
                 compute_dtype: str | None = None) -> None:
        self.obs_shape = obs_shape  # (H, W, C)
        self.act_dim = act_dim
        self.channels = tuple(channels)
        self.hidden = hidden
        self.compute_dtype = compute_dtype
        h, w, c = obs_shape
        self._specs = []
        in_c = c
        for out_c in self.channels:
            self._specs.append(("conv", (3, 3, in_c, out_c)))
            in_c = out_c
            h, w = (h + 1) // 2, (w + 1) // 2  # stride-2 convs
        self._flat_len = h * w * in_c
        self._specs.append(("dense", (self._flat_len, hidden)))
        self._specs.append(("dense", (hidden, act_dim)))
        self.dim = sum(
            int(__import__("numpy").prod(shape)) + shape[-1]
            for _, shape in self._specs
        )

    def init(self, key):
        import jax
        import jax.numpy as jnp
        import numpy as np

        parts = []
        for kind, shape in self._specs:
            key, wk = jax.random.split(key)
            fan_in = int(np.prod(shape[:-1]))
            w = jax.random.normal(wk, shape) / jnp.sqrt(fan_in)
            parts.append(w.ravel())
            parts.append(jnp.zeros((shape[-1],)))
        return jnp.concatenate(parts)

    def apply(self, flat_params, obs):
        import jax
        import jax.numpy as jnp
        import numpy as np

        dt = _compute_dtype(self.compute_dtype)
        x = obs[None]  # NHWC with N=1
        if dt is not None:
            x = x.astype(dt)
            flat_params = flat_params.astype(dt)
        offset = 0
        n = len(self._specs)
        for i, (kind, shape) in enumerate(self._specs):
            count = int(np.prod(shape))
            w = flat_params[offset:offset + count].reshape(shape)
            offset += count
            b = flat_params[offset:offset + shape[-1]]
            offset += shape[-1]
            if kind == "conv":
                x = jax.lax.conv_general_dilated(
                    x, w, window_strides=(2, 2), padding="SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                x = jnp.tanh(x + b)
            else:
                if x.ndim > 2:
                    x = x.reshape(x.shape[0], -1)
                x = x @ w + b
                if i < n - 1:
                    x = jnp.tanh(x)
        return x[0].astype(jnp.float32)

    def act(self, flat_params, obs):
        import jax.numpy as jnp

        return jnp.argmax(self.apply(flat_params, obs))


class GRUPolicy:
    """Single-layer GRU with a linear readout, as flat parameter vectors —
    the recurrent model family for partially-observable ES tasks (the
    reference's ES examples are feed-forward only; memory policies are
    the standard extension for masked/occluded observations).

    Contract: ``init_carry()`` gives the zero hidden state;
    ``act_step(flat_params, carry, obs) -> (carry', action)`` advances
    one step. Use ``fiber_tpu.models.rollout_recurrent`` to evaluate on
    any env with the reset/step interface; everything stays jittable and
    vmappable (a population of GRUs is one (pop, dim) tensor, same as
    the MLP path)."""

    def __init__(self, obs_dim: int, act_dim: int,
                 hidden: int = 32) -> None:
        self.obs_dim = obs_dim
        self.act_dim = act_dim
        self.hidden = hidden
        # 3 gates x (W: obs->h, U: h->h, b) + readout (h->act, b)
        self.dim = (
            3 * (obs_dim * hidden + hidden * hidden + hidden)
            + hidden * act_dim + act_dim
        )

    def init(self, key):
        import jax
        import jax.numpy as jnp

        o, h, a = self.obs_dim, self.hidden, self.act_dim
        parts = []
        for fan_in, shape in (
            (o, (o, h)), (h, (h, h)), (None, (h,)),   # z gate
            (o, (o, h)), (h, (h, h)), (None, (h,)),   # r gate
            (o, (o, h)), (h, (h, h)), (None, (h,)),   # candidate
            (h, (h, a)), (None, (a,)),                # readout
        ):
            if fan_in is None:
                parts.append(jnp.zeros(shape))
            else:
                key, wk = jax.random.split(key)
                parts.append(
                    (jax.random.normal(wk, shape) / jnp.sqrt(fan_in)).ravel()
                )
        return jnp.concatenate(parts)

    def init_carry(self):
        import jax.numpy as jnp

        return jnp.zeros((self.hidden,))

    def _unpack(self, flat):
        o, h, a = self.obs_dim, self.hidden, self.act_dim
        shapes = [(o, h), (h, h), (h,)] * 3 + [(h, a), (a,)]
        out, offset = [], 0
        for shape in shapes:
            n = 1
            for s in shape:
                n *= s
            out.append(flat[offset:offset + n].reshape(shape))
            offset += n
        return out

    def step(self, flat_params, carry, obs):
        """(carry', logits) for one step; jittable/vmappable."""
        import jax

        (wz, uz, bz, wr, ur, br, wh, uh, bh, wo, bo) = \
            self._unpack(flat_params)
        z = jax.nn.sigmoid(obs @ wz + carry @ uz + bz)
        r = jax.nn.sigmoid(obs @ wr + carry @ ur + br)
        import jax.numpy as jnp

        cand = jnp.tanh(obs @ wh + (r * carry) @ uh + bh)
        new_carry = (1.0 - z) * carry + z * cand
        return new_carry, new_carry @ wo + bo

    def act_step(self, flat_params, carry, obs):
        import jax.numpy as jnp

        new_carry, logits = self.step(flat_params, carry, obs)
        return new_carry, jnp.argmax(logits)
